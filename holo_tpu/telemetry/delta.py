"""Shared-delta telemetry fan-out (ISSUE 11): serve thousands of gNMI
subscribers at O(1) per-tick render cost.

Before this module every gNMI SAMPLE/ON_CHANGE subscriber independently
walked and diffed the state subtree on its own timer
(``gnmi_server._SubSampler``), so per-tick cost grew linearly with
subscriber count.  The :class:`FanoutEngine` applies the same
incremental-dataflow framing that made SPF cheap (DeltaPath): compute
ONE change-set per coalesced tick epoch, render each changed leaf once,
and fan the shared rendered notification out to every due subscriber
through the existing bounded queues.

Epoch / versioning contract
---------------------------
- The engine keeps one leaf store ``{path -> value}`` plus a per-leaf
  ``last-changed epoch``.  A tick that observes any leaf change
  advances the monotonic epoch id by one; an unchanged tick keeps it.
- Subscriptions become *epoch cursors* grouped into **interval
  buckets**: subscribers sharing (path, mode, sample interval,
  heartbeat, suppress) share one bucket, one cursor, and one rendered
  notification per fire — per-tick render cost is O(distinct buckets),
  never O(subscribers).
- suppress-redundant is an epoch comparison (``changed-epoch >
  cursor``), heartbeat is a render-cache hit keyed on the current
  epoch: neither re-walks the tree.  Suppression is therefore
  *epoch-granular*: a leaf that changed and reverted (A->B->A) across
  intermediate epochs between a slow bucket's fires is resent with its
  (correct, current) value where the legacy value diff would have
  stayed silent — gNMI suppress_redundant is best-effort, and a bucket
  firing at every epoch (the bench identity arm) is provably
  value-exact.
- The registry's write-time leaf stamps
  (:func:`holo_tpu.telemetry.registry.write_stamp`) short-circuit idle
  ticks entirely: when every bucket sits under the registry-backed
  ``holo-telemetry/metric`` subtree, no callback-backed gauge is live,
  and nothing external invalidated the tree, an unchanged stamp skips
  the walk itself.

Fallback contract (same breaker discipline as the SPF plane): any
engine failure increments ``holo_gnmi_fanout_fallback_total``, N
consecutive failures open the breaker, and every stream degrades to
the per-subscriber walk path (``_SubSampler``) with byte-identical
output; a cooldown later the engine half-opens and fresh streams probe
it again.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import deque

from holo_tpu import telemetry

log = logging.getLogger("holo_tpu.telemetry.delta")

ROOT = "holo-telemetry"
# The registry-backed subtree: ONLY these leaves are provably frozen by
# an unchanged write stamp (flight/convergence/cache stats under
# holo-telemetry/ move without registry writes), so the idle
# short-circuit requires every bucket to sit strictly under it.
METRIC_ROOT = "holo-telemetry/metric"
# The engine's OWN live stats leaf (provider.py surfaces it for Get).
# It is excluded from the sampled leaf store: diffing it would make
# every epoch advance change the tree again — a self-sustaining
# change feedback loop that re-renders forever on an idle system.
# Subscribers read it via Get; the registry-backed holo_gnmi_fanout_*
# METRIC leaves still flow through sampling like any other counter.
SELF_ROOT = "holo-telemetry/gnmi-fanout"

#: consecutive tick failures before the breaker opens
BREAKER_THRESHOLD = 3
#: seconds an open breaker parks before half-opening to a probe
BREAKER_COOLDOWN = 30.0
#: per-epoch change-set window kept for O(changed) delta renders;
#: cursors older than the window fall back to a full stamp scan
RECENT_EPOCHS = 128
#: distinct covering subtree roots beyond which the scoped per-root
#: fetch costs more than one full-tree walk (every provider runs per
#: get_state call) — fall back to the single full walk instead
MAX_SCOPED_ROOTS = 4

# Every family here is stamped=False: the engine's own bookkeeping must
# not advance the registry write stamp, or serving a heartbeat would
# re-arm the next tick's walk and the idle short-circuit (and suppress
# streams over the metric subtree) would never quiesce.
_EPOCHS = telemetry.counter(
    "holo_gnmi_fanout_epochs_total",
    "Shared-delta fan-out epochs (ticks that observed a leaf change)",
    stamped=False,
)
_RENDERS = telemetry.counter(
    "holo_gnmi_fanout_shared_renders_total",
    "Notifications rendered ONCE and shared across all due subscribers",
    ("kind",),
    stamped=False,
)
_CACHE = telemetry.counter(
    "holo_gnmi_fanout_render_cache_total",
    "Shared render cache lookups keyed by (epoch, subtree)",
    ("result",),
    stamped=False,
)
_LEAVES = telemetry.histogram(
    "holo_gnmi_fanout_leaves_changed",
    "Changed-leaf count per fan-out epoch",
    buckets=(0, 1, 2, 5, 10, 25, 50, 100, 250, 1000, 10000),
    stamped=False,
)
_TICK = telemetry.histogram(
    "holo_gnmi_fanout_tick_seconds",
    "Wall seconds per coalesced fan-out tick (snapshot+diff+render+put)",
    stamped=False,
)
_FALLBACK = telemetry.counter(
    "holo_gnmi_fanout_fallback_total",
    "Delta-engine failures degrading subscribers to the walk path",
    ("reason",),
    stamped=False,
)
_SUBSCRIBERS = telemetry.gauge(
    "holo_gnmi_fanout_subscribers", "Epoch cursors attached to the engine",
    stamped=False,
)
_BUCKETS = telemetry.gauge(
    "holo_gnmi_fanout_buckets", "Distinct interval buckets in the engine",
    stamped=False,
)

# Engines register here (weakly) so the holo-telemetry provider leaf
# can surface fan-out stats without owning a reference.
_ENGINES: "weakref.WeakSet[FanoutEngine]" = weakref.WeakSet()


def register_engine(engine: "FanoutEngine") -> None:
    _ENGINES.add(engine)


def engines_stats() -> list[dict]:
    return [e.stats() for e in list(_ENGINES)]


def _pb():
    """The gNMI lite proto module + render helpers (lazy: importing the
    server pulls grpc; by render time it is always loaded)."""
    import holo_tpu.daemon.gnmi_server as gs

    return gs


def _match(base: str, path: str) -> bool:
    """Same subtree predicate as the legacy per-subscriber walk."""
    return (
        not base
        or path == base
        or path.startswith((base + "/", base + "["))
    )


class _Member:
    """One attached subscriber queue inside a bucket.  ``needs_full``
    marks a cursor that has not received its first sampled push yet —
    its first notification is a full sync (shared with every other
    member syncing at the same tick), matching the legacy sampler's
    empty ``last`` dict."""

    __slots__ = ("queue", "sid", "needs_full")

    def __init__(self, queue, sid: int, needs_full: bool) -> None:
        self.queue = queue
        self.sid = sid
        self.needs_full = needs_full


class _Bucket:
    """A shared sampler: the epoch-cursor replacement for one
    ``_SubSampler`` timer configuration, serving EVERY subscriber with
    that configuration.  Timer semantics mirror the legacy sampler
    (sample + heartbeat next-due, beat wins the mode label when both
    fire in one wake)."""

    __slots__ = (
        "path", "kind", "interval", "heartbeat", "suppress",
        "next_sample", "next_beat", "cursor", "members",
    )

    def __init__(self, spec: tuple, now: float, cursor: int) -> None:
        self.path, self.kind, self.interval, self.heartbeat, self.suppress = (
            spec
        )
        self.next_sample = now + self.interval if self.interval else None
        self.next_beat = now + self.heartbeat if self.heartbeat else None
        self.cursor = cursor
        self.members: list[_Member] = []

    def next_due(self) -> float | None:
        # All _Bucket state is guarded by the owning engine's lock.
        s, b = self.next_sample, self.next_beat
        if s is None:
            return b
        if b is None:
            return s
        return min(s, b)

    def advance_if_due(self, now: float) -> tuple[bool, bool]:
        beat = self.next_beat is not None and now >= self.next_beat
        sample = self.next_sample is not None and now >= self.next_sample
        while self.next_beat is not None and self.next_beat <= now:
            self.next_beat += self.heartbeat
        while self.next_sample is not None and self.next_sample <= now:
            self.next_sample += self.interval
        return beat, sample


def bucket_spec(sub, tick: float) -> tuple | None:
    """(path, kind, interval, heartbeat, suppress) for a
    ``pb.Subscription``, or None when it needs no engine timer.

    SAMPLE keeps its own interval (gNMI 0.8 default/floor rules);
    ON_CHANGE / TARGET_DEFINED ride the engine's base tick for real
    change delivery — an upgrade over the legacy path, where ON_CHANGE
    state subtrees only ever saw commit/yang notifications — plus
    their optional heartbeat."""
    gs = _pb()
    path = gs.path_to_str(sub.path)
    heartbeat = (
        max(sub.heartbeat_interval / 1e9, gs.MIN_SAMPLE_INTERVAL)
        if sub.heartbeat_interval
        else None
    )
    if sub.mode == gs.pb.SAMPLE:
        interval = max(
            sub.sample_interval / 1e9 or gs.DEFAULT_SAMPLE_INTERVAL,
            gs.MIN_SAMPLE_INTERVAL,
        )
        return (path, "sample", interval, heartbeat, bool(sub.suppress_redundant))
    # ON_CHANGE / TARGET_DEFINED: deltas at the base tick, suppressed
    # by construction (only changed leaves ever go out).
    interval = max(tick, gs.MIN_SAMPLE_INTERVAL) if tick else None
    if interval is None and heartbeat is None:
        return None
    return (path, "on-change", interval, heartbeat, True)


class FanoutEngine:
    """The shared-delta observatory: one snapshot + one change-set per
    coalesced tick epoch, rendered once per bucket, fanned out through
    the caller's bounded queues.

    ``fetch_state``   -> the full operational tree (one walk per tick);
    ``deliver(q, sid, notif, in_burst) -> bool``
                      -> bounded put with the caller's drop/burst
                         accounting (gnmi_server._deliver);
    ``burst_snapshot``-> set of sids currently in a drop burst;
    ``on_push(mode, n_updates)``
                      -> per-delivery metric hook (the legacy
                         holo_gnmi_sample_updates_total surface);
    ``clock``/``clock_ns``
                      -> bucket timers / notification timestamps
                         (injectable: virtual-clock storms and the
                         byte-identity bench arm pin both).
    """

    def __init__(
        self,
        fetch_state,
        deliver,
        burst_snapshot=None,
        on_push=None,
        tick: float = 1.0,
        clock=time.monotonic,
        clock_ns=None,
        breaker_threshold: int = BREAKER_THRESHOLD,
        breaker_cooldown: float = BREAKER_COOLDOWN,
    ) -> None:
        self._fetch_state = fetch_state
        self._deliver = deliver
        self._burst_snapshot = burst_snapshot or (lambda: frozenset())
        self._on_push = on_push
        self.tick = tick
        self._clock = clock
        self._clock_ns = clock_ns or (lambda: int(time.time() * 1e9))
        self._lock = threading.Lock()
        # One tick at a time: the ticker thread and any manual
        # tick_now() driver (bench, tests) serialize here, so the
        # store/diff path stays single-writer.
        self._tick_lock = threading.Lock()
        self._buckets: dict[tuple, _Bucket] = {}
        self._all_telemetry = True
        # Union of bucket subtree roots (None = some bucket wants the
        # whole tree): the fetch closure may scope its get_state walk
        # to these instead of snapshotting every provider per tick.
        self._roots: tuple | None = None
        # Leaf store + versioning.
        self._epoch = 0
        self._store: dict[str, object] = {}
        self._changed: dict[str, int] = {}  # path -> last-changed epoch
        self._recent: deque = deque(maxlen=RECENT_EPOCHS)  # (epoch, [paths])
        self._stamp: int | None = None  # registry stamp at last walk
        self._dirty = True  # external invalidation (commit/yang notify)
        # Shared render caches: `_rendered` memoizes one pb.Update per
        # leaf (invalidated when the leaf changes); `_cache` memoizes
        # whole notifications keyed (kind, path[, since]) and is
        # cleared on every epoch advance — a heartbeat over an
        # unchanged epoch is a pure cache hit.
        self._rendered: dict[str, object] = {}
        self._cache: dict[tuple, object] = {}
        # Breaker (SPF-plane discipline: consecutive failures open,
        # cooldown half-opens, a successful tick closes).
        self._threshold = breaker_threshold
        self._cooldown = breaker_cooldown
        self._failures = 0
        self._open_at: float | None = None
        # Ticker thread (lazy: parked until the first bucket exists).
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._stopped = False

    # -- subscriber management ------------------------------------------

    def attach(self, q, sid: int, subscriptions) -> list | None:
        """Group a stream's subscriptions into interval buckets; returns
        an opaque handle for :meth:`detach`, or None when the breaker
        is open (the caller then runs the legacy walk path)."""
        if not self.healthy():
            _FALLBACK.labels(reason="breaker-open").inc()
            return None
        specs = [
            s
            for s in (bucket_spec(sub, self.tick) for sub in subscriptions)
            if s is not None
        ]
        if not specs:
            return []
        now = self._clock()
        handle = []
        with self._lock:
            for spec in specs:
                b = self._buckets.get(spec)
                if b is None:
                    b = _Bucket(spec, now, self._epoch)
                    self._buckets[spec] = b
                # EVERY new cursor owes a first full sampled push: a
                # change landing between the stream's preamble snapshot
                # and this attach would otherwise be silently lost (the
                # bucket cursor may already sit past the epoch the
                # client saw).
                m = _Member(q, sid, needs_full=True)
                b.members.append(m)
                handle.append((b, m))
            self._all_telemetry, self._roots = self._scope_of(self._buckets)
            self._update_gauges_locked()
        self._wake.set()
        return handle

    def detach(self, handle) -> None:
        if not handle:
            return
        with self._lock:
            for b, m in handle:
                try:
                    b.members.remove(m)
                except ValueError:
                    pass
                if not b.members:
                    self._buckets.pop(
                        (b.path, b.kind, b.interval, b.heartbeat, b.suppress),
                        None,
                    )
            self._all_telemetry, self._roots = self._scope_of(self._buckets)
            self._update_gauges_locked()

    @staticmethod
    def _scope_of(buckets) -> tuple:
        """(all_telemetry, roots) for a bucket table — pure, so the
        caller assigns both under its own lock hold.

        Roots are collapsed to COVERING prefixes (a bucket nested
        under another bucket's subtree adds no fetch work) and capped:
        every provider is consulted per get_state call, so past a few
        distinct roots one full-tree walk is cheaper than N scoped
        ones — the cap falls back to it."""
        all_telemetry = all(k[0].startswith(METRIC_ROOT) for k in buckets)
        paths = sorted({k[0] for k in buckets})
        if not paths or "" in paths:
            return all_telemetry, None
        covering: list[str] = []
        for p in paths:
            if not any(_match(c, p) for c in covering):
                covering.append(p)
        if len(covering) > MAX_SCOPED_ROOTS:
            return all_telemetry, None
        return all_telemetry, tuple(covering)

    def sample_roots(self) -> tuple | None:
        """Union of subscribed subtree roots, for scope-aware fetch
        closures (None = fetch the full tree)."""
        with self._lock:
            return self._roots

    def _update_gauges_locked(self) -> None:
        _SUBSCRIBERS.set(sum(len(b.members) for b in self._buckets.values()))
        _BUCKETS.set(len(self._buckets))

    def invalidate(self) -> None:
        """External state change (commit / yang notification): the next
        tick must walk even if the registry stamp is unchanged."""
        with self._lock:
            self._dirty = True
        self._wake.set()

    # -- breaker --------------------------------------------------------

    def healthy(self) -> bool:
        """False while the breaker is open; a cooldown later it
        half-opens (True) so new streams / the next tick probe it."""
        with self._lock:
            if self._open_at is None:
                return True
            if self._clock() - self._open_at >= self._cooldown:
                return True  # half-open: next failure re-opens
            return False

    def _note_failure(self, reason: str) -> None:
        _FALLBACK.labels(reason=reason).inc()
        with self._lock:
            self._failures += 1
            if self._failures >= self._threshold:
                opening = self._open_at is None
                self._open_at = self._clock()
            else:
                opening = False
        if opening:
            log.warning(
                "gNMI shared-delta fan-out breaker OPEN after %d "
                "consecutive tick failures; subscribers degrade to the "
                "per-subscriber walk path",
                self._failures,
            )

    # -- ticking --------------------------------------------------------

    def next_due(self) -> float | None:
        with self._lock:
            due = [b.next_due() for b in self._buckets.values()]
        due = [t for t in due if t is not None]
        return min(due) if due else None

    def tick_now(self, now: float | None = None, state=None) -> dict:
        """One coalesced tick: advance every due bucket against ONE
        state snapshot/epoch, render per bucket (shared cache), fan out
        to member queues.  Manual drivers (bench/tests) may inject
        ``now`` and a pre-fetched ``state``."""
        with self._tick_lock:
            return self._tick_locked(now, state)

    def tick_guarded(self, now: float | None = None) -> dict | None:
        """The ticker's tick: any failure feeds the breaker (and the
        fallback counter) instead of propagating — subscribers degrade
        to the walk path, they never lose the stream."""
        try:
            return self.tick_now(now)
        except Exception as e:  # noqa: BLE001 — breaker + walk fallback
            log.debug("gNMI fan-out tick failed: %s", e, exc_info=True)
            self._note_failure(type(e).__name__)
            return None

    def _tick_locked(self, now, state) -> dict:
        if now is None:
            now = self._clock()
        with self._lock:
            due = []
            for b in self._buckets.values():
                nd = b.next_due()
                if nd is not None and now >= nd:
                    beat, sample = b.advance_if_due(now)
                    due.append((b, beat, sample, list(b.members), b.cursor))
        if not due:
            return {"fired": 0, "epoch": self._epoch}
        t0 = time.perf_counter()
        walked = False
        if state is not None:
            # An injected snapshot is authoritative (bench/test drivers
            # pin the exact state both arms see): never skip it.
            self._refresh(state)
            walked = True
        elif not self._can_skip_walk():
            self._refresh(self._fetch_state())
            walked = True
        epoch = self._epoch
        t_walked = time.perf_counter() - t0
        bursts = self._burst_snapshot()
        delivered = dropped = 0
        t_render = 0.0

        def timed(render, *args):
            nonlocal t_render
            tr = time.perf_counter()
            try:
                return render(*args)
            finally:
                t_render += time.perf_counter() - tr

        for b, beat, sample, members, cursor in due:
            mode = (
                "heartbeat"
                if beat
                else ("sample" if b.kind == "sample" else "on-change")
            )
            # Lazy shared renders: each flavor's update list is
            # computed at most ONCE per bucket fire — and only when
            # some member actually needs it (a bucket of all-new
            # cursors never pays for the delta) — then wrapped in ONE
            # freshly-stamped Notification shared by every member.
            full_u = None
            full_notif = None
            delta_u = _UNSET
            delta_notif = None
            full_fire = beat or (sample and not b.suppress)
            for m in members:
                syncing = m.needs_full or full_fire
                if syncing:
                    # First sampled push is a full sync (shared: every
                    # member syncing this tick gets the same render);
                    # any full render (a beat) also settles the debt.
                    if full_u is None:
                        full_u = timed(self._render_full, b.path)
                    if full_notif is None and full_u:
                        full_notif = timed(self._notif_of, full_u)
                    out = full_notif
                else:
                    if delta_u is _UNSET:
                        delta_u = timed(
                            self._render_delta, b.path, cursor
                        )
                    if delta_notif is None and delta_u:
                        delta_notif = timed(self._notif_of, delta_u)
                    out = delta_notif
                if out is None:
                    continue
                if self._deliver(m.queue, m.sid, out, m.sid in bursts):
                    delivered += 1
                    if self._on_push is not None:
                        self._on_push(mode, len(out.update))
                    if m.needs_full:
                        # The baseline debt clears only on a CONFIRMED
                        # put: a full sync dropped on a full queue must
                        # retry at the next fire, or the cursor would
                        # serve deltas against a baseline the client
                        # never received.
                        m.needs_full = False
                else:
                    dropped += 1
            b.cursor = epoch
        with self._lock:
            self._failures = 0
            if self._open_at is not None:
                self._open_at = None
                log.info("gNMI shared-delta fan-out breaker closed")
        dt = time.perf_counter() - t0
        if walked or delivered:
            # Skipped-idle ticks stay out of the histogram AND out of
            # the write stamp: observing them would advance the stamp
            # and wake the next tick's walk for nothing.
            _TICK.observe(dt, exemplar={"epoch": epoch})
        if walked:
            # Stamp AFTER the engine's own per-tick metric observes:
            # the tick's bookkeeping must not wake the next tick's walk
            # (a feedback loop that would defeat the idle
            # short-circuit).  The price is a tick-execution-wide
            # masking window: a foreign write landing mid-tick is
            # folded into this stamp and its leaf stays stale until the
            # NEXT write anywhere — an eventually-consistent surface,
            # same as a scrape racing a write.
            with self._lock:
                self._stamp = telemetry.write_stamp()
        return {
            "fired": len(due),
            "epoch": epoch,
            "walked": walked,
            "delivered": delivered,
            "dropped": dropped,
            "tick_seconds": dt,
            # The O(1)-in-subscribers portion (snapshot+diff+render)
            # vs the O(subscribers) bounded-queue delivery floor — the
            # split the gnmi_fanout bench gates on.
            "render_seconds": t_walked + t_render,
            "deliver_seconds": max(dt - t_walked - t_render, 0.0),
        }

    def _can_skip_walk(self) -> bool:
        """O(1) idle tick: every bucket under holo-telemetry, no
        callback-backed gauges live, nothing external invalidated the
        tree, and the registry write stamp unchanged since the last
        walk — the snapshot is provably byte-identical."""
        with self._lock:
            if self._dirty or self._stamp is None or not self._all_telemetry:
                return False
        return (
            telemetry.volatile_children() == 0
            and telemetry.write_stamp() == self._stamp
        )

    def _refresh(self, state) -> bool:
        """Diff one walked snapshot against the leaf store; advances the
        epoch iff anything changed."""
        gs = _pb()
        trees = state if isinstance(state, list) else [state]
        leaves = {
            p: v
            for tree in trees
            for p, v in gs._walk_leaves("", tree)
            if not p.startswith(SELF_ROOT)
        }
        store = self._store
        changed = [p for p, v in leaves.items() if store.get(p, _MISS) != v]
        removed = [p for p in store if p not in leaves]
        if not changed and not removed:
            with self._lock:
                self._dirty = False
            return False
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
            for p in changed:
                store[p] = leaves[p]
                self._changed[p] = epoch
                self._rendered.pop(p, None)
            for p in removed:
                del store[p]
                self._changed.pop(p, None)
                self._rendered.pop(p, None)
            self._recent.append((epoch, changed))
            self._cache.clear()
            self._dirty = False
        _EPOCHS.inc()
        _LEAVES.observe(len(changed) + len(removed))
        return True

    # -- shared rendering -----------------------------------------------

    def _leaf_update(self, path: str):
        """One pb.Update per (leaf, value) — parsed/typed ONCE per
        change, shared by every notification that carries the leaf."""
        u = self._rendered.get(path)
        if u is None:
            gs = _pb()
            u = gs.pb.Update(
                path=gs.str_to_path(path),
                val=gs._typed_value(self._store[path]),
            )
            with self._lock:
                self._rendered[path] = u
        return u

    def _notif_of(self, updates):
        """One Notification per bucket fire: the update LIST is the
        cached/shared artifact; the timestamp is stamped fresh at push
        time so heartbeats over an unchanged epoch still read as live
        (the legacy walk path stamps every push too)."""
        gs = _pb()
        notif = gs.pb.Notification(timestamp=self._clock_ns())
        for u in updates:
            notif.update.add().CopyFrom(u)
        return notif

    def _updates(self, paths):
        return tuple(self._leaf_update(p) for p in sorted(paths))

    def _render_full(self, path: str):
        """Cached tuple of pb.Updates for the whole subtree (cleared
        only on epoch advance — a heartbeat over an unchanged epoch is
        a pure cache hit)."""
        key = ("full", path)
        if key in self._cache:
            _CACHE.labels(result="hit").inc()
            return self._cache[key]
        _CACHE.labels(result="miss").inc()
        updates = self._updates(
            [p for p in self._store if _match(path, p)]
        )
        _RENDERS.labels(kind="full").inc()
        with self._lock:
            self._cache[key] = updates
        return updates

    def _render_delta(self, path: str, since: int):
        """Updates for leaves whose last-changed epoch is newer than
        the cursor — the epoch-comparison replacement for the legacy
        value diff.  Returns None when nothing changed."""
        if since >= self._epoch:
            return None
        key = ("delta", path, since)
        if key in self._cache:
            _CACHE.labels(result="hit").inc()
            return self._cache[key]
        _CACHE.labels(result="miss").inc()
        if self._recent and self._recent[0][0] <= since + 1:
            cand: set[str] = set()
            for epoch, paths in reversed(self._recent):
                if epoch <= since:
                    break
                cand.update(paths)
            # Deletions between the cursor and now leave stale paths in
            # the window; the store lookup drops them.
            paths = [
                p for p in cand if p in self._store and _match(path, p)
            ]
        else:
            paths = [
                p
                for p, e in self._changed.items()
                if e > since and _match(path, p)
            ]
        updates = self._updates(paths) if paths else None
        if updates is not None:
            _RENDERS.labels(kind="delta").inc()
        with self._lock:
            self._cache[key] = updates
        return updates

    # -- ticker thread --------------------------------------------------

    def start(self) -> None:
        """Idempotent: spin the coalescing ticker up (parks while no
        buckets exist, so an idle service costs one blocked thread)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stopped = False
            self._thread = threading.Thread(
                target=self._run, name="gnmi-fanout-ticker", daemon=True
            )
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            t = self._thread
            self._thread = None
        self._wake.set()
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stopped:
            nd = self.next_due()
            if nd is None:
                self._wake.wait()
                self._wake.clear()
                continue
            now = self._clock()
            if nd > now:
                # Cap the sleep so attach()/invalidate() wakes and
                # clock skew (tests swapping clocks) resolve quickly.
                self._wake.wait(min(nd - now, 0.5))
                self._wake.clear()
                continue
            if self.tick_guarded(now) is None and not self.healthy():
                # Open: park for the cooldown (or an early wake).
                self._wake.wait(self._cooldown)
                self._wake.clear()

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            n_members = sum(len(b.members) for b in self._buckets.values())
            state = (
                "closed"
                if self._open_at is None
                else (
                    "half-open"
                    if self._clock() - self._open_at >= self._cooldown
                    else "open"
                )
            )
            return {
                "epoch": self._epoch,
                "subscribers": n_members,
                "buckets": len(self._buckets),
                "leaves": len(self._store),
                "breaker": state,
                "consecutive-failures": self._failures,
                "all-telemetry": self._all_telemetry,
                "tick": self.tick,
            }


class _Miss:
    __slots__ = ()


_MISS = _Miss()
_UNSET = _Miss()

"""Flight recorder: a bounded in-memory ring of recent observability
events, dumped as a **postmortem bundle** when something goes wrong.

PR 4's resilience layer detects failures (breaker open, crash-loop →
degraded, SIGTERM) but throws away the context that explains them: by
the time an operator looks, the spans, metric movement, and journal
position around the failure are gone.  The flight recorder keeps the
last ``capacity`` entries — completed trace spans (tapped off the
default :class:`~holo_tpu.telemetry.trace.SpanTracer`), event-journal
sequence markers (:func:`journal_mark`, stamped by
``utils/event_recorder.py`` on every journaled delivery), and discrete
resilience events (breaker transitions, actor crashes/restarts) — in a
lock-light deque, **off by default** (``[telemetry]
flight-buffer-entries`` > 0 arms it; the hot-path cost when disarmed is
one module-global ``None`` check).

A **postmortem trigger** (:func:`trigger`, wired from
``resilience/breaker.py`` breaker-open, ``resilience/supervisor.py``
crash-loop degrade, and the daemon's SIGTERM handler) snapshots the
ring and writes one JSON bundle to ``[telemetry] postmortem-dir``:

- ``ring`` — the recent-event window (spans renumbered relative to the
  first recorded span, so two runs of the same seeded scenario produce
  identical bundles);
- ``metrics`` — counter / histogram-count **deltas** since the recorder
  was armed (gauges and histogram sums are wall-time-dependent and
  stay on the scrape surface);
- ``health`` — breaker + supervision state, restricted to unhealthy
  entries so long-dead test breakers do not leak in;
- ``journal-tail`` — the last :data:`JOURNAL_TAIL` journal sequence
  markers, joining the bundle to the event-recorder file on disk.

Determinism is a design requirement (the chaos acceptance test pins a
seeded run's bundle byte-identical across runs): timestamps come from
an injectable clock (the daemon passes its loop clock — virtual in
tests), breaker-name ``#N`` uniquifiers and ``0x...`` addresses inside
strings are normalized, and volatile wall-time quantities are excluded
as described above.  Render with ``holo-tpu-tools postmortem``.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from collections import deque
from pathlib import Path

from holo_tpu import telemetry

log = logging.getLogger("holo_tpu.telemetry")

#: journal seq markers preserved verbatim in the bundle tail
JOURNAL_TAIL = 32

# Cross-run noise scrubbing for bundle strings: breaker-name "#N"
# instance uniquifiers and object addresses inside reprs.
_UNIQ = re.compile(r"#\d+$")
_ADDR = re.compile(r"0x[0-9a-fA-F]+")


def _scrub(v):
    if isinstance(v, str):
        return _ADDR.sub("0x?", _UNIQ.sub("", v))
    if isinstance(v, (int, float, bool)) or v is None:
        return v
    return _ADDR.sub("0x?", str(v))


class FlightRecorder:
    """One process-wide ring (module singleton via :func:`configure`)."""

    def __init__(
        self,
        capacity: int = 2048,
        postmortem_dir: str | Path | None = None,
        clock=time.monotonic,
        min_dump_interval: float = 60.0,
    ):
        """``min_dump_interval`` (clock seconds) debounces repeat dumps
        for the same reason: a breaker flapping open every
        recovery_timeout over a long outage must not fill the disk —
        the first bundle holds the interesting context; repeats within
        the window only land an event in the ring."""
        self.capacity = int(capacity)
        self.postmortem_dir = (
            Path(postmortem_dir) if postmortem_dir is not None else None
        )
        self.min_dump_interval = float(min_dump_interval)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._span_base: int | None = None
        self._dumps = 0
        self._last_dump: dict[str, float] = {}  # scrubbed reason -> clock
        # Metric baseline for the bundle's delta section, taken at arm
        # time with the same normalization as the dump-time walk.
        self._baseline = self._counts()

    # -- hot-path taps (O(1) each, append under a short lock)

    def note_span(self, sp) -> None:
        """Tracer completion tap (installed by :func:`configure`)."""
        attrs = {str(k): _scrub(v) for k, v in sp.attrs.items()}
        with self._lock:
            if self._span_base is None:
                self._span_base = sp.span_id
            base = self._span_base
            parent = (
                sp.parent_id - base
                if sp.parent_id is not None and sp.parent_id >= base
                else None
            )
            self._ring.append(
                (
                    "span",
                    sp.name,
                    sp.span_id - base,
                    parent,
                    round(sp.start_us, 3),
                    round(sp.dur_us, 3),
                    attrs,
                )
            )

    def journal_mark(self, seq: int, actor: str = "") -> None:
        """Event-journal position marker (one per journaled delivery)."""
        t = round(self._clock() - self._t0, 6)
        with self._lock:
            self._ring.append(("journal", int(seq), str(actor), t))

    def event(self, kind: str, **fields) -> None:
        """Discrete resilience/lifecycle event (breaker transition,
        actor crash, postmortem trigger, ...)."""
        t = round(self._clock() - self._t0, 6)
        clean = {str(k): _scrub(v) for k, v in sorted(fields.items())}
        with self._lock:
            self._ring.append(("event", kind, clean, t))

    # -- bundle assembly (cold path)

    @staticmethod
    def _counts() -> dict[str, float]:
        """{normalized series name -> monotone count}: counter values
        and histogram counts (gauges and sums are wall/state-dependent
        and excluded by design).  Normalized-name collisions (breaker
        uniquifiers) sum."""
        out: dict[str, float] = {}
        for fam in telemetry.registry().families():
            if fam.kind == "gauge":
                continue
            for key, child in fam.children():
                labels = ",".join(
                    _UNIQ.sub("", f"{n}={v}")
                    for n, v in zip(fam.labelnames, key)
                )
                name = f"{fam.name}{{{labels}}}" if labels else fam.name
                cur = child.count if fam.kind == "histogram" else child.value
                out[name] = out.get(name, 0) + cur
        return out

    def metric_deltas(self) -> dict[str, float]:
        cur = self._counts()
        out = {}
        for name, v in cur.items():
            d = v - self._baseline.get(name, 0)
            if d:
                out[name] = int(d) if float(d).is_integer() else d
        return out

    @staticmethod
    def _health() -> dict:
        """Resilience health restricted to entries a postmortem reader
        cares about: non-closed / recently-failing breakers (names
        normalized) and supervision verdicts."""
        from holo_tpu.resilience import health_snapshot

        health = health_snapshot()
        brs = {}
        for name, snap in health.get("breakers", {}).items():
            if snap["state"] == "closed" and not snap["consecutive-failures"]:
                continue
            snap = dict(snap)
            snap["last-error"] = _scrub(snap.get("last-error", ""))
            brs[_UNIQ.sub("", name)] = snap
        out: dict = {}
        if brs:
            out["breakers"] = brs
        if "supervision" in health:
            out["supervision"] = health["supervision"]
        return out

    def snapshot_ring(self) -> list:
        with self._lock:
            return list(self._ring)

    def postmortem(self, reason: str, extra: dict | None = None):
        """Assemble + (when a directory is configured) write one bundle.
        Returns ``(path | None, bundle dict | None)`` — ``(None, None)``
        when the same reason already dumped within
        ``min_dump_interval``.  File I/O happens outside the ring lock;
        filenames are a dump ordinal + reason slug — deterministic, no
        wall-clock component."""
        ring = self.snapshot_ring()
        with self._lock:
            key = _scrub(reason)
            now = self._clock()
            last = self._last_dump.get(key)
            if last is not None and now - last < self.min_dump_interval:
                log.debug(
                    "postmortem for %r debounced (%.1fs since last)",
                    key, now - last,
                )
                return None, None
            self._last_dump[key] = now
            self._dumps += 1
            n = self._dumps
        tail = [e for e in ring if e[0] == "journal"][-JOURNAL_TAIL:]
        bundle = {
            "schema": "holo-postmortem/1",
            "reason": _scrub(reason),
            "dump": n,
            "ring": [list(e) for e in ring],
            "metrics": self.metric_deltas(),
            "health": self._health(),
            "journal-tail": [[e[1], e[2]] for e in tail],
        }
        if extra:
            bundle["extra"] = {str(k): _scrub(v) for k, v in extra.items()}
        path = None
        if self.postmortem_dir is not None:
            text = json.dumps(bundle, sort_keys=True, indent=2)
            slug = re.sub(r"[^A-Za-z0-9._-]+", "-", bundle["reason"])[:48]
            self.postmortem_dir.mkdir(parents=True, exist_ok=True)
            path = self.postmortem_dir / f"postmortem-{n:03d}-{slug}.json"
            path.write_text(text + "\n")
            log.warning("postmortem bundle written: %s", path)
        return path, bundle

    def stats(self) -> dict:
        """holo-telemetry state-leaf view."""
        with self._lock:
            return {
                "entries": len(self._ring),
                "capacity": self.capacity,
                "dumps": self._dumps,
            }


# -- process-wide singleton ---------------------------------------------

_RECORDER: FlightRecorder | None = None


def configure(
    entries: int = 0,
    postmortem_dir: str | Path | None = None,
    clock=None,
) -> FlightRecorder | None:
    """Arm (``entries`` > 0) or disarm (0) the process-wide recorder and
    (un)install the tracer completion tap.  The daemon calls this at
    boot from ``[telemetry] flight-buffer-entries`` / ``postmortem-dir``
    with its loop clock; bench and tests flip it directly.

    Arming also swaps the default tracer onto the same clock (epoch
    reset), so the span entries and the journal/event stamps inside one
    bundle share a timebase — and a virtual-clock run is deterministic
    end to end.  Disarming restores ``time.monotonic``."""
    global _RECORDER
    tracer = telemetry.tracer()
    if entries and int(entries) > 0:
        clk = clock or time.monotonic
        _RECORDER = FlightRecorder(int(entries), postmortem_dir, clk)
        tracer.use_clock(clk)
        tracer.on_complete = _RECORDER.note_span
    else:
        _RECORDER = None
        tracer.on_complete = None
        tracer.use_clock(time.monotonic)
    return _RECORDER


def recorder() -> FlightRecorder | None:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER is not None


def journal_mark(seq: int, actor: str = "") -> None:
    r = _RECORDER
    if r is not None:
        r.journal_mark(seq, actor)


def event(kind: str, **fields) -> None:
    r = _RECORDER
    if r is not None:
        r.event(kind, **fields)


def trigger(reason: str, extra: dict | None = None) -> Path | None:
    """Postmortem capture: record the trigger in the ring, then dump a
    bundle (when armed and a directory is configured).  The callers are
    failure paths — breaker-open, crash-loop degrade, SIGTERM — so a
    dump failure is logged, never propagated."""
    r = _RECORDER
    if r is None:
        return None
    r.event("postmortem-trigger", reason=reason)
    try:
        path, _ = r.postmortem(reason, extra=extra)
        return path
    except Exception:  # noqa: BLE001 — forensics must not worsen faults
        log.exception("postmortem dump failed (reason=%s)", reason)
        return None

"""Unified telemetry: process-wide metrics registry + span tracer.

One import surface for every instrumentation site::

    from holo_tpu import telemetry

    _DISPATCHES = telemetry.counter(
        "holo_spf_dispatch_total", "SPF device dispatches", ("engine",))
    _DISPATCHES.labels(engine="tpu").inc()

    with telemetry.span("spf.dispatch", instance="ospfv2"):
        ...

Exports ride three surfaces (all daemon-wired in
:mod:`holo_tpu.daemon.daemon` behind the ``[telemetry]`` config
section):

- Prometheus text endpoint (:mod:`holo_tpu.telemetry.prometheus`);
- the gNMI/gRPC state tree via
  :class:`holo_tpu.telemetry.provider.TelemetryStateProvider`;
- Chrome trace-event JSON span dumps (:mod:`holo_tpu.telemetry.trace`)
  via ``holo-tpu-tools trace`` or ``HOLO_TPU_TRACE_DUMP=<path>``.

Everything here is stdlib-only and import-light: instrumented hot paths
(SPF dispatch, RIB churn, packet rx/tx) pay a dict hit and a locked
float add per event, and :func:`set_enabled` (False) turns every update
into an early return — the ``telemetry_overhead`` bench scenario keeps
the instrumented SPF path within noise of a disabled registry.
"""

from __future__ import annotations

import os

from holo_tpu.telemetry import registry as _registry_mod
from holo_tpu.telemetry.registry import (  # noqa: F401 — public API
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    deferred_mean,
    enabled,
    volatile_children,
    write_stamp,
)
from holo_tpu.telemetry.trace import SpanTracer

_registry = MetricsRegistry()
_tracer = SpanTracer()


def set_enabled(on: bool) -> None:
    """Global kill switch for BOTH the metrics registry and the default
    span tracer — the overhead bench's control arm must shed every
    instrumentation cost, spans included."""
    _registry_mod.set_enabled(on)
    _tracer.enabled = bool(on)


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _registry


def tracer() -> SpanTracer:
    """The process-wide default span tracer."""
    return _tracer


def counter(name: str, help: str = "", labelnames=(), stamped: bool = True):
    return _registry.counter(name, help, tuple(labelnames), stamped=stamped)


def gauge(name: str, help: str = "", labelnames=(), stamped: bool = True):
    return _registry.gauge(name, help, tuple(labelnames), stamped=stamped)


def histogram(
    name: str, help: str = "", labelnames=(), buckets=None,
    stamped: bool = True,
):
    return _registry.histogram(
        name, help, tuple(labelnames), buckets, stamped=stamped
    )


def span(name: str, **attrs):
    """Context manager recording one span on the default tracer."""
    return _tracer.span(name, **attrs)


def current_span_id():
    return _tracer.current_span_id()


def current_instance():
    return _tracer.current_instance()


def snapshot(prefix: str | None = None) -> dict:
    """Flat metrics view for bench rows / debugging."""
    return _registry.snapshot(prefix)


# Optional env-triggered span dump on process exit: any run (bench
# stage, test, daemon) gets a perfetto-loadable trace with no code
# change.  Registered once, at first package import.
_dump_path = os.environ.get("HOLO_TPU_TRACE_DUMP")
if _dump_path:  # pragma: no cover — exercised via subprocess in tests
    import atexit

    atexit.register(lambda: _tracer.dump(_dump_path))

"""First-class TPU-relay watch (ISSUE 12 satellite).

The relay has been down since round 3 — RELAY_WATCH.log shows 45
straight down-probes — yet the only in-process signal was per-stage
``extra.relay`` strings hand-rolled across ``bench.py`` and
``profiling.capture_device_trace``.  This module is the one place that
state lives:

- ``holo_relay_up`` gauge + ``holo_relay_probes_total{result}`` counter
  (Prometheus + the gNMI metric leaves, like every other family);
- a ``holo-telemetry/relay`` state leaf (:func:`stats`, served by
  :class:`~holo_tpu.telemetry.provider.TelemetryStateProvider`) with
  probe count / last error / last verdict;
- the shared row helpers the bench stages previously hand-rolled:
  :func:`summary` (the ``extra.relay`` dict) and :func:`not_used` (the
  per-stage "this stage never touched the relay" marker).

Probes themselves stay where they were (fresh-subprocess probes in
``bench.py`` — wedging is per-process, so an in-process probe would be
a lie); callers report verdicts here via :func:`note_probe`.  A daemon
gets its own in-process verdict from the platform check inside
``profiling.capture_device_trace`` (``[telemetry] device-trace-dir``);
a daemon configured without it leaves the leaf absent rather than
faking a probe it never ran.
"""

from __future__ import annotations

from holo_tpu import telemetry
from holo_tpu.telemetry import slo

_UP = telemetry.gauge(
    "holo_relay_up",
    "1 while the last TPU relay probe answered, 0 after a failed "
    "probe, unset before the first verdict",
)
_PROBES = telemetry.counter(
    "holo_relay_probes_total",
    "TPU relay probe verdicts reported to the watch",
    ("result",),
)

# Module-singleton state (GIL-atomic single-writer updates: the bench
# driver / daemon probe loop is one thread).
_state = {
    "status": "unknown",  # unknown | up | down
    "probes": 0,
    "last_error": None,
    "last_took_s": None,
}


def note_probe(ok: bool, error: str | None = None, took_s=None) -> None:
    """Record one probe verdict (gauge + counter + leaf state)."""
    _state["status"] = "up" if ok else "down"
    _state["probes"] += 1
    if error:
        _state["last_error"] = str(error)[:300]
    elif ok:
        _state["last_error"] = None
    if took_s is not None:
        _state["last_took_s"] = round(float(took_s), 3)
    _UP.set(1.0 if ok else 0.0)
    _PROBES.labels(result="up" if ok else "down").inc()
    # SLO availability feed (ISSUE 20): every holo_relay_up flip grades
    # the relay objective — "MXU bets blocked on the relay" becomes
    # budget arithmetic (down seconds over the compliance window)
    # instead of a prose note.  One module-global check when disarmed.
    slo.note_relay(bool(ok))


def status() -> dict:
    """Current watch state (a copy)."""
    return dict(_state)


def stats() -> dict:
    """holo-telemetry/relay gNMI leaf."""
    return dict(_state)


def summary(up: bool, history: list | None = None) -> dict:
    """The bench's ``extra.relay`` row: overall verdict + probe tally +
    the last probe error — one shape for every consumer (previously
    hand-rolled per stage)."""
    history = history or []
    errors = [h.get("error") for h in history if h.get("error")]
    return {
        "status": "up" if up else "down",
        "probes": len(history) or _state["probes"],
        "last_error": errors[-1] if errors else _state["last_error"],
    }


def not_used(reason: str | None = None) -> str:
    """The per-stage "this row never touched the relay" marker — the
    one spelling every stage row and fallback-list entry shares."""
    return f"not-used ({reason})" if reason else "not-used"

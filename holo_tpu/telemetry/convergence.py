"""Convergence observatory: causal event→FIB tracing.

The quantity the ROADMAP's perf arc is graded by — how long the network
takes to converge after a topology event — was invisible before this
module: PR 2/5 instrumented individual dispatches, but nothing joined a
*cause* (an LSA/LSP arrival, a BFD session dropping, carrier loss, an
interface config change) to its *effect* (the kernel FIB reflecting the
new topology).  This module stamps every topology-changing event with a
causal ``event_id`` at its origin and rides it through the whole chain:

    origin (protocol/BFD/ibus)          convergence.begin(trigger)
      → ibus publish                    IbusMsg.event_id (captured)
      → actor processing                EventLoop delivery context hook
      → SPF-delay FSM + dispatch        instance pend/drain + observe("spf")
      → RIB route ops                   observe("rib")
      → kernel FIB install / FRR flip   fib_commit() → observe("fib")

Each phase records a ``holo_convergence_seconds{trigger,phase}``
histogram observation with an OpenMetrics exemplar (the active trace
span id when one exists, the event id otherwise), so a scrape can jump
from a latency bucket to the trace that produced it; the per-event
causal **timeline** (origin, marks, dispatch sites with their span ids
— joining the marshal/device/readback sub-spans from
:mod:`holo_tpu.telemetry.profiling` — and the closing FIB commit) lands
in the flight-recorder ring on completion, so postmortem bundles carry
the last convergence stories leading up to a failure.

Dispatch attribution: the SPF/FRR backends call :func:`note_dispatch`
with the mode that actually served the computation (``device`` /
``scalar`` / ``fallback``).  An event served by the breaker's scalar
fallback closes with ``phase="fallback"`` instead of ``"fib"`` — the
storm bench splits its distributions on exactly this.

Everything is **off by default**: the hot-path cost while disarmed is
one module-global ``None`` check per seam (``[telemetry]
convergence-events`` arms it in the daemon; bench/tests call
:func:`configure` directly with the loop clock, which makes every
timeline and latency deterministic under the virtual clock).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager, nullcontext

from holo_tpu import telemetry
from holo_tpu.telemetry import flight

#: trigger classes (open set — these are the documented ones)
TRIGGER_LSA = "lsa"  # OSPF LSA arrival/change
TRIGGER_LSP = "lsp"  # IS-IS LSP arrival/change
TRIGGER_BFD = "bfd"  # BFD session state change
TRIGGER_CARRIER = "carrier"  # interface operational/carrier change
TRIGGER_IFCONFIG = "ifconfig"  # interface/instance config change

#: phases observed on holo_convergence_seconds (origin → phase end)
PHASE_SPF = "spf"  # SPF/route computation finished
PHASE_RIB = "rib"  # first RIB route operation applied
PHASE_FIB = "fib"  # first kernel FIB commit (event complete)
PHASE_FALLBACK = "fallback"  # FIB commit served via scalar fallback

# Convergence latencies span one virtual-clock instant (an O(1) FRR
# flip) to tens of seconds (LONG_WAIT SPF delays + retransmits under
# loss) — the default log-spaced bucket ladder covers exactly that.
_CONV_SECONDS = telemetry.histogram(
    "holo_convergence_seconds",
    "Topology-event to FIB convergence latency, by causal phase",
    ("trigger", "phase"),
)
_CONV_EVENTS = telemetry.counter(
    "holo_convergence_events_total",
    "Causal convergence events, by trigger class and outcome",
    ("trigger", "outcome"),
)

#: per-event timeline entries kept before the tail is dropped
TIMELINE_LIMIT = 64

# Critical-path ledger hook (ISSUE 17): while armed, event lifecycle
# moments (begin / spf-scheduled / phase observed / finish) are ALSO
# stamped into holo_tpu.telemetry.critpath's cross-thread waterfall.
# One module global, installed only by critpath.configure — the
# disarmed cost at every seam is exactly this None check.  The hook
# keeps its OWN clock (profiling.clock): the tracker's clock may be a
# storm's virtual loop clock, under which host compute is invisible.
_CP_HOOK = None


def set_critpath_hook(ledger) -> None:
    """Install/remove the critical-path ledger
    (:func:`holo_tpu.telemetry.critpath.configure` is the only
    caller); ``None`` disarms."""
    global _CP_HOOK
    _CP_HOOK = ledger


# SLO-engine hook (ISSUE 20): while armed, every fib_commit close ALSO
# grades the event's end-cut latency against the declared objectives in
# holo_tpu.telemetry.slo.  Same contract as _CP_HOOK: one module
# global, installed only by slo.configure, a single None check when
# disarmed — and the clock is read ONLY under a non-None hook, so the
# disarmed path stays byte-identical (poisoned-clock tested).
_SLO_HOOK = None


def set_slo_hook(engine) -> None:
    """Install/remove the SLO engine
    (:func:`holo_tpu.telemetry.slo.configure` is the only caller);
    ``None`` disarms."""
    global _SLO_HOOK
    _SLO_HOOK = engine


class _Event:
    """One open causal event (mutated only under the tracker lock)."""

    __slots__ = (
        "eid", "trigger", "t0", "attrs", "observed", "dispatch",
        "fallback", "timeline", "truncated",
    )

    def __init__(self, eid: int, trigger: str, t0: float, attrs: dict):
        self.eid = eid
        self.trigger = trigger
        self.t0 = t0
        self.attrs = attrs
        self.observed: set[str] = set()
        self.dispatch: dict[str, str] = {}  # site -> device|scalar|fallback
        self.fallback = False
        self.timeline: list = []
        self.truncated = 0


class ConvergenceTracker:
    """Process-wide causal event tracker (module singleton via
    :func:`configure`).

    Open events live in a bounded insertion-ordered map (an event storm
    cannot grow memory without limit: the oldest open event is closed as
    ``outcome="evicted"`` when a new one would exceed ``capacity``);
    completed timelines keep the most recent ``capacity`` entries.
    """

    def __init__(self, capacity: int = 512, clock=time.monotonic):
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._next = 1
        self._open: "OrderedDict[int, _Event]" = OrderedDict()
        self._done: deque = deque(maxlen=self.capacity)
        self._tls = threading.local()
        self._completed = 0

    # -- context (threadlocal active-event stack)

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> tuple[int, ...]:
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else ()

    @contextmanager
    def activation(self, eids: tuple[int, ...]):
        """Make ``eids`` the active causal context for the dynamic
        extent (nested activations stack; the delivery hook uses this to
        re-establish context when a message carrying event ids is
        handled on another actor/thread)."""
        st = self._stack()
        st.append(tuple(eids))
        try:
            yield
        finally:
            st.pop()

    # -- recording

    def begin(self, trigger: str, **attrs) -> int:
        """Stamp a new causal event at its origin; returns its id."""
        t = self._clock()
        clean = {str(k): str(v) for k, v in sorted(attrs.items())}
        evicted: _Event | None = None
        with self._lock:
            eid = self._next
            self._next += 1
            ev = _Event(eid, str(trigger), t, clean)
            ev.timeline.append(("origin", 0.0, clean))
            self._open[eid] = ev
            if len(self._open) > self.capacity:
                _, evicted = self._open.popitem(last=False)
        cp = _CP_HOOK
        if cp is not None:
            cp.ev_begin(eid, str(trigger))
        if evicted is not None:
            self._finish(evicted, "evicted")
        _CONV_EVENTS.labels(trigger=trigger, outcome="begun").inc()
        return eid

    def _events(self, eids) -> list[_Event]:
        with self._lock:
            return [ev for e in eids if (ev := self._open.get(e)) is not None]

    def active_triggers(self) -> tuple[str, ...]:
        """Trigger names of the currently-active causal events (storm
        harness: attribute real dispatch wall time to its trigger)."""
        return tuple(ev.trigger for ev in self._events(self.current()))

    def _entry(self, ev: _Event, step: str, attrs: dict) -> None:
        """Append one timeline entry (caller holds no lock)."""
        t = round(self._clock() - ev.t0, 9)
        with self._lock:
            if len(ev.timeline) >= TIMELINE_LIMIT:
                ev.truncated += 1
                return
            ev.timeline.append((step, t, attrs))

    def mark(self, step: str, eids=None, **attrs) -> None:
        """Timeline-only entry for the active (or given) events."""
        clean = {str(k): str(v) for k, v in sorted(attrs.items())}
        for ev in self._events(eids if eids is not None else self.current()):
            self._entry(ev, step, clean)

    def note_dispatch(self, site: str, mode: str) -> None:
        """Record which engine served a dispatch for the active events
        (``device`` / ``scalar`` / ``fallback``), joining the profiling
        sub-spans via the enclosing dispatch span id."""
        eids = self.current()
        if not eids:
            return
        sid = telemetry.current_span_id()
        attrs = {"site": site, "mode": mode}
        if sid is not None:
            attrs["span_id"] = str(sid)
        for ev in self._events(eids):
            with self._lock:
                ev.dispatch[site] = mode
                if mode == "fallback":
                    ev.fallback = True
            self._entry(ev, "dispatch", attrs)

    def observe(self, phase: str, eids=None, **attrs) -> None:
        """Histogram observation ``now - origin`` for each event that
        has not seen ``phase`` yet, with a span/event exemplar."""
        now = self._clock()
        clean = {str(k): str(v) for k, v in sorted(attrs.items())}
        sid = telemetry.current_span_id()
        for ev in self._events(eids if eids is not None else self.current()):
            with self._lock:
                if phase in ev.observed:
                    fresh = False
                else:
                    ev.observed.add(phase)
                    fresh = True
            if not fresh:
                continue
            cp = _CP_HOOK
            if cp is not None:
                cp.ev_phase(ev.eid, phase)
            exemplar = (
                {"span_id": sid} if sid is not None else {"event_id": ev.eid}
            )
            _CONV_SECONDS.labels(trigger=ev.trigger, phase=phase).observe(
                max(now - ev.t0, 0.0), exemplar=exemplar
            )
            self._entry(ev, phase, clean)

    def fib_commit(self, op: str = "install", eids=None, **attrs) -> None:
        """The FIB moment: observe the event-to-FIB total (phase
        ``fib``, or ``fallback`` when a scalar fallback served the
        computation) and complete the event — its causal timeline is
        flushed to the flight-recorder ring."""
        to_close: list[_Event] = []
        use = eids if eids is not None else self.current()
        for ev in self._events(use):
            with self._lock:
                phase = PHASE_FALLBACK if ev.fallback else PHASE_FIB
            self.observe(phase, eids=(ev.eid,), op=op, **attrs)
            sl = _SLO_HOOK
            if sl is not None:
                # End-cut on the TRACKER's clock (virtual in storms) —
                # the latency the convergence histogram itself records.
                sl.note_endcut(
                    ev.trigger, max(self._clock() - ev.t0, 0.0), ev.fallback
                )
            with self._lock:
                if self._open.pop(ev.eid, None) is not None:
                    to_close.append(ev)
        for ev in to_close:
            self._finish(ev, "converged")

    def sweep(self) -> int:
        """Close every still-open event (storm settle / shutdown): no
        histogram observation — an event that never touched the FIB is
        a no-op convergence-wise — but the timeline still flushes so
        the ring shows what it did do.  Returns the count closed."""
        with self._lock:
            evs = list(self._open.values())
            self._open.clear()
        for ev in evs:
            self._finish(ev, "no-fib")
        return len(evs)

    def _finish(self, ev: _Event, outcome: str) -> None:
        with self._lock:
            record = {
                "eid": ev.eid,
                "trigger": ev.trigger,
                "outcome": outcome,
                "fallback": ev.fallback,
                "dispatch": dict(ev.dispatch),
                "timeline": list(ev.timeline),
                "truncated": ev.truncated,
            }
            self._done.append(record)
            self._completed += 1
        cp = _CP_HOOK
        if cp is not None:
            cp.ev_done(ev.eid, outcome, ev.fallback)
        _CONV_EVENTS.labels(trigger=ev.trigger, outcome=outcome).inc()
        # Ring entry outside our lock (the flight recorder locks its
        # own ring); disarmed flight makes this a no-op.
        flight.event(
            "convergence",
            eid=ev.eid,
            trigger=ev.trigger,
            outcome=outcome,
            fallback=ev.fallback,
            phases=",".join(
                f"{s}@{t}" for s, t, _ in record["timeline"][:TIMELINE_LIMIT]
            ),
        )

    # -- queries

    def timelines(self) -> list[dict]:
        """Completed event records, oldest first (bench/test surface)."""
        with self._lock:
            return [dict(r) for r in self._done]

    def stats(self) -> dict:
        """holo-telemetry state-leaf view."""
        with self._lock:
            return {
                "open": len(self._open),
                "completed": self._completed,
                "capacity": self.capacity,
            }


# -- process-wide singleton + module-level seams ------------------------

_TRACKER: ConvergenceTracker | None = None


def _delivery_context(msg):
    """EventLoop delivery hook: re-establish the causal context of a
    message stamped with ``event_id`` (ibus envelopes, marshalled
    callbacks, storm-harness messages) for the handler's extent."""
    t = _TRACKER
    if t is None:
        return None
    eids = getattr(msg, "event_id", None)
    if not eids:
        return None
    if isinstance(eids, int):
        eids = (eids,)
    return t.activation(tuple(eids))


def configure(
    capacity: int = 0, clock=None
) -> ConvergenceTracker | None:
    """Arm (``capacity`` > 0) or disarm (0) the process-wide tracker and
    (un)install the runtime delivery-context hook.  The daemon calls
    this at boot from ``[telemetry] convergence-events``; bench and
    tests pass the loop clock for deterministic timelines."""
    global _TRACKER
    from holo_tpu.utils import runtime as _runtime

    if capacity and int(capacity) > 0:
        _TRACKER = ConvergenceTracker(int(capacity), clock or time.monotonic)
        _runtime.set_delivery_context(_delivery_context)
    else:
        _TRACKER = None
        _runtime.set_delivery_context(None)
    return _TRACKER


def tracker() -> ConvergenceTracker | None:
    return _TRACKER


def enabled() -> bool:
    return _TRACKER is not None


def begin(trigger: str, **attrs) -> int | None:
    """Origin stamp (no-op while disarmed)."""
    t = _TRACKER
    if t is None:
        return None
    return t.begin(trigger, **attrs)


def current() -> tuple[int, ...]:
    t = _TRACKER
    return t.current() if t is not None else ()


def active_triggers() -> tuple[str, ...]:
    t = _TRACKER
    return t.active_triggers() if t is not None else ()


def activation(eids):
    """Context manager activating ``eids`` (accepts None/empty)."""
    t = _TRACKER
    if t is None or not eids:
        return nullcontext()
    if isinstance(eids, int):
        eids = (eids,)
    return t.activation(tuple(eids))


def mark(step: str, eids=None, **attrs) -> None:
    t = _TRACKER
    if t is not None:
        t.mark(step, eids=eids, **attrs)


def note_dispatch(site: str, mode: str) -> None:
    t = _TRACKER
    if t is not None:
        t.note_dispatch(site, mode)


def observe(phase: str, eids=None, **attrs) -> None:
    t = _TRACKER
    if t is not None:
        t.observe(phase, eids=eids, **attrs)


def fib_commit(op: str = "install", eids=None, **attrs) -> None:
    t = _TRACKER
    if t is not None:
        t.fib_commit(op=op, eids=eids, **attrs)


def sweep() -> int:
    t = _TRACKER
    return t.sweep() if t is not None else 0


# -- protocol-instance helpers (the shared pend/drain contract) ---------

#: per-instance bound on causal ids pending on the next SPF run
PENDING_LIMIT = 256


def pend_schedule(pending: list, default_trigger: str, instance: str = "") -> None:
    """The SPF-schedule origin stamp every protocol instance shares:
    inherit the active causal ids (the schedule is part of a larger
    chain — a storm flap, a BFD notification) or begin a fresh event of
    ``default_trigger`` class, then park the ids on ``pending`` (the
    instance's bounded list) for the SPF run the delay FSM coalesces
    them into.  No-op while disarmed."""
    t = _TRACKER
    if t is None:
        return
    eids = t.current()
    if not eids:
        eids = (t.begin(default_trigger, instance=instance),)
    for e in eids:
        if e not in pending and len(pending) < PENDING_LIMIT:
            pending.append(e)
    t.mark("spf-scheduled", eids=eids, instance=instance)
    cp = _CP_HOOK
    if cp is not None:
        for e in eids:
            cp.ev_sched(e)


@contextmanager
def spf_run(pending: list, instance: str = ""):
    """Drain ``pending`` into an active causal context around one SPF
    run (route publishes inside capture the ids) and observe the
    ``spf`` phase on normal completion.  Yields the drained ids."""
    eids = tuple(pending)
    del pending[:]
    with activation(eids):
        yield eids
        if eids:
            observe(PHASE_SPF, eids=eids, instance=instance)

"""Telemetry as operational state: a read-only northbound provider
serving the registry under the ``holo-telemetry`` subtree, so gNMI
``Get``/``Subscribe`` (and the gRPC GetState path) see live metric
leaves with no extra plumbing — the ``_RuntimeStateProvider`` pattern.

Tree shape (walks into one gNMI update per leaf under PROTO encoding):

    holo-telemetry/
      metric[<name>]/            # list keyed by exposition name
        name                     # counter/gauge: bare family name;
        value                    #   histograms expand to _count/_sum
        labels                   # "k=v,k=v" ("" when label-less)
        exemplars                # histogram _count rows only: the
                                 #   OpenMetrics bucket exemplars
                                 #   ("le=<b>:span_id=<id>:value=<v>;...")
                                 #   Prometheus renders since PR 5 —
                                 #   the gNMI surface now carries the
                                 #   same span-id join keys
      health/                    # resilience summary (ISSUE 4)
        breakers/<name>/...      # dispatch-breaker state + failure tally
        supervision/...          # degraded actors, restart counts
      flight/                    # flight recorder (ISSUE 5; only while
        entries, capacity, dumps #   armed via flight-buffer-entries)
      spf-graph-cache/           # shared marshaled-graph cache (ISSUE 7):
        entries, capacity,       #   eviction/occupancy + DeltaPath chain
        evictions, deltas-...    #   state, next to the hit/miss counters
        sharded-entries, mesh,   #   + mesh placement (ISSUE 8): resident
        per-device/...           #   entries/rows/bytes per device
      gnmi-fanout/               # shared-delta fan-out engine (ISSUE 11):
        epoch, subscribers,      #   epoch id, cursor/bucket population,
        buckets, breaker, ...    #   breaker state + failure tally
      bgp-table/                 # device BGP plane (ISSUE 16): dispatch
        dispatches, fallbacks,   #   and fallback tallies, compiled shapes,
        tables/...               #   resident rows/cols + poisoned prefixes
      observatory/               # dispatch observatory (ISSUE 12; while
        sketches, observations,  #   armed): sketch population, sentinel
        sentinel/...             #   ledger + regressed keys, peak source
      relay/                     # TPU relay watch (ISSUE 12): last probe
        status, probes, ...      #   verdict, tally, last error
"""

from __future__ import annotations

from holo_tpu.northbound.provider import Provider as NbProvider

ROOT = "holo-telemetry"


class TelemetryStateProvider(NbProvider):
    """Read-only: owns no config subtree, vetoes nothing."""

    name = "telemetry"

    def __init__(self, registry=None):
        if registry is None:
            from holo_tpu import telemetry

            registry = telemetry.registry()
        self._registry = registry

    def filter_changes(self, changes):
        return []  # state-only: never part of a commit fan-out

    def get_state(self, path: str | None = None) -> dict:
        if path and not ROOT.startswith(path.split("/")[0]):
            return {}
        metrics = []
        for fam in self._registry.families():
            for key, child in fam.children():
                labels = ",".join(
                    f"{n}={v}" for n, v in zip(fam.labelnames, key)
                )
                if fam.kind == "histogram":
                    rows = [
                        (f"{fam.name}_count", child.count),
                        (f"{fam.name}_sum", round(child.sum, 9)),
                    ]
                else:
                    rows = [(fam.name, child.value)]
                exemplars = (
                    _exemplar_leaf(child) if fam.kind == "histogram" else ""
                )
                for name, value in rows:
                    entry = {
                        "name": f"{name}{{{labels}}}" if labels else name,
                        "value": value,
                        "labels": labels,
                    }
                    if exemplars and name.endswith("_count"):
                        # One leaf per histogram child (on the _count
                        # row): the bucket exemplars Prometheus has
                        # rendered since PR 5, now on the gNMI surface.
                        entry["exemplars"] = exemplars
                    metrics.append(entry)
        out = {"metric": metrics}
        health = _resilience_health()
        if health:
            out["health"] = health
        from holo_tpu.telemetry import flight

        rec = flight.recorder()
        if rec is not None:
            out["flight"] = rec.stats()
        from holo_tpu.telemetry import convergence

        tr = convergence.tracker()
        if tr is not None:
            out["convergence"] = tr.stats()
        # Lazy: the marshal cache pulls in jax — a daemon that never
        # dispatched device work should not pay the import at scrape
        # time, so the leaf appears once the engine module is loaded.
        import sys

        eng = sys.modules.get("holo_tpu.ops.spf_engine")
        if eng is not None:
            out["spf-graph-cache"] = eng.shared_graph_cache().stats()
        # Async dispatch pipeline + engine tuner (ISSUE 9): the leaf
        # appears once the pipeline package is armed (same lazy
        # discipline — an unarmed daemon pays nothing at scrape time).
        disp = sys.modules.get("holo_tpu.pipeline.dispatch")
        if disp is not None:
            # Bind once: a concurrent reset_process_pipeline() between
            # a check and a second lookup must not crash the scrape.
            pipe = disp.process_pipeline()
            if pipe is not None:
                out["pipeline"] = pipe.stats()
        tun = sys.modules.get("holo_tpu.pipeline.tuner")
        if tun is not None:
            tuner = tun.active_tuner()
            if tuner is not None:
                out["engine-tuner"] = tuner.stats()
        # Shared-delta gNMI fan-out (ISSUE 11): epoch / bucket /
        # breaker stats, one entry per live engine (same lazy
        # discipline — a daemon that never served a stream pays
        # nothing at scrape time).  Get-only by contract: the engine
        # excludes this leaf from its own sampled store (delta.py
        # SELF_ROOT) so its epoch bookkeeping cannot feed back into
        # the change-set it is diffing.
        # Device BGP table (ISSUE 16): Adj-RIB-In plane residency and
        # dispatch/fallback tallies, one entry per live backend (same
        # lazy discipline — scalar-only daemons never import the module).
        bgm = sys.modules.get("holo_tpu.ops.bgp_table")
        if bgm is not None:
            rows = bgm.backends_stats()
            if rows:
                out["bgp-table"] = rows[0] if len(rows) == 1 else rows
        fan = sys.modules.get("holo_tpu.telemetry.delta")
        if fan is not None:
            rows = fan.engines_stats()
            if rows:
                out["gnmi-fanout"] = rows[0] if len(rows) == 1 else rows
        # Dispatch observatory (ISSUE 12): sketch population, sentinel
        # ledger state, roofline peak source — present while armed.
        obsm = sys.modules.get("holo_tpu.telemetry.observatory")
        if obsm is not None:
            ob = obsm.active()
            if ob is not None:
                out["observatory"] = ob.stats()
        # Critical-path ledger (ISSUE 17): per-phase trigger→FIB
        # quantiles, bound-verdict tally, host-fraction — while armed.
        cpm = sys.modules.get("holo_tpu.telemetry.critpath")
        if cpm is not None:
            cp = cpm.active()
            if cp is not None:
                out["critical-path"] = cp.stats()
        # SLO plane (ISSUE 20): per-objective burn/budget/sentinel
        # state — while armed; the canary prober's attribution tallies
        # ride the same leaf when one is standing.
        slm = sys.modules.get("holo_tpu.telemetry.slo")
        if slm is not None:
            sl = slm.active()
            if sl is not None:
                out["slo"] = sl.stats()
                cam = sys.modules.get("holo_tpu.telemetry.canary")
                if cam is not None:
                    pr = cam.active()
                    if pr is not None:
                        out["slo"]["canary"] = pr.stats()
        # Device-residency byte ledger (ISSUE 17 satellite): per-plane
        # resident bytes — present once any device subsystem loaded
        # (the module itself stays lazy like the leaves it sums).
        resm = sys.modules.get("holo_tpu.telemetry.residency")
        if resm is not None:
            rs = resm.snapshot()
            if rs.get("total-bytes") or any(
                r["entries"] for r in rs["planes"].values()
            ):
                out["device-residency"] = rs
        # TPU relay watch (ISSUE 12 satellite): probe verdicts become
        # queryable state instead of a log file nobody reads in-process.
        relm = sys.modules.get("holo_tpu.telemetry.relay")
        if relm is not None:
            rs = relm.stats()
            if rs.get("probes") or rs.get("status") != "unknown":
                out["relay"] = rs
        return {ROOT: out}


def _exemplar_leaf(hist) -> str:
    """Compact scalar rendering of a histogram child's OpenMetrics
    bucket exemplars: ``le=<bucket>:<k>=<v>:value=<obs>`` joined by
    ``;`` in ascending bucket order (a gNMI leaf carries one scalar —
    the span-id join key is what matters)."""
    out = []
    for le, (pairs, value) in sorted(hist.exemplars().items()):
        le_s = "+Inf" if le == float("inf") else f"{le:g}"
        kv = ":".join(f"{k}={v}" for k, v in pairs)
        out.append(f"le={le_s}:{kv}:value={value:g}")
    return ";".join(out)


def _resilience_health() -> dict:
    """Breaker + supervision summary — the health leaf an operator (or
    an alerting pipeline subscribed over gNMI) watches instead of
    deriving state from raw counters."""
    from holo_tpu.resilience import health_snapshot

    return health_snapshot()

"""Dispatch observatory: always-on roofline attribution, streaming
quantile sketches, and an online perf-regression sentinel (ISSUE 12).

The next kernel arc (tropical min-plus SPF, hierarchical partitioning —
ROADMAP items 1-2) is graded observationally: "cost_analysis() shows
the flops moving from gather bytes to contraction flops".  Until now
that evidence existed only as one-shot ``bench.py`` rows.  This module
is the always-on instrument every subsequent kernel PR reports through:

- **Streaming quantile sketches** — DDSketch-style relative-error
  buckets (:class:`DDSketch`): each value lands in the log-spaced
  bucket ``ceil(log_gamma(v))`` with ``gamma = (1+alpha)/(1-alpha)``,
  so any quantile estimate is within ``alpha`` relative error of the
  true sample quantile.  Sketches are **deterministic** (no sampling),
  **mergeable** (bucket-count addition — fleet aggregation composes),
  and **bounded** (``max_bins`` with lowest-bucket collapse).  One
  sketch per key ``(site, stage, engine, shape-bucket⊃mesh, kind)``,
  fed from the existing ``holo_profile_stage_seconds`` observe path
  (:func:`holo_tpu.telemetry.profiling.stage`) behind ``[telemetry]
  observatory``: the armed hot path pays one dict hit + int adds per
  sub-span, the disarmed path ONE module-global check, and — by design
  — **no new locks**: sketch updates ride the same GIL-atomic
  dict/int discipline as the registry's write stamp (racing observers
  may coalesce an increment; quantile estimates already carry the
  sketch's own ``alpha`` envelope, which dominates).

- **Roofline attribution** — :meth:`Observatory.roofline` joins the
  compile-time ``cost_analysis()`` FLOP / bytes-accessed estimates per
  fresh (engine, shape) jit bucket (the backends call
  :func:`note_cost` right where they feed ``EngineTuner.cost_prior``)
  with the measured ``device`` sub-span sketch into achieved FLOP/s,
  bytes/s, arithmetic intensity, and a memory-/compute-bound verdict
  per bucket.  The verdict is the classic ridge-point test — AI below
  ``peak_flops / peak_bytes`` ⇒ the kernel CANNOT be compute-bound on
  that machine — so it is deterministic (compile-time numerators,
  configured peaks), while the achieved-rate rows carry the measured
  p50.  Peaks come from ``[telemetry] roofline-peaks``; the default is
  an honest CPU guess labeled ``relay: not-used`` until the TPU relay
  returns with real specs.

- **Online regression sentinel** — every ``check_every`` observations
  of a key, its sketch p50/p99 are compared against a persisted
  runtime baseline with the exact ``BENCH_baseline.json`` ledger
  discipline: unseen keys are SEEDED from the current run, >10% drift
  (plus a small absolute floor) flags a regression — a warn-only
  flight-ring event (``observatory-regression``) plus
  ``holo_observatory_regressions_total{bucket,quantile}`` — and >5%
  improvements RATCHET the baseline down.  Never a breaker, never a
  fallback: the DeltaPath-style incremental paths make regressions
  easy to hide inside warm medians, and the sentinel's only job is to
  make them loud.

Surfaces: ``holo-tpu-tools explain`` (top-k cost centers + roofline
fractions + the tuner's win/loss ledger), the
``holo-telemetry/observatory`` gNMI leaf
(:mod:`holo_tpu.telemetry.provider`), the Prometheus families above,
and ``bench.py explain_spf`` / ``observatory_overhead``.

Determinism: :class:`DeterministicTimer` swaps the profiling stage
timer for a counter clock (each read advances a fixed quantum), so a
seeded workload produces **byte-identical** sketch serializations and
reports across runs — the classification/structure signal stays real
(cost-analysis numerators, bucket keys, verdicts); the walls become
read-counts and the report says so (``timing: deterministic``).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from dataclasses import dataclass
from pathlib import Path

from holo_tpu import telemetry
from holo_tpu.telemetry import flight, profiling

log = logging.getLogger("holo_tpu.telemetry")

#: sketch values at or below this are exact zeros (a stage wall of 0.0
#: only happens under a deterministic timer that was never advanced)
MIN_TRACKABLE = 1e-9

#: sentinel drift thresholds — the BENCH_baseline.json discipline:
#: >10% worse flags, >5% better ratchets, plus an absolute floor (the
#: same role as the ledger's +0.25 slack on percent gates).  The floor
#: is 5ms: below it live the async-launch overlap artifacts (a device
#: sub-span measures time-until-ready, so host work between launch and
#: sync makes small walls bimodal — 0.2ms vs 2.5ms on the same kernel)
#: and scheduler noise, both owned by the <2% paired-median bench
#: gates; the regressions the always-on sentinel exists for — injected
#: stalls, platform slowdowns, accidental recompile storms — move
#: dispatch-wall-scale quantiles by far more.
DRIFT_FLAG = 0.10
DRIFT_RATCHET = 0.05
DRIFT_FLOOR_S = 5e-3

_REGRESSIONS = telemetry.counter(
    "holo_observatory_regressions_total",
    "Sketch-bucket quantiles that drifted >10% past the persisted "
    "runtime baseline (warn-only; ledger-seeded keys never flag on "
    "their seeding run)",
    ("bucket", "quantile"),
)
# Population gauges update from the sentinel tick / stats() only —
# stamped=False so observatory bookkeeping can never wake the gNMI
# fan-out's skip-the-walk short-circuit (the delta.py discipline).
_SKETCHES = telemetry.gauge(
    "holo_observatory_sketches",
    "Live (site, stage, engine, shape-bucket, kind) sketch keys",
    stamped=False,
)
_OBSERVATIONS = telemetry.gauge(
    "holo_observatory_observations",
    "Total stage observations folded into the sketches",
    stamped=False,
)


class DDSketch:
    """Relative-error streaming quantile sketch (DDSketch-style).

    ``quantile(q)`` is within ``alpha`` relative error of the true
    sample quantile; memory is bounded by ``max_bins`` (lowest buckets
    collapse together — the tail quantiles the sentinel watches keep
    full accuracy); two sketches with the same ``alpha`` merge by
    bucket-count addition, associatively and commutatively; and the
    whole state serializes to a canonical JSON document
    (:meth:`serialize`) that is byte-identical for identical
    observation multisets.  No locks: see the module docstring.
    """

    __slots__ = (
        "alpha", "max_bins", "_gamma", "_log_gamma",
        "bins", "zero", "count", "total", "vmin", "vmax",
    )

    def __init__(self, alpha: float = 0.01, max_bins: int = 512):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.max_bins = int(max_bins)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self._gamma)
        self.bins: dict[int, int] = {}
        self.zero = 0
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        if v < 0.0:
            v = 0.0  # durations; a clock step backwards clamps
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= MIN_TRACKABLE:
            self.zero += 1
            return
        i = math.ceil(math.log(v) / self._log_gamma)
        # Deliberately lock-free (ISSUE 12 contract: the dispatch hot
        # path gains no new locks): dict get/set on the GIL; a racing
        # observe may coalesce one count — inside the sketch's own
        # alpha error envelope, which dominates.
        self.bins[i] = self.bins.get(i, 0) + 1  # holo-lint: disable=HL204
        if len(self.bins) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        # Collapse the two LOWEST buckets together (tail accuracy is
        # what the p99 sentinel needs; the collapsed floor only ever
        # UNDER-reports how fast the fastest dispatches were).  Racing
        # collapses tolerate an already-popped bin (lock-free
        # contract): pop(lo, 0) + get(nxt, 0) never raise.
        idxs = sorted(self.bins)
        lo, nxt = idxs[0], idxs[1]
        self.bins[nxt] = self.bins.get(nxt, 0) + self.bins.pop(lo, 0)

    def _bucket_value(self, i: int) -> float:
        # Midpoint of bucket (gamma^(i-1), gamma^i]: within alpha
        # relative of every value the bucket holds.
        return 2.0 * self._gamma ** i / (self._gamma + 1.0)

    def quantile(self, q: float) -> float | None:
        """Estimate the ``q``-quantile (None on an empty sketch)."""
        if not self.count:
            return None
        rank = q * (self.count - 1)
        acc = self.zero
        if acc > rank:
            return 0.0
        # items() snapshot in one C call (GIL-atomic): a concurrent
        # observe/collapse can never fault the walk.
        for i, c in sorted(self.bins.items()):
            acc += c
            if acc > rank:
                return self._bucket_value(i)
        return float(self.vmax)

    def merge(self, other: "DDSketch") -> "DDSketch":
        """Fold ``other`` into self (same ``alpha`` required)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} != "
                f"{other.alpha}"
            )
        for i, c in other.bins.items():
            self.bins[i] = self.bins.get(i, 0) + c
        while len(self.bins) > self.max_bins:
            self._collapse()
        self.zero += other.zero
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        return self

    def to_doc(self) -> dict:
        """Canonical JSON-able state (sorted bins, rounded floats)."""
        return {
            "alpha": self.alpha,
            "zero": self.zero,
            "count": self.count,
            "sum": round(self.total, 9),
            "min": round(self.vmin, 9) if self.count else None,
            "max": round(self.vmax, 9) if self.count else None,
            "bins": [[i, self.bins[i]] for i in sorted(self.bins)],
        }

    @classmethod
    def from_doc(cls, doc: dict, max_bins: int = 512) -> "DDSketch":
        sk = cls(float(doc["alpha"]), max_bins)
        sk.zero = int(doc.get("zero", 0))
        sk.count = int(doc.get("count", 0))
        sk.total = float(doc.get("sum", 0.0))
        sk.vmin = float(doc["min"]) if doc.get("min") is not None else math.inf
        sk.vmax = (
            float(doc["max"]) if doc.get("max") is not None else -math.inf
        )
        sk.bins = {int(i): int(c) for i, c in doc.get("bins", [])}
        return sk

    def serialize(self) -> bytes:
        """Byte-identical canonical encoding of :meth:`to_doc`."""
        return json.dumps(
            self.to_doc(), sort_keys=True, separators=(",", ":")
        ).encode()


@dataclass(frozen=True)
class RooflinePeaks:
    """Per-backend peak specs the roofline verdict tests against.

    The default is an HONEST commodity-CPU guess — labeled ``relay:
    not-used`` exactly like the bench rows — because the TPU relay has
    been down since round 3 and inventing TPU peaks would classify
    every kernel compute-bound by fiat.  ``[telemetry] roofline-peaks``
    replaces it the day real specs matter.
    """

    flops_per_sec: float = 5.0e10  # ~50 GFLOP/s sustained scalar+SIMD
    bytes_per_sec: float = 1.0e10  # ~10 GB/s sustained DRAM stream
    source: str = "cpu-default (relay: not-used)"

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (flops/byte) where the machine stops
        being bandwidth-limited: AI below this ⇒ memory-bound."""
        return self.flops_per_sec / self.bytes_per_sec

    @classmethod
    def from_config(cls, raw) -> "RooflinePeaks":
        """``[telemetry] roofline-peaks`` table / dict / None."""
        if raw is None:
            return cls()
        if isinstance(raw, RooflinePeaks):
            return raw
        return cls(
            flops_per_sec=float(raw["flops"]),
            bytes_per_sec=float(raw["bytes"]),
            source=str(raw.get("name", "configured")),
        )


def key_str(key: tuple) -> str:
    """Canonical string form of a sketch key — the ledger key, the
    metric ``bucket`` label, and the report row id.  Square brackets
    are rendered as parens: the string rides gNMI list-key path
    segments (``metric[<name>{bucket=...}]``), whose grammar reserves
    ``[``/``]``."""
    site, stage, engine, bucket, kind = key
    b = (
        "-"
        if bucket in (None, "-")
        else json.dumps(list(bucket), separators=(",", ":"), default=str)
        .replace("[", "(")
        .replace("]", ")")
    )
    return f"{site}/{stage}|{engine}|{kind}|{b}"


class DeterministicTimer:
    """Counter clock for byte-identical observatory runs: every read
    advances ``quantum``, so stage walls count timer reads instead of
    wall time.  Install via ``profiling.set_stage_timer``; a seeded
    workload then produces identical sketches on every run."""

    def __init__(self, quantum: float = 1e-4):
        self.t = 0.0
        self.quantum = float(quantum)

    def __call__(self) -> float:
        self.t += self.quantum
        return self.t


class Observatory:
    """One process-wide instrument (module singleton via
    :func:`configure`).  Hot path = :meth:`_observe`, installed as the
    profiling stage observer; everything else is cold reporting."""

    def __init__(
        self,
        alpha: float = 0.01,
        max_bins: int = 512,
        check_every: int = 32,
        ledger_path: str | Path | None = None,
        peaks: RooflinePeaks | dict | None = None,
    ):
        self.alpha = float(alpha)
        self.max_bins = int(max_bins)
        self.check_every = int(check_every)
        self.peaks = RooflinePeaks.from_config(peaks)
        self.ledger_path = Path(ledger_path) if ledger_path else None
        self._sketches: dict[tuple, DDSketch] = {}
        self._costs: dict[tuple, dict] = {}
        # Sentinel state: the persisted quantile baseline plus the
        # per-(key, quantile) regressed latch (events fire on the
        # TRANSITION into regressed, not on every re-check).
        self._ledger: dict[str, dict] = {}
        self._regressed: dict[tuple, bool] = {}
        self._seeded = 0
        self._ratcheted = 0
        self._flags = 0
        self._n_obs = 0
        self._dirty = False
        if self.ledger_path is not None:
            self.load_ledger()

    # -- hot path (no locks; see module docstring) ----------------------

    def _observe(self, site: str, stage: str, device: str, seconds: float):
        """Profiling stage observer.  ``device != "-"`` rows are the
        per-device skew split of one already-observed sharded span —
        folding them in would double-count the dispatch."""
        if device != "-":
            return
        ctx = profiling.dispatch_ctx()
        if ctx is None:
            engine = kind = "-"
            bucket = "-"
        else:
            engine = ctx.get("engine", "-")
            kind = ctx.get("kind", "-")
            bucket = ctx.get("bucket") or "-"
        key = (site, stage, engine, bucket, kind)
        sk = self._sketches.get(key)
        if sk is None:
            # Lock-free by contract (see module docstring): setdefault
            # is atomic under the GIL, so two racing first-observers
            # both get the one surviving sketch.
            sk = self._sketches.setdefault(  # holo-lint: disable=HL204
                key, DDSketch(self.alpha, self.max_bins)
            )
        sk.observe(seconds)
        self._n_obs += 1
        if self.check_every and sk.count % self.check_every == 0:
            self._sentinel_check(key, sk)

    # -- cost join (called by the backends next to cost_prior) ----------

    def note_cost(
        self, site: str, kind: str, engine: str, bucket, entry: dict | None
    ) -> None:
        """Attach a compile-time ``cost_analysis()`` estimate for one
        (site, engine, shape-bucket, kind) — the roofline numerator."""
        if not entry:
            return
        # Lock-free single-key write (cold path — once per fresh XLA
        # compile); readers iterate a point-in-time view via list().
        self._costs[  # holo-lint: disable=HL204
            (site, str(engine), bucket or "-", str(kind))
        ] = {
            "flops": float(entry.get("flops", 0.0)),
            "bytes": float(entry.get("bytes", 0.0)),
        }

    # -- regression sentinel --------------------------------------------

    def _sentinel_check(self, key: tuple, sk: DDSketch) -> None:
        p50 = sk.quantile(0.5)
        p99 = sk.quantile(0.99)
        if p50 is None:
            return
        ks = key_str(key)
        ent = self._ledger.get(ks)
        if ent is None:
            self._ledger[ks] = {
                "p50": round(p50, 9), "p99": round(p99, 9)
            }
            self._seeded += 1
            self._dirty = True
            self._update_gauges()
            return
        dirty = False
        for qname, measured in (("p50", p50), ("p99", p99)):
            base = ent.get(qname)
            if base is None:
                ent[qname] = round(measured, 9)
                dirty = True
                continue
            floor = max(base * DRIFT_FLAG, DRIFT_FLOOR_S)
            regressed = measured > base + floor
            latch = (ks, qname)
            was = self._regressed.get(latch, False)
            if regressed and not was:
                # Lock-free latch write (sentinel tick, 1/check_every
                # observes): GIL-atomic bool flip; a racing reader of
                # sentinel() sees before-or-after, both valid.
                self._regressed[latch] = True  # holo-lint: disable=HL204
                self._flags += 1
                _REGRESSIONS.labels(bucket=ks, quantile=qname).inc()
                flight.event(
                    "observatory-regression",
                    bucket=ks,
                    quantile=qname,
                    baseline=round(base, 6),
                    measured=round(measured, 6),
                )
                log.warning(
                    "observatory: %s %s regressed %.3fms -> %.3fms "
                    "(baseline +%d%%) — warn-only, dispatch unaffected",
                    ks, qname, base * 1e3, measured * 1e3,
                    int(DRIFT_FLAG * 100),
                )
            elif not regressed:
                if was:
                    self._regressed[latch] = False
                if measured < base - max(
                    base * DRIFT_RATCHET, DRIFT_FLOOR_S
                ):
                    ent[qname] = round(measured, 9)
                    self._ratcheted += 1
                    dirty = True
        if dirty:
            self._dirty = True
        self._update_gauges()

    def checkpoint(self) -> dict:
        """Force one sentinel pass over every populated sketch — seed
        and compare NOW instead of at each key's next ``check_every``
        boundary.  The bench stages bracket their clean/regressed
        phases with it (a key whose count never crosses the modulo
        must still get a pre-regression baseline), and the daemon's
        stop path closes its final window the same way.  Returns
        :meth:`sentinel`."""
        for key, sk in list(self._sketches.items()):
            if sk.count:
                self._sentinel_check(key, sk)
        if self._dirty and self.ledger_path is not None:
            self.save_ledger()
        return self.sentinel()

    def _update_gauges(self) -> None:
        _SKETCHES.set(len(self._sketches))
        _OBSERVATIONS.set(self._n_obs)

    def load_ledger(self, path: str | Path | None = None) -> bool:
        """Load the persisted quantile baseline; a corrupt file is
        discarded (the sentinel just re-seeds — ledger discipline)."""
        p = Path(path) if path is not None else self.ledger_path
        if p is None or not p.exists():
            return False
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError) as e:
            log.warning("observatory ledger load from %s failed: %s", p, e)
            return False
        if not isinstance(doc, dict):
            return False
        self._ledger = {
            str(k): dict(v) for k, v in doc.items() if isinstance(v, dict)
        }
        return True

    def save_ledger(self, path: str | Path | None = None) -> bool:
        """Atomic (tmp + rename) write of the baseline; never raises —
        a full disk must not take a dispatch down."""
        p = Path(path) if path is not None else self.ledger_path
        if p is None:
            return False
        try:
            doc = json.dumps(self._ledger, sort_keys=True, indent=1)
            tmp = p.with_suffix(p.suffix + ".tmp")
            tmp.write_text(doc + "\n")
            os.replace(tmp, p)
            self._dirty = False
            return True
        except OSError as e:
            log.warning("observatory ledger save to %s failed: %s", p, e)
            return False

    # Seeds/ratchets only MARK the ledger dirty — the actual JSON
    # write happens at checkpoint boundaries (bench phase brackets,
    # daemon stop, explicit save_ledger), never as a synchronous disk
    # write on the dispatch thread that happened to seed a new key.

    # -- reporting (cold path) ------------------------------------------

    def quantiles(self, key: tuple) -> dict | None:
        sk = self._sketches.get(key)
        if sk is None or not sk.count:
            return None
        return {
            "count": sk.count,
            "total_s": round(sk.total, 9),
            "p50_s": round(sk.quantile(0.5), 9),
            "p99_s": round(sk.quantile(0.99), 9),
        }

    def site_p99(self, site: str) -> float | None:
        """Worst p99 seconds across every (stage, engine, shape-bucket,
        kind) sketch at ``site`` — the hung-dispatch watchdog's learned
        budget base (conservative by construction: a hang is declared
        only well past the slowest bucket's observed tail).  None while
        the site is cold."""
        worst = None
        # list() = one GIL-atomic snapshot (the cost_centers idiom).
        for key, sk in list(self._sketches.items()):
            if key[0] != site or not sk.count:
                continue
            q = sk.quantile(0.99)
            if worst is None or q > worst:
                worst = q
        return worst

    def cost_centers(self, top: int | None = None) -> list[dict]:
        """Sketch keys ranked by total attributed seconds — where the
        dispatch time actually went, with sketch-derived quantiles."""
        rows = []
        # list() = one GIL-atomic snapshot: dispatch threads keep
        # inserting sketch keys while a scrape renders.
        for key, sk in list(self._sketches.items()):
            if not sk.count:
                continue
            site, stage, engine, bucket, kind = key
            rows.append(
                {
                    "key": key_str(key),
                    "site": site,
                    "stage": stage,
                    "engine": engine,
                    "kind": kind,
                    "bucket": (
                        list(bucket) if isinstance(bucket, tuple) else bucket
                    ),
                    "count": sk.count,
                    "total_s": round(sk.total, 9),
                    "p50_s": round(sk.quantile(0.5), 9),
                    "p99_s": round(sk.quantile(0.99), 9),
                }
            )
        rows.sort(key=lambda r: (-r["total_s"], r["key"]))
        return rows[:top] if top else rows

    def roofline(self) -> list[dict]:
        """Per (site, engine, shape-bucket, kind): the cost-model join.

        Verdict = ridge-point test on the kernel's arithmetic intensity
        (deterministic); achieved rates divide the compile-time
        numerators by the measured device-stage sketch p50."""
        rows = []
        for (site, engine, bucket, kind), cost in list(self._costs.items()):
            flops, nbytes = cost["flops"], cost["bytes"]
            ai = flops / nbytes if nbytes else math.inf
            verdict = (
                "memory-bound" if ai < self.peaks.ridge else "compute-bound"
            )
            row = {
                "site": site,
                "engine": engine,
                "kind": kind,
                "bucket": (
                    list(bucket) if isinstance(bucket, tuple) else bucket
                ),
                "flops": flops,
                "bytes": nbytes,
                "ai_flops_per_byte": (
                    round(ai, 6) if math.isfinite(ai) else None
                ),
                "verdict": verdict,
                "peaks": self.peaks.source,
            }
            q = self.quantiles((site, "device", engine, bucket, kind))
            if q is not None and q["p50_s"] > 0:
                p50 = q["p50_s"]
                achieved_flops = flops / p50
                achieved_bytes = nbytes / p50
                # The bucket's attainable ceiling: bandwidth-capped
                # below the ridge, compute-capped above it.
                attainable = min(
                    self.peaks.flops_per_sec,
                    ai * self.peaks.bytes_per_sec,
                )
                row.update(
                    device_p50_s=p50,
                    device_p99_s=q["p99_s"],
                    dispatches=q["count"],
                    achieved_flops_per_sec=round(achieved_flops, 3),
                    achieved_bytes_per_sec=round(achieved_bytes, 3),
                    roofline_fraction=(
                        round(achieved_flops / attainable, 9)
                        if attainable
                        else None
                    ),
                )
            rows.append(row)
        rows.sort(
            key=lambda r: (r["site"], str(r["bucket"]), r["engine"], r["kind"])
        )
        return rows

    def sentinel(self) -> dict:
        regressed = sorted(
            f"{ks}:{q}"
            for (ks, q), on in list(self._regressed.items())
            if on
        )
        return {
            "ledger-entries": len(self._ledger),
            "seeded": self._seeded,
            "ratcheted": self._ratcheted,
            "flags": self._flags,
            "regressed": regressed,
            "path": str(self.ledger_path) if self.ledger_path else None,
        }

    def report(self, top: int | None = None) -> dict:
        """The full explain document (canonical field order)."""
        return {
            "timing": (
                "deterministic"
                if profiling.stage_timer_overridden()
                else "wall"
            ),
            "peaks": {
                "flops_per_sec": self.peaks.flops_per_sec,
                "bytes_per_sec": self.peaks.bytes_per_sec,
                "ridge_flops_per_byte": round(self.peaks.ridge, 6),
                "source": self.peaks.source,
            },
            "cost_centers": self.cost_centers(top),
            "roofline": self.roofline(),
            "sentinel": self.sentinel(),
        }

    def serialize(self) -> bytes:
        """Canonical byte encoding of every sketch — the byte-identity
        surface (two same-seed deterministic runs compare equal)."""
        doc = {
            key_str(k): sk.to_doc()
            for k, sk in list(self._sketches.items())
            if sk.count
        }
        return json.dumps(
            doc, sort_keys=True, separators=(",", ":")
        ).encode()

    def stats(self) -> dict:
        """holo-telemetry/observatory gNMI leaf."""
        self._update_gauges()
        return {
            "sketches": len(self._sketches),
            "observations": self._n_obs,
            "cost-buckets": len(self._costs),
            "alpha": self.alpha,
            "check-every": self.check_every,
            "peaks-source": self.peaks.source,
            "sentinel": self.sentinel(),
        }


# -- process-wide singleton ---------------------------------------------

_ACTIVE: Observatory | None = None
_CONFIG_LOCK = threading.Lock()


def configure(
    enabled: bool = True,
    *,
    alpha: float = 0.01,
    max_bins: int = 512,
    check_every: int = 32,
    ledger_path: str | Path | None = None,
    peaks: RooflinePeaks | dict | None = None,
) -> Observatory | None:
    """Arm (install the profiling stage observer) or disarm the
    process-wide observatory.  The daemon calls this at boot from
    ``[telemetry] observatory`` / ``observatory-ledger`` /
    ``roofline-peaks``; bench, the explain CLI, and tests flip it
    directly.  Disarming restores the one-global-check stage path."""
    global _ACTIVE
    with _CONFIG_LOCK:
        if not enabled:
            _ACTIVE = None
            profiling.set_observer(None)
            return None
        obs = Observatory(
            alpha=alpha,
            max_bins=max_bins,
            check_every=check_every,
            ledger_path=ledger_path,
            peaks=peaks,
        )
        _ACTIVE = obs
        profiling.set_observer(obs._observe)
        return obs


def active() -> Observatory | None:
    return _ACTIVE


def note_cost(
    site: str, kind: str, engine: str, bucket, entry: dict | None
) -> None:
    """Backend seam: forward a fresh-compile cost entry when armed."""
    obs = _ACTIVE
    if obs is not None:
        obs.note_cost(site, kind, engine, bucket, entry)

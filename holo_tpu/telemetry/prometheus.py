"""Prometheus text exposition (format 0.0.4) + stdlib HTTP endpoint.

No prometheus_client dependency: the renderer walks the registry and
emits ``# HELP`` / ``# TYPE`` blocks with histogram ``_bucket``/``_sum``
/``_count`` expansion; the endpoint is a ThreadingHTTPServer on a
daemon thread serving ``GET /metrics`` (anything else: 404).  Started
from the daemon behind the ``[telemetry]`` config section.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("holo_tpu.telemetry")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# Exemplars are an OpenMetrics feature: the classic 0.0.4 grammar allows
# only `value [timestamp]` after the labels, so a 0.0.4 scrape must
# never see them.  The endpoint renders them only when the scraper
# advertises OpenMetrics in its Accept header (Prometheus does when
# configured for it), and then also serves this content type + `# EOF`.
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _labelstr(names, values, extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _exemplar_str(ex: tuple) -> str:
    """OpenMetrics exemplar suffix: `` # {k="v"} value``, rendered on
    histogram ``_bucket`` lines whose bucket holds one
    (:meth:`Histogram.observe` with ``exemplar=``)."""
    pairs, value = ex
    labels = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return f" # {{{labels}}} {_fmt_value(value)}"


def render_text(registry, openmetrics: bool = False) -> str:
    """The whole registry in Prometheus exposition format.

    ``openmetrics=True`` additionally renders histogram-bucket
    exemplars and the terminating ``# EOF`` — valid only under the
    OpenMetrics content type, never on a 0.0.4 scrape (whose grammar
    would reject the exemplar suffix and fail the entire scrape)."""
    lines: list[str] = []
    for fam in registry.families():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        children = fam.children()
        if not children and not fam.labelnames:
            # A declared label-less family renders its zero value (a
            # scrape seeing the series exist beats a gap).
            children = [((), fam.labels())]
        for key, child in children:
            if fam.kind == "histogram":
                exemplars = child.exemplars() if openmetrics else {}
                for le, acc in child.cumulative():
                    ex = exemplars.get(le)
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labelstr(fam.labelnames, key, (('le', _fmt_value(le)),))}"
                        f" {acc}{_exemplar_str(ex) if ex else ''}"
                    )
                base = _labelstr(fam.labelnames, key)
                lines.append(f"{fam.name}_sum{base} {_fmt_value(child.sum)}")
                lines.append(f"{fam.name}_count{base} {child.count}")
            else:
                lines.append(
                    f"{fam.name}{_labelstr(fam.labelnames, key)} "
                    f"{_fmt_value(child.value)}"
                )
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    registry = None  # set on the subclass by start_http_server

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        # Content negotiation: exemplars only for scrapers that accept
        # OpenMetrics (a 0.0.4 parser would reject the whole scrape).
        openmetrics = "application/openmetrics-text" in self.headers.get(
            "Accept", ""
        )
        try:
            body = render_text(self.registry, openmetrics=openmetrics)
            if openmetrics:
                body += "# EOF\n"
            body = body.encode()
        except Exception:  # noqa: BLE001 — a scrape must not kill the server
            log.exception("metrics render failed")
            self.send_error(500)
            return
        self.send_response(200)
        self.send_header(
            "Content-Type",
            OPENMETRICS_CONTENT_TYPE if openmetrics else CONTENT_TYPE,
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes are not log-worthy
        pass


def start_http_server(registry, address: str) -> ThreadingHTTPServer:
    """Serve ``/metrics`` for ``registry`` on ``address`` ("host:port");
    returns the server (call ``.shutdown()`` to stop).  Port 0 picks a
    free port — read it back from ``server.server_address``."""
    host, _, port = address.rpartition(":")
    handler = type("MetricsHandler", (_Handler,), {"registry": registry})
    server = ThreadingHTTPServer((host or "127.0.0.1", int(port)), handler)
    server.daemon_threads = True
    thread = threading.Thread(
        target=server.serve_forever, name="telemetry-http", daemon=True
    )
    thread.start()
    return server

"""Lazy build + ctypes loader for the C++ native components.

The native pieces (scalar SPF baseline now; runtime core as it lands) are
compiled on first use into ``native/build/`` with g++ — no pip/cmake
dependency — and loaded via ctypes.  Rebuilds happen automatically when the
source is newer than the shared object.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
BUILD = NATIVE / "build"


def _ensure(so_name: str, sources: list[str], extra: list[str] | None = None) -> Path:
    BUILD.mkdir(parents=True, exist_ok=True)
    so = BUILD / so_name
    srcs = [NATIVE / s for s in sources]
    if so.exists() and all(so.stat().st_mtime >= s.stat().st_mtime for s in srcs):
        return so
    cmd = [
        "g++",
        "-O3",
        "-march=native",
        "-std=c++17",
        "-shared",
        "-fPIC",
        *(extra or []),
        *[str(s) for s in srcs],
        "-o",
        str(so),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr}"
        )
    return so


_spf_lib = None


def spf_baseline_lib() -> ctypes.CDLL:
    global _spf_lib
    if _spf_lib is None:
        lib = ctypes.CDLL(str(_ensure("libspf_baseline.so", ["spf_baseline.cpp"])))
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C")
        lib.holo_spf_scalar.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i32p,
            ctypes.c_void_p, ctypes.c_int32, i32p, i32p, i32p, u64p, u8p,
        ]
        lib.holo_spf_scalar.restype = None
        lib.holo_spf_scalar_batch.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i32p,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, i32p, u8p,
        ]
        lib.holo_spf_scalar_batch.restype = None
        _spf_lib = lib
    return _spf_lib


def native_spf(topo, edge_mask=None):
    """C++ scalar SPF: returns (dist, parent, hops, nh_u64) numpy arrays."""
    if topo.n_atoms() > 64:
        raise ValueError(
            f"native baseline supports <= 64 next-hop atoms, got {topo.n_atoms()}"
        )
    lib = spf_baseline_lib()
    n, e = topo.n_vertices, topo.n_edges
    dist = np.empty(n, np.int32)
    parent = np.empty(n, np.int32)
    hops = np.empty(n, np.int32)
    nh = np.empty(n, np.uint64)
    is_router = np.ascontiguousarray(topo.is_router, np.uint8)
    mask_p = None
    if edge_mask is not None:
        mask_arr = np.ascontiguousarray(edge_mask, np.uint8)
        mask_p = mask_arr.ctypes.data_as(ctypes.c_void_p)
    lib.holo_spf_scalar(
        n, e,
        np.ascontiguousarray(topo.edge_src),
        np.ascontiguousarray(topo.edge_dst),
        np.ascontiguousarray(topo.edge_cost),
        np.ascontiguousarray(topo.edge_direct_atom),
        mask_p, topo.root, dist, parent, hops, nh, is_router,
    )
    return dist, parent, hops, nh


_runtime_lib = None


def runtime_core_lib() -> ctypes.CDLL:
    """C++ runtime core: timer wheel, MPSC rings, epoll poller."""
    global _runtime_lib
    if _runtime_lib is None:
        lib = ctypes.CDLL(str(_ensure("libruntime_core.so", ["runtime_core.cpp"])))
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
        lib.holo_wheel_new.restype = ctypes.c_void_p
        lib.holo_wheel_free.argtypes = [ctypes.c_void_p]
        lib.holo_wheel_create.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.holo_wheel_create.restype = ctypes.c_int32
        lib.holo_wheel_arm.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
        lib.holo_wheel_cancel.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.holo_wheel_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.holo_wheel_advance.argtypes = [
            ctypes.c_void_p, ctypes.c_double, i64p, ctypes.c_int,
        ]
        lib.holo_wheel_advance.restype = ctypes.c_int
        lib.holo_ring_new.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
        lib.holo_ring_new.restype = ctypes.c_void_p
        lib.holo_ring_free.argtypes = [ctypes.c_void_p]
        lib.holo_ring_push.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32]
        lib.holo_ring_push.restype = ctypes.c_int
        lib.holo_ring_pop.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32]
        lib.holo_ring_pop.restype = ctypes.c_int
        lib.holo_poller_new.restype = ctypes.c_int
        lib.holo_poller_add.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_uint32]
        lib.holo_poller_del.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.holo_poller_wait.argtypes = [
            ctypes.c_int, ctypes.c_int, i32p, u32p, ctypes.c_int,
        ]
        lib.holo_poller_wait.restype = ctypes.c_int
        lib.holo_monotonic_now.restype = ctypes.c_double
        _runtime_lib = lib
    return _runtime_lib


def native_spf_batch_dist(topo, edge_masks) -> np.ndarray:
    """C++ serial what-if batch (distances only): the CPU baseline workload."""
    lib = spf_baseline_lib()
    n, e = topo.n_vertices, topo.n_edges
    b = edge_masks.shape[0]
    out = np.empty((b, n), np.int32)
    masks = np.ascontiguousarray(edge_masks, np.uint8)
    lib.holo_spf_scalar_batch(
        n, e,
        np.ascontiguousarray(topo.edge_src),
        np.ascontiguousarray(topo.edge_dst),
        np.ascontiguousarray(topo.edge_cost),
        np.ascontiguousarray(topo.edge_direct_atom),
        masks.ctypes.data_as(ctypes.c_void_p), b, topo.root, out,
        np.ascontiguousarray(topo.is_router, np.uint8),
    )
    return out

"""Provider-side interface: config callbacks keyed by schema path.

Reference: holo-northbound/src/configuration.rs (Prepare/Abort/Apply
:33-43, CallbacksBuilder :70, validation :90), state.rs, rpc.rs.
"""

from __future__ import annotations

import enum
import fnmatch
from dataclasses import dataclass, field
from typing import Any, Callable

from holo_tpu.yang.data import DiffOp


class CommitPhase(enum.Enum):
    PREPARE = "prepare"
    ABORT = "abort"
    APPLY = "apply"


class CommitError(Exception):
    """Raised by a provider in Prepare to veto a transaction."""


@dataclass
class Callbacks:
    """Path-pattern keyed callbacks.  Patterns use fnmatch over canonical
    paths with list keys stripped to '*': e.g.
    ``routing/control-plane-protocols/ospfv2/area[*]/interface[*]/cost``."""

    config: dict[str, Callable] = field(default_factory=dict)
    rpcs: dict[str, Callable] = field(default_factory=dict)
    state: dict[str, Callable] = field(default_factory=dict)

    def match_config(self, path: str) -> Callable | None:
        norm = _normalize(path)
        cb = self.config.get(norm)
        if cb is not None:
            return cb
        for pat, cb in self.config.items():
            if fnmatch.fnmatch(norm, pat):
                return cb
        return None


def _normalize(path: str) -> str:
    """Replace concrete list keys with '*': a/b[x]/c -> a/b[*]/c."""
    out = []
    depth = 0
    for ch in path:
        if ch == "[":
            depth += 1
            out.append("[*")
        elif ch == "]":
            depth -= 1
            out.append("]")
        elif depth == 0:
            out.append(ch)
    return "".join(out)


class Provider:
    """A northbound provider (base system component or protocol master).

    Lifecycle per transaction: validate(new_tree) on all providers; then
    Prepare fan-out (CommitError vetoes); Apply or Abort.  Providers see
    only the changes matching their subtree prefix.
    """

    name = "provider"
    subtree_prefixes: tuple[str, ...] = ()

    def callbacks(self) -> Callbacks:
        return Callbacks()

    def validate(self, new_tree) -> None:
        """Raise CommitError to reject the candidate."""

    def filter_changes(self, changes: list[DiffOp]) -> list[DiffOp]:
        if not self.subtree_prefixes:
            return changes
        return [
            c
            for c in changes
            if any(c.path.startswith(p) for p in self.subtree_prefixes)
        ]

    def commit(self, phase: CommitPhase, old_tree, new_tree, changes: list[DiffOp]) -> None:
        """Default: dispatch each change to a matching config callback."""
        cbs = self.callbacks()
        for change in changes:
            cb = cbs.match_config(change.path)
            if cb is not None:
                cb(phase, change, old_tree, new_tree)

    def get_state(self, path: str | None = None) -> dict:
        """Operational state subtree (merged into GetState responses)."""
        return {}

    def rpc(self, name: str, input: dict) -> dict:
        raise KeyError(f"unknown rpc {name}")

"""Northbound core: the two-phase-commit transaction engine.

Reference: holo-daemon/src/northbound/core.rs — create_transaction
(:393-491), per-provider commit fan-out (:495-539), confirmed-commit
rollback timer (:70-98,633-651), rollback log (:317-338, db.rs).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path

from holo_tpu.northbound.provider import CommitError, CommitPhase, Provider
from holo_tpu.yang.data import DataTree, diff_trees


@dataclass
class Transaction:
    id: int
    timestamp: float
    comment: str
    changes_json: str
    config_json: str  # full running config AFTER this transaction


class Northbound:
    """Owns the running config and serializes transactions across providers."""

    def __init__(self, schema, providers: list[Provider], db_path: Path | None = None):
        self.schema = schema
        self.providers = providers
        self.running = DataTree(schema)
        self.txn_log: list[Transaction] = []
        self._next_txn_id = 1
        self.db_path = db_path
        self._confirmed_pending: tuple[float, str] | None = None  # (deadline, prev cfg)
        if db_path is not None and db_path.exists():
            self._load_db()

    # -- transactions

    def commit(
        self,
        candidate: DataTree,
        comment: str = "",
        confirmed_timeout: float | None = None,
        now: float | None = None,
    ) -> Transaction:
        """Validate + two-phase commit the candidate config.

        Raises CommitError if any provider vetoes in validate/Prepare; the
        Abort fan-out restores provider state in that case.
        """
        now = time.time() if now is None else now
        for p in self.providers:
            p.validate(candidate)
        changes = diff_trees(self.running, candidate)
        if not changes:
            return self._record(comment, changes, now)

        prepared: list[tuple[Provider, list]] = []
        try:
            for p in self.providers:
                pch = p.filter_changes(changes)
                if pch:
                    p.commit(CommitPhase.PREPARE, self.running, candidate, pch)
                    prepared.append((p, pch))
        except CommitError:
            for p, pch in prepared:
                p.commit(CommitPhase.ABORT, self.running, candidate, pch)
            raise

        old_running = self.running
        for p, pch in prepared:
            p.commit(CommitPhase.APPLY, old_running, candidate, pch)
        self.running = candidate.copy()

        if confirmed_timeout is not None:
            self._confirmed_pending = (now + confirmed_timeout, old_running.to_json())
        return self._record(comment, changes, now)

    def confirm(self) -> None:
        """Confirm a pending confirmed-commit (cancels auto-rollback)."""
        self._confirmed_pending = None

    def check_confirmed_timeout(self, now: float) -> bool:
        """Roll back if a confirmed commit expired.  Returns True if rolled."""
        if self._confirmed_pending is None:
            return False
        deadline, prev_json = self._confirmed_pending
        if now < deadline:
            return False
        self._confirmed_pending = None
        prev = DataTree.from_json(self.schema, prev_json)
        self.commit(prev, comment="confirmed-commit rollback", now=now)
        return True

    def rollback(self, txn_id: int) -> Transaction:
        """Restore the configuration recorded by transaction ``txn_id``."""
        for txn in self.txn_log:
            if txn.id == txn_id:
                target = DataTree.from_json(self.schema, txn.config_json)
                return self.commit(target, comment=f"rollback to #{txn_id}")
        raise KeyError(f"no transaction {txn_id}")

    def get_transaction(self, txn_id: int) -> Transaction:
        for txn in self.txn_log:
            if txn.id == txn_id:
                return txn
        raise KeyError(f"no transaction {txn_id}")

    def _record(self, comment, changes, now) -> Transaction:
        txn = Transaction(
            id=self._next_txn_id,
            timestamp=now,
            comment=comment,
            changes_json=json.dumps(
                [
                    {"op": c.kind.value, "path": c.path, "value": str(c.value)}
                    for c in changes
                ]
            ),
            config_json=self.running.to_json(),
        )
        self._next_txn_id += 1
        self.txn_log.append(txn)
        self._save_db()
        return txn

    # -- operational state

    def get_state(self, path: str | None = None) -> dict:
        out: dict = {}
        for p in self.providers:
            sub = p.get_state(path)
            _deep_merge(out, sub)
        return out

    # -- persistence (pickledb equivalent: a JSON file)

    def _save_db(self) -> None:
        if self.db_path is None:
            return
        data = {
            "next_txn_id": self._next_txn_id,
            "transactions": [
                {
                    "id": t.id,
                    "timestamp": t.timestamp,
                    "comment": t.comment,
                    "changes": t.changes_json,
                    "config": t.config_json,
                }
                for t in self.txn_log[-32:]
            ],
        }
        self.db_path.write_text(json.dumps(data))

    def _load_db(self) -> None:
        data = json.loads(self.db_path.read_text())
        self._next_txn_id = data.get("next_txn_id", 1)
        self.txn_log = [
            Transaction(
                id=t["id"],
                timestamp=t["timestamp"],
                comment=t["comment"],
                changes_json=t["changes"],
                config_json=t["config"],
            )
            for t in data.get("transactions", [])
        ]


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v

"""Northbound framework: providers, callbacks, 3-phase transactions.

Reference: holo-northbound (configuration.rs 3-phase commit, state.rs
operational walks, rpc.rs) + holo-daemon/src/northbound/core.rs
(transaction engine, rollback, confirmed commit).
"""

from holo_tpu.northbound.core import Northbound, Transaction
from holo_tpu.northbound.provider import CommitPhase, Provider

__all__ = ["Northbound", "Transaction", "CommitPhase", "Provider"]

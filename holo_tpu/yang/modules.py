"""Schema module definitions mirroring the IETF models the reference
implements (holo-yang/modules/ietf/*): ietf-interfaces, ietf-routing with
per-protocol subtrees, ietf-system, ietf-key-chain, ietf-routing-policy.

These are our own declarative definitions shaped to the same northbound
paths; full YANG-text parsing is a later layer (see package docstring).
"""

from __future__ import annotations

from holo_tpu.yang.schema import C, L, Leaf, LeafList, Schema


def _leaf(name, type="string", **kw):
    return Leaf(name, type, **kw)


def interfaces_module():
    return C(
        "interfaces",
        L(
            "interface",
            "name",
            _leaf("name"),
            _leaf("description"),
            _leaf("type", "enum", enum=("ethernet", "loopback", "vlan", "macvlan")),
            _leaf("enabled", "boolean", default=True),
            _leaf("mtu", "uint16", default=1500),
            # 802.1Q subinterface config (reference holo-interface
            # encapsulation/dot1q-vlan + parent-interface,
            # northbound/configuration.rs:122-131): a "vlan"-typed
            # interface with both leaves is created via netlink.
            _leaf("parent-interface"),
            _leaf("vlan-id", "uint16"),
            LeafList("address", "ifaddr"),  # host addr + prefix length
        ),
    )


def system_module():
    return C(
        "system",
        _leaf("hostname"),
        _leaf("contact"),
        _leaf("location"),
    )


def keychains_module():
    return C(
        "key-chains",
        L(
            "key-chain",
            "name",
            _leaf("name"),
            L(
                "key",
                "key-id",
                _leaf("key-id", "uint32"),
                _leaf("key-string"),
                _leaf("crypto-algorithm", "enum",
                      enum=("md5", "hmac-sha-1", "hmac-sha-256", "hmac-sha-384",
                            "hmac-sha-512")),
                # ietf-key-chain lifetimes (RFC 8177): independent send
                # and accept windows make key rollover lossless
                # (reference holo-utils/src/keychain.rs:42-92).
                C(
                    "send-lifetime",
                    _leaf("start-date-time"),
                    _leaf("end-date-time"),
                ),
                C(
                    "accept-lifetime",
                    _leaf("start-date-time"),
                    _leaf("end-date-time"),
                ),
                C(
                    "lifetime",
                    C(
                        "send-accept-lifetime",
                        _leaf("start-date-time"),
                        _leaf("end-date-time"),
                    ),
                ),
            ),
        ),
    )


def routing_policy_module():
    # BGP augmentations mirror the reference's BgpMatchSets /
    # BgpPolicyCondition / BgpPolicyAction surface
    # (holo-utils/src/policy.rs:139-386).
    match_options = ("any", "all", "invert")

    def _cmp_cond(name):
        return C(name, _leaf("value", "uint32"),
                 _leaf("op", "enum", enum=("eq", "le", "ge")))

    def _set_comm(name):
        return C(
            name,
            _leaf("method", "enum", enum=("add", "remove", "replace")),
            LeafList("communities", "string"),
        )

    return C(
        "routing-policy",
        C(
            "defined-sets",
            L("prefix-set", "name", _leaf("name"), LeafList("prefix", "prefix")),
            L("tag-set", "name", _leaf("name"), LeafList("tag", "uint32")),
            L("neighbor-set", "name", _leaf("name"),
              LeafList("address", "string")),
            L("community-set", "name", _leaf("name"),
              LeafList("member", "string")),
            L("ext-community-set", "name", _leaf("name"),
              LeafList("member", "string")),
            L("large-community-set", "name", _leaf("name"),
              LeafList("member", "string")),
            L("as-path-set", "name", _leaf("name"),
              LeafList("member", "uint32")),
            L("next-hop-set", "name", _leaf("name"),
              LeafList("address", "string")),
        ),
        L(
            "policy-definition",
            "name",
            _leaf("name"),
            L(
                "statement",
                "name",
                _leaf("name"),
                C(
                    "conditions",
                    _leaf("match-prefix-set"),
                    _leaf("match-tag-set"),
                    _leaf("match-neighbor-set"),
                    _leaf("match-community-set"),
                    _leaf("community-match-options", "enum",
                          enum=match_options),
                    _leaf("match-ext-community-set"),
                    _leaf("ext-community-match-options", "enum",
                          enum=match_options),
                    _leaf("match-large-community-set"),
                    _leaf("large-community-match-options", "enum",
                          enum=match_options),
                    _leaf("match-as-path-set"),
                    _leaf("match-next-hop-set"),
                    _cmp_cond("med"),
                    _cmp_cond("local-pref"),
                    _cmp_cond("as-path-length"),
                    _cmp_cond("community-count"),
                    _leaf("origin-eq", "enum",
                          enum=("igp", "egp", "incomplete")),
                ),
                C(
                    "actions",
                    _leaf("policy-result", "enum",
                          enum=("accept-route", "reject-route")),
                    _leaf("set-metric", "uint32"),
                    _leaf("set-tag", "uint32"),
                    _leaf("set-local-pref", "uint32"),
                    _set_comm("set-community"),
                    _set_comm("set-ext-community"),
                    _set_comm("set-large-community"),
                    _leaf("set-route-origin", "enum",
                          enum=("igp", "egp", "incomplete")),
                    _leaf("set-next-hop", "string"),
                    C("set-med",
                      _leaf("set", "uint32"),
                      _leaf("add", "uint32"),
                      _leaf("subtract", "uint32")),
                    C("set-as-path-prepend",
                      _leaf("asn", "uint32"),
                      _leaf("repeat", "uint8")),
                ),
            ),
        ),
    )


def _spf_control():
    return C(
        "spf-control",
        _leaf("paths", "uint16", default=16),
        C(
            "ietf-spf-delay",
            _leaf("initial-delay", "uint32", default=50),
            _leaf("short-delay", "uint32", default=200),
            _leaf("long-delay", "uint32", default=5000),
            _leaf("hold-down", "uint32", default=10000),
            _leaf("time-to-learn", "uint32", default=500),
        ),
        _leaf("backend", "enum", enum=("scalar", "tpu"), default="scalar"),
    )


def _fast_reroute():
    """ietf-ospf/isis fast-reroute container + holo's remote-lfa /
    ti-lfa / engine extension leaves — the shape the routing provider's
    ``_frr_config`` consumes (providers.py).  No defaulted leaves: an
    untouched container stays absent, which means FRR disabled."""
    return C(
        "fast-reroute",
        _leaf("lfa", "boolean"),  # RFC 5286 (absent = true when set)
        _leaf("remote-lfa", "boolean"),  # RFC 7490
        _leaf("ti-lfa", "boolean"),  # requires SR
        _leaf("engine", "enum", enum=("scalar", "tpu")),
    )


def _ospf_subtree(name):
    return C(
        name,
        _leaf("router-id", "ip"),
        _leaf("enabled", "boolean", default=True),
        LeafList("redistribute", "string"),  # protocols to inject as type-5
        _spf_control(),
        _fast_reroute(),
        L(
            "area",
            "area-id",
            _leaf("area-id"),
            _leaf("area-type", "enum", enum=("normal", "stub", "nssa"),
                  default="normal"),
            _leaf("default-cost", "uint32", default=1),
            L(
                "interface",
                "name",
                _leaf("name"),
                _leaf("interface-type", "enum",
                      enum=("broadcast", "point-to-point"), default="broadcast"),
                _leaf("cost", "uint16", default=10),
                _leaf("hello-interval", "uint16", default=10),
                _leaf("dead-interval", "uint32", default=40),
                _leaf("retransmit-interval", "uint16", default=5),
                _leaf("priority", "uint8", default=1),
                _leaf("passive", "boolean", default=False),
                _leaf("bfd", "boolean", default=False),
                C(
                    "authentication",
                    _leaf("key-chain"),
                    _leaf("type", "enum",
                          enum=("none", "simple", "md5"), default="none"),
                    _leaf("key"),
                    # OSPFv3 (RFC 7166) inline-key parameters: the SA id
                    # carried in the authentication trailer + HMAC
                    # algorithm.  Ignored by OSPFv2.
                    _leaf("sa-id", "uint16", default=1),
                    _leaf("crypto-algorithm", "enum",
                          enum=("sha1", "sha256", "sha384", "sha512"),
                          default="sha256"),
                ),
            ),
        ),
    )


def _rip_subtree(name):
    return C(
        name,
        _leaf("enabled", "boolean", default=True),
        _leaf("update-interval", "uint16", default=30),
        _leaf("invalid-interval", "uint16", default=180),
        _leaf("flush-interval", "uint16", default=240),
        L("interface", "name", _leaf("name"),
          _leaf("cost", "uint8", default=1),
          _leaf("split-horizon", "enum",
                enum=("disabled", "simple", "poison-reverse"),
                default="poison-reverse"),
          # ietf-rip per-interface authentication (reference holo-rip
          # configuration.rs:309-339: key + crypto-algorithm); the
          # key-chain option resolves keys by lifetime.  RIPng (RFC
          # 2080) has no in-protocol auth — validate() rejects it there.
          C("authentication",
            _leaf("key"),
            _leaf("key-id", "uint32", default=1),
            _leaf("type", "enum", enum=("password", "md5"),
                  default="md5"),
            _leaf("key-chain"))),
    )


def _bgp_subtree():
    return C(
        "bgp",
        _leaf("as", "uint32"),
        _leaf("router-id", "ip"),
        # Real TCP sessions vs the in-memory test fabric.
        _leaf("transport", "enum", enum=("fabric", "tcp"), default="fabric"),
        _leaf("port", "uint16", default=179),
        L(
            "neighbor",
            "address",
            _leaf("address", "ip"),
            _leaf("peer-as", "uint32"),
            _leaf("hold-time", "uint16", default=90),
            _leaf("connect-retry-interval", "uint16", default=30),
            _leaf("import-policy"),
            _leaf("export-policy"),
            _leaf("authentication-key"),  # TCP-MD5 (RFC 2385)
            # GTSM (RFC 5082): expected hop budget; unset = disabled.
            _leaf("ttl-security", "uint8"),
            _leaf("tcp-mss", "uint16"),  # reference network.rs set_mss
        ),
        L(
            "network",
            "prefix",
            _leaf("prefix", "prefix"),  # locally originated route
        ),
    )


def _bfd_subtree():
    return C(
        "bfd",
        L(
            "session",
            "dest-addr",
            _leaf("dest-addr", "ip"),
            _leaf("source-addr", "ip"),
            _leaf("local-multiplier", "uint8", default=3),
            _leaf("desired-min-tx-interval", "uint32", default=1000000),
            _leaf("required-min-rx-interval", "uint32", default=1000000),
        ),
    )


def _vrrp_subtree():
    return C(
        "vrrp",
        L(
            "instance",
            "vrid",
            _leaf("vrid", "uint8"),
            _leaf("interface"),
            _leaf("version", "enum", enum=("2", "3"), default="3"),
            _leaf("priority", "uint8", default=100),
            _leaf("advertise-interval", "uint16", default=1),
            LeafList("virtual-address", "ip"),
        ),
    )


def _static_subtree():
    return C(
        "static-routes",
        L(
            "route",
            "prefix",
            _leaf("prefix", "prefix"),
            _leaf("next-hop", "ip"),
            _leaf("interface"),
            _leaf("metric", "uint32", default=0),
        ),
    )


def routing_module():
    """ietf-routing shaped: control-plane-protocols hosting each protocol."""
    return C(
        "routing",
        _leaf("router-id", "ip"),
        C(
            "control-plane-protocols",
            _ospf_subtree("ospfv2"),
            _ospf_subtree("ospfv3"),
            C("isis",
              _leaf("enabled", "boolean", default=True),
              _leaf("system-id"),
              _leaf("level", "enum", enum=("level-1", "level-2", "level-all"),
                    default="level-all"),
              _spf_control(),
              _fast_reroute(),
              # Instance-level LSP/SNP authentication (reference
              # holo-isis northbound configuration.rs:531-597: key-chain
              # OR inline key + key-id + crypto-algorithm).
              C("authentication",
                _leaf("key-chain"),
                _leaf("key"),
                _leaf("key-id", "uint32", default=1),
                _leaf("crypto-algorithm", "enum",
                      enum=("hmac-md5", "hmac-sha1", "hmac-sha256"),
                      default="hmac-md5")),
              L("interface", "name", _leaf("name"),
                _leaf("interface-type", "enum",
                      enum=("broadcast", "point-to-point"), default="broadcast"),
                _leaf("metric", "uint32", default=10),
                # Per-circuit hello authentication (reference
                # configuration.rs hello_auth paths).
                C("hello-authentication",
                  _leaf("key-chain"),
                  _leaf("key"),
                  _leaf("key-id", "uint32", default=1),
                  _leaf("crypto-algorithm", "enum",
                        enum=("hmac-md5", "hmac-sha1", "hmac-sha256"),
                        default="hmac-md5")))),
            _rip_subtree("ripv2"),
            _rip_subtree("ripng"),
            _bgp_subtree(),
            _bfd_subtree(),
            _vrrp_subtree(),
            C("igmp",
              L("interface", "name", _leaf("name"),
                _leaf("version", "uint8", default=2),
                _leaf("query-interval", "uint16", default=125))),
            C("ldp",
              _leaf("enabled", "boolean", default=True),
              _leaf("lsr-id"),
              _leaf("label-distribution-control", "enum",
                    enum=("independent", "ordered"),
                    default="independent"),
              L("interface", "name", _leaf("name"),
                _leaf("hello-interval", "uint16", default=5))),
            _static_subtree(),
        ),
    )


def full_schema() -> Schema:
    s = Schema()
    s.mount(interfaces_module())
    s.mount(system_module())
    s.mount(keychains_module())
    s.mount(routing_policy_module())
    s.mount(routing_module())
    return s

"""Schema node model: containers, lists, leaves with typed values.

Equivalent role to libyang's compiled schema (holo-yang); deliberately
small: the features the northbound engine needs — path resolution, type
checking, defaults, mandatory enforcement — not full YANG.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable


class SchemaError(Exception):
    pass


@dataclass
class Leaf:
    name: str
    type: str = "string"  # string|uint8|uint16|uint32|int32|boolean|ip|prefix|enum
    default: Any = None
    mandatory: bool = False
    enum: tuple[str, ...] = ()
    config: bool = True

    def check(self, value: Any) -> Any:
        t = self.type
        try:
            if t == "string":
                return str(value)
            if t in ("uint8", "uint16", "uint32", "int32"):
                v = int(value)
                lims = {
                    "uint8": (0, 0xFF),
                    "uint16": (0, 0xFFFF),
                    "uint32": (0, 0xFFFFFFFF),
                    "int32": (-(1 << 31), (1 << 31) - 1),
                }[t]
                if not lims[0] <= v <= lims[1]:
                    raise SchemaError(f"{self.name}: {v} out of range for {t}")
                return v
            if t == "boolean":
                if isinstance(value, bool):
                    return value
                return {"true": True, "false": False}[str(value).lower()]
            if t == "ip":
                from ipaddress import ip_address

                return ip_address(value)
            if t == "prefix":
                from ipaddress import ip_network

                return ip_network(value, strict=False)
            if t == "ifaddr":
                # interface address: host ip + prefix length preserved
                from ipaddress import ip_interface

                return ip_interface(value)
            if t == "enum":
                v = str(value)
                if v not in self.enum:
                    raise SchemaError(f"{self.name}: {v!r} not in {self.enum}")
                return v
        except SchemaError:
            raise
        except Exception as e:
            raise SchemaError(f"{self.name}: bad {t} value {value!r}: {e}") from e
        raise SchemaError(f"{self.name}: unknown type {t}")


@dataclass
class LeafList:
    name: str
    type: str = "string"
    config: bool = True

    def check(self, values) -> list:
        leaf = Leaf(self.name, self.type)
        return [leaf.check(v) for v in values]


@dataclass
class List:
    name: str
    key: str  # single key leaf name (compound keys via tuple-string later)
    children: dict[str, Any] = field(default_factory=dict)
    config: bool = True

    def child(self, name: str):
        c = self.children.get(name)
        if c is None:
            raise SchemaError(f"list {self.name}: no child {name!r}")
        return c


@dataclass
class Container:
    name: str
    children: dict[str, Any] = field(default_factory=dict)
    presence: bool = False
    config: bool = True

    def child(self, name: str):
        c = self.children.get(name)
        if c is None:
            raise SchemaError(f"container {self.name}: no child {name!r}")
        return c


def C(name: str, *children, presence=False, config=True) -> Container:
    return Container(name, {c.name: c for c in children}, presence, config)


def L(name: str, key: str, *children, config=True) -> List:
    return List(name, key, {c.name: c for c in children}, config)


_SEG = re.compile(r"([^/\[]+)(?:\[(?:[^=\]]+=)?([^\]]+)\])?")


@dataclass
class Schema:
    """A forest of top-level containers, addressable by slash paths."""

    roots: dict[str, Container] = field(default_factory=dict)

    def mount(self, root: Container) -> None:
        self.roots[root.name] = root

    def resolve(self, path: str):
        """Resolve 'a/b[key]/c' to the schema node (ignoring key values)."""
        segs = parse_path(path)
        if not segs:
            raise SchemaError("empty path")
        name0, _ = segs[0]
        node = self.roots.get(name0)
        if node is None:
            raise SchemaError(f"no module root {name0!r}")
        for name, _key in segs[1:]:
            if isinstance(node, (Container, List)):
                node = node.child(name)
            else:
                raise SchemaError(f"cannot descend into leaf at {name}")
        return node


def parse_path(path: str) -> list[tuple[str, str | None]]:
    """'a/b[k=v]/c' -> [('a', None), ('b', 'v'), ('c', None)].

    Splitting is bracket-aware: list keys may themselves contain slashes
    (e.g. ``static-routes/route[10.0.0.0/16]``).
    """
    segs: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in path.strip("/"):
        if ch == "/" and depth == 0:
            if cur:
                segs.append("".join(cur))
                cur = []
            continue
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        cur.append(ch)
    if cur:
        segs.append("".join(cur))
    out: list[tuple[str, str | None]] = []
    for seg in segs:
        m = _SEG.fullmatch(seg)
        if not m:
            raise SchemaError(f"bad path segment {seg!r}")
        out.append((m.group(1), m.group(2)))
    return out

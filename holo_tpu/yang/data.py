"""Instance data trees + structural diff.

The transaction engine diffs running vs candidate trees into an ordered
change list (equivalent of libyang's DataDiff driving
changes_from_diff, holo-daemon/src/northbound/core.rs:408-425).
"""

from __future__ import annotations

import copy
import enum
import json
from dataclasses import dataclass
from typing import Any

from holo_tpu.yang.schema import (
    Container,
    Leaf,
    LeafList,
    List,
    Schema,
    SchemaError,
    parse_path,
)


class DiffKind(enum.Enum):
    CREATE = "create"
    MODIFY = "modify"
    DELETE = "delete"


@dataclass(frozen=True)
class DiffOp:
    kind: DiffKind
    path: str  # canonical slash path with [key] segments
    value: Any = None


class DataTree:
    """Schema-validated nested-dict instance tree.

    Layout: containers -> dict, lists -> dict key-value -> entry dict,
    leaves -> scalar, leaf-lists -> list.
    """

    def __init__(self, schema: Schema, root: dict | None = None):
        self.schema = schema
        self.root: dict = root if root is not None else {}

    def copy(self) -> "DataTree":
        return DataTree(self.schema, copy.deepcopy(self.root))

    # -- editing

    def set(self, path: str, value: Any = None) -> None:
        """Set a leaf (value given) or create a container/list entry."""
        segs = parse_path(path)
        node, data = self._descend(segs[:-1], create=True)
        name, key = segs[-1]
        child = self._schema_child(node, name)
        if isinstance(child, Leaf):
            data[name] = child.check(value)
        elif isinstance(child, LeafList):
            data[name] = child.check(value if isinstance(value, list) else [value])
        elif isinstance(child, List):
            if key is None:
                raise SchemaError(f"list {name} requires [key]")
            entry = data.setdefault(name, {}).setdefault(key, {})
            key_leaf = child.child(child.key)
            entry[child.key] = key_leaf.check(key)
        elif isinstance(child, Container):
            data.setdefault(name, {})
        else:
            raise SchemaError(f"cannot set {path}")

    def delete(self, path: str) -> None:
        segs = parse_path(path)
        try:
            node, data = self._descend(segs[:-1], create=False)
        except KeyError:
            return
        name, key = segs[-1]
        child = self._schema_child(node, name)
        if isinstance(child, List) and key is not None:
            entries = data.get(name)
            if entries is not None:
                entries.pop(key, None)
                if not entries:
                    data.pop(name, None)
        else:
            data.pop(name, None)

    def get(self, path: str, default=None):
        segs = parse_path(path)
        try:
            _, data = self._descend(segs[:-1], create=False)
        except KeyError:
            return default
        name, key = segs[-1]
        val = data.get(name, default)
        if key is not None and isinstance(val, dict):
            return val.get(key, default)
        return val

    def _schema_child(self, node, name):
        if isinstance(node, (Container, List)):
            return node.child(name)
        raise SchemaError(f"cannot descend into {node}")

    def _descend(self, segs, create: bool):
        """Walk to the parent of the target, returning (schema_node, dict)."""
        if not segs:
            # top level: pseudo-container holding module roots
            class _Root:
                def child(_self, name):
                    c = self.schema.roots.get(name)
                    if c is None:
                        raise SchemaError(f"no module root {name!r}")
                    return c

            return _Root(), self.root
        name0, key0 = segs[0]
        node = self.schema.roots.get(name0)
        if node is None:
            raise SchemaError(f"no module root {name0!r}")
        data = self.root.setdefault(name0, {}) if create else self.root[name0]
        segs = segs[1:]
        cur_key = key0
        for name, key in segs:
            child = node.child(name)
            if isinstance(child, List):
                if key is None:
                    raise SchemaError(f"list {name} requires [key]")
                entries = data.setdefault(name, {}) if create else data[name]
                if create:
                    entry = entries.setdefault(key, {})
                    entry.setdefault(child.key, child.child(child.key).check(key))
                else:
                    entry = entries[key]
                node, data = child, entry
            elif isinstance(child, Container):
                data = data.setdefault(name, {}) if create else data[name]
                node = child
            else:
                raise SchemaError(f"cannot descend through leaf {name}")
        return node, data

    # -- serialization (ietf-json-shaped)

    def to_json(self) -> str:
        def enc(o):
            return str(o)

        return json.dumps(self.root, default=enc, sort_keys=True, indent=2)

    @classmethod
    def from_json(cls, schema: Schema, text: str) -> "DataTree":
        tree = cls(schema)
        raw = json.loads(text) if text.strip() else {}
        tree._load(raw)
        return tree

    def _load(self, raw: dict) -> None:
        """Validate a raw nested dict into the tree (used by from_json)."""

        def walk(snode, rdata, out):
            for name, val in rdata.items():
                child = snode.child(name)
                if isinstance(child, Leaf):
                    out[name] = child.check(val)
                elif isinstance(child, LeafList):
                    out[name] = child.check(val)
                elif isinstance(child, Container):
                    out[name] = {}
                    walk(child, val, out[name])
                elif isinstance(child, List):
                    out[name] = {}
                    for key, entry in val.items():
                        e = out[name].setdefault(key, {})
                        walk(child, entry, e)
                        e.setdefault(child.key, child.child(child.key).check(key))

        for root_name, val in raw.items():
            root = self.schema.roots.get(root_name)
            if root is None:
                raise SchemaError(f"no module root {root_name!r}")
            self.root[root_name] = {}
            walk(root, val, self.root[root_name])


def diff_trees(old: DataTree, new: DataTree) -> list[DiffOp]:
    """Ordered structural diff (creates parent-first, deletes child-first)."""
    ops: list[DiffOp] = []

    def walk(snode, opath, odata, ndata):
        names = list(dict.fromkeys(list(odata.keys()) + list(ndata.keys())))
        for name in names:
            child = snode.child(name)
            p = f"{opath}/{name}" if opath else name
            in_old, in_new = name in odata, name in ndata
            if isinstance(child, Leaf):
                if in_old and not in_new:
                    ops.append(DiffOp(DiffKind.DELETE, p, odata[name]))
                elif not in_old and in_new:
                    ops.append(DiffOp(DiffKind.CREATE, p, ndata[name]))
                elif odata[name] != ndata[name]:
                    ops.append(DiffOp(DiffKind.MODIFY, p, ndata[name]))
            elif isinstance(child, LeafList):
                if odata.get(name) != ndata.get(name):
                    kind = (
                        DiffKind.DELETE
                        if not in_new
                        else (DiffKind.CREATE if not in_old else DiffKind.MODIFY)
                    )
                    ops.append(DiffOp(kind, p, ndata.get(name)))
            elif isinstance(child, Container):
                if in_old and not in_new:
                    walk(child, p, odata[name], {})
                    ops.append(DiffOp(DiffKind.DELETE, p))
                elif not in_old and in_new:
                    ops.append(DiffOp(DiffKind.CREATE, p))
                    walk(child, p, {}, ndata[name])
                else:
                    walk(child, p, odata[name], ndata[name])
            elif isinstance(child, List):
                okeys = odata.get(name, {}) if in_old else {}
                nkeys = ndata.get(name, {}) if in_new else {}
                for key in dict.fromkeys(list(okeys.keys()) + list(nkeys.keys())):
                    ep = f"{p}[{key}]"
                    if key in okeys and key not in nkeys:
                        walk(child, ep, okeys[key], {})
                        ops.append(DiffOp(DiffKind.DELETE, ep))
                    elif key not in okeys and key in nkeys:
                        ops.append(DiffOp(DiffKind.CREATE, ep))
                        walk(child, ep, {}, nkeys[key])
                    else:
                        walk(child, ep, okeys[key], nkeys[key])

    class _Root:
        def child(_self, name):
            c = old.schema.roots.get(name)
            if c is None:
                raise SchemaError(f"no module root {name!r}")
            return c

    walk(_Root(), "", old.root, new.root)
    return ops

"""YANG text front-end (RFC 7950 subset) for the YANG-lite schema.

The reference loads its 104 modules through libyang; this parser covers
the statement subset those modules actually use for CONFIG modeling —
module/container/list/leaf/leaf-list, types (integers, string, boolean,
enumeration, inet addresses/prefixes), key, default, mandatory, config,
presence, typedef (one-level resolution), grouping/uses — and maps them
onto the same :mod:`holo_tpu.yang.schema` nodes the built-in modules
use, so a parsed module mounts and validates identically.

Augment and deviation statements are APPLIED across the module set
(load_modules grafts augments onto foreign trees to a fixpoint, then
prunes/retypes per deviations — the libyang context-load behavior).
Statements that do not affect config-tree shape (description, reference,
namespace, prefix, import, revision, organization, contact, notification,
rpc, when, must, status, units, yang-version, ordered-by...) are parsed
and skipped.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from holo_tpu.yang.schema import Container, Leaf, LeafList, List, SchemaError


@dataclass
class Stmt:
    """One YANG statement: ``keyword [argument] { substatements }``."""

    keyword: str
    arg: str | None
    subs: list = field(default_factory=list)

    def sub(self, keyword: str) -> "Stmt | None":
        for s in self.subs:
            if s.keyword == keyword:
                return s
        return None

    def all(self, keyword: str) -> list:
        return [s for s in self.subs if s.keyword == keyword]


class YangParseError(SchemaError):
    pass


def _tokenize(text: str) -> list[str]:
    """Tokens: quoted strings (with ``+`` concatenation handled by the
    parser), ``{``, ``}``, ``;`` and bare words."""
    out: list[str] = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in " \t\r\n":
            i += 1
        elif text.startswith("//", i):
            i = text.find("\n", i)
            i = n if i < 0 else i
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise YangParseError("unterminated comment")
            i = j + 2
        elif ch in "\"'":
            j = i + 1
            buf = []
            while j < n and text[j] != ch:
                if ch == '"' and text[j] == "\\" and j + 1 < n:
                    esc = text[j + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}
                               .get(esc, esc))
                    j += 2
                else:
                    buf.append(text[j])
                    j += 1
            if j >= n:
                raise YangParseError("unterminated string")
            out.append('"' + "".join(buf))  # marker prefix: quoted token
            i = j + 1
        elif ch in "{};":
            out.append(ch)
            i += 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n{};\"'":
                j += 1
            out.append(text[i:j])
            i = j
    return out


def _parse_stmts(tokens: list[str], pos: int) -> tuple[list, int]:
    stmts: list[Stmt] = []
    while pos < len(tokens) and tokens[pos] != "}":
        kw = tokens[pos]
        if kw.startswith('"'):
            raise YangParseError(f"unexpected string where keyword expected")
        pos += 1
        # Argument: bare word or quoted string(s) joined by '+'.
        arg = None
        if pos < len(tokens) and tokens[pos] not in "{};":
            parts = []
            while True:
                t = tokens[pos]
                parts.append(t[1:] if t.startswith('"') else t)
                pos += 1
                if pos < len(tokens) and tokens[pos] == "+":
                    pos += 1
                    continue
                break
            arg = "".join(parts)
        if pos >= len(tokens):
            raise YangParseError(f"{kw}: missing terminator")
        if tokens[pos] == ";":
            stmts.append(Stmt(kw, arg))
            pos += 1
        elif tokens[pos] == "{":
            subs, pos = _parse_stmts(tokens, pos + 1)
            if pos >= len(tokens) or tokens[pos] != "}":
                raise YangParseError(f"{kw}: missing closing brace")
            stmts.append(Stmt(kw, arg, subs))
            pos += 1
        else:
            raise YangParseError(f"{kw}: expected ';' or '{{'")
    return stmts, pos


def parse_text(text: str) -> Stmt:
    """Parse YANG text into a statement tree (module or submodule)."""
    tokens = _tokenize(text)
    stmts, pos = _parse_stmts(tokens, 0)
    if pos != len(tokens):
        raise YangParseError("trailing tokens after module")
    if len(stmts) != 1 or stmts[0].keyword not in ("module", "submodule"):
        raise YangParseError("expected exactly one module statement")
    return stmts[0]


# YANG type -> schema-lite type.
_TYPE_MAP = {
    "string": "string",
    "uint8": "uint8",
    "uint16": "uint16",
    "uint32": "uint32",
    "int32": "int32",
    "boolean": "boolean",
    "inet:ip-address": "ip",
    "inet:ipv4-address": "ip",
    "inet:ipv6-address": "ip",
    "inet:ip-prefix": "prefix",
    "inet:ipv4-prefix": "prefix",
    "inet:ipv6-prefix": "prefix",
    # Best-effort mappings: validated downstream where it matters.
    "union": "string",
    "identityref": "string",
    "yang:dotted-quad": "string",
    "inet:domain-name": "string",
    "uint64": "uint32",
    "int64": "int32",
    "uint": "uint32",
    "binary": "string",
    "empty": "boolean",
}


class _Builder:
    def __init__(self, module: Stmt, shared: "dict | None" = None):
        """``shared``: cross-module grouping/typedef namespaces (bare
        names) built by :func:`load_modules` — the import-resolution
        analog of libyang's module set."""
        self.module = module
        self.shared = shared or {"groupings": {}, "typedefs": {}}
        self.typedefs: dict[str, tuple[str, tuple]] = {}  # name -> (type, enum)
        self.groupings: dict[str, Stmt] = {}
        for td in module.all("typedef"):
            t = td.sub("type")
            if t is not None:
                base, enum = self._resolve_type(t)
                self.typedefs[td.arg] = (base, enum)
        for g in module.all("grouping"):
            self.groupings[g.arg] = g

    def _resolve_type(self, t: Stmt) -> tuple[str, tuple]:
        name = t.arg or "string"
        if name == "enumeration":
            return "enum", tuple(e.arg for e in t.all("enum"))
        if name in self.typedefs:
            return self.typedefs[name]
        # Strip an unknown prefix: "foo:bar" -> try the mapped full name
        # first, then bare "bar" as a local typedef.
        mapped = _TYPE_MAP.get(name)
        if mapped is not None:
            return mapped, ()
        bare = name.split(":")[-1]
        if bare in self.typedefs:
            return self.typedefs[bare]
        if bare in self.shared["typedefs"]:
            return self.shared["typedefs"][bare]
        return _TYPE_MAP.get(bare, "string"), ()

    def _children(self, stmt: Stmt, config: bool) -> list:
        out = []
        for s in stmt.subs:
            node = self._node(s, config)
            if node is not None:
                out.append(node)
            elif s.keyword == "uses":
                bare = s.arg.split(":")[-1]
                g = (
                    self.groupings.get(s.arg)
                    or self.groupings.get(bare)
                    or self.shared["groupings"].get(bare)
                )
                if g is None:
                    raise YangParseError(f"uses {s.arg}: unknown grouping")
                out.extend(self._children(g, config))
        return out

    def _config(self, stmt: Stmt, inherited: bool) -> bool:
        c = stmt.sub("config")
        if c is None:
            return inherited
        return c.arg == "true"

    def _node(self, s: Stmt, config: bool):
        if s.keyword == "container":
            cfg = self._config(s, config)
            return Container(
                s.arg,
                {c.name: c for c in self._children(s, cfg)},
                presence=s.sub("presence") is not None,
                config=cfg,
            )
        if s.keyword == "list":
            cfg = self._config(s, config)
            key = s.sub("key")
            # Compound keys: schema-lite addresses lists by their first
            # key leaf (the reference's config lists are single-keyed).
            key_name = (key.arg.split()[0] if key is not None and key.arg
                        else "name")
            return List(
                s.arg, key_name,
                {c.name: c for c in self._children(s, cfg)},
                config=cfg,
            )
        if s.keyword == "leaf":
            cfg = self._config(s, config)
            t = s.sub("type")
            base, enum = (
                self._resolve_type(t) if t is not None else ("string", ())
            )
            default = s.sub("default")
            mandatory = s.sub("mandatory")
            leaf = Leaf(
                s.arg, base,
                enum=enum,
                mandatory=mandatory is not None and mandatory.arg == "true",
                config=cfg,
            )
            if default is not None:
                leaf.default = leaf.check(default.arg)
            return leaf
        if s.keyword == "leaf-list":
            t = s.sub("type")
            base, _enum = (
                self._resolve_type(t) if t is not None else ("string", ())
            )
            return LeafList(s.arg, base, config=self._config(s, config))
        return None  # non-data statement: skipped (or 'uses', see caller)


def build_module(module: Stmt, shared: dict | None = None) -> list:
    """Statement tree -> top-level schema nodes (mountable containers)."""
    return _Builder(module, shared)._children(module, config=True)


def load_yang(text: str) -> list:
    """YANG text -> mountable schema nodes (the libyang-load analog)."""
    return build_module(parse_text(text))


def load_modules(texts: list[str]) -> dict[str, list]:
    """Parse a whole module SET with cross-module grouping/typedef
    resolution (imports resolve by bare name, like libyang's context):
    {module name: top-level schema nodes}."""
    modules = [parse_text(t) for t in texts]
    shared: dict = {"groupings": {}, "typedefs": {}}

    def collect(stmt):
        for s in stmt.subs:
            if s.keyword == "grouping":
                shared["groupings"].setdefault(s.arg, s)
            collect(s)

    for m in modules:
        collect(m)
    # Typedefs need per-module resolution first (they may chain).
    for m in modules:
        b = _Builder(m, shared)
        for name, resolved in b.typedefs.items():
            shared["typedefs"].setdefault(name, resolved)
    trees = {m.arg: build_module(m, shared) for m in modules}
    apply_augments(trees, modules, shared)
    apply_deviations(trees, modules, shared)
    return trees


def _prefix_map(module: Stmt) -> dict[str, str]:
    """prefix -> module-name for a module's own prefix + its imports."""
    out: dict[str, str] = {}
    own = module.sub("prefix")
    if own is not None:
        out[own.arg] = module.arg
    for imp in module.all("import"):
        p = imp.sub("prefix")
        if p is not None:
            out[p.arg] = imp.arg
    return out


def _resolve_target(trees: dict, prefixes: dict, path: str):
    """Resolve an augment/deviation absolute schema path.

    Returns (parent, name, node) where ``parent`` is the containing node
    (or the target module's root list for top-level targets) — or None
    when any component crosses a statement we don't model (choice/case,
    notification bodies, ...)."""
    comps = [c for c in path.strip("/").split("/") if c]
    if not comps:
        return None
    first = comps[0]
    if ":" not in first:
        return None
    pref, name = first.split(":", 1)
    mod = prefixes.get(pref)
    roots = trees.get(mod)
    if roots is None:
        return None
    node = next((r for r in roots if getattr(r, "name", None) == name), None)
    if node is None:
        return None
    parent: object = roots
    for comp in comps[1:]:
        cname = comp.split(":", 1)[1] if ":" in comp else comp
        children = getattr(node, "children", None)
        if children is None or cname not in children:
            return None
        parent, node = node, children[cname]
    return parent, getattr(node, "name", None), node


def apply_augments(
    trees: dict[str, list], modules: list[Stmt], shared: dict
) -> int:
    """Graft each module's top-level augment statements onto the target
    module's schema tree (libyang's ctx augment application).  Augments
    may target nodes OTHER augments create (holo-ospf targets the ospf
    container that ietf-ospf grafts into ietf-routing), so application
    iterates to a fixpoint.  Returns the number of statements applied."""
    ctx = {
        id(m): (_prefix_map(m), _Builder(m, shared)) for m in modules
    }
    pending = [
        (m, aug) for m in modules for aug in m.all("augment")
    ]
    applied = 0
    while pending:
        progressed = False
        still = []
        for m, aug in pending:
            prefixes, builder = ctx[id(m)]
            got = _resolve_target(trees, prefixes, aug.arg)
            if got is None:
                still.append((m, aug))
                continue
            _parent, _name, node = got
            children = getattr(node, "children", None)
            if children is None:
                continue
            cfg = getattr(node, "config", True)
            new = builder._children(aug, cfg)
            for child in new:
                children[child.name] = child
            applied += 1 if new else 0
            progressed = True
        if not progressed:
            break
        pending = still
    return applied


def apply_deviations(
    trees: dict[str, list], modules: list[Stmt], shared: dict | None = None
) -> int:
    """Apply each module's deviation statements (the libyang analog):
    ``deviate not-supported`` prunes the target node; ``deviate
    replace { type ... }`` retypes a leaf; add/delete of defaults adjust
    the leaf in place.  Returns the number applied."""
    applied = 0
    for m in modules:
        prefixes = _prefix_map(m)
        builder = _Builder(m, shared)
        for dev in m.all("deviation"):
            got = _resolve_target(trees, prefixes, dev.arg)
            if got is None:
                continue
            parent, name, node = got
            for deviate in dev.all("deviate"):
                kind = deviate.arg
                if kind == "not-supported":
                    children = getattr(parent, "children", None)
                    if children is not None:
                        children.pop(name, None)
                    elif isinstance(parent, list):
                        parent[:] = [
                            r
                            for r in parent
                            if getattr(r, "name", None) != name
                        ]
                    applied += 1
                elif kind == "replace":
                    t = deviate.sub("type")
                    if t is not None and isinstance(node, Leaf):
                        node.base, node.enum = builder._resolve_type(t)
                        applied += 1
                    d = deviate.sub("default")
                    if d is not None and isinstance(node, Leaf):
                        node.default = node.check(d.arg)
                        applied += 1
                elif kind in ("add", "delete"):
                    d = deviate.sub("default")
                    if d is not None and isinstance(node, Leaf):
                        node.default = (
                            node.check(d.arg) if kind == "add" else None
                        )
                        applied += 1
    return applied

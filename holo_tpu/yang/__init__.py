"""YANG-lite substrate: schema registry, data trees, diffs.

The reference embeds 104 IETF YANG modules and drives everything through
libyang (holo-yang/src/lib.rs:20-26).  libyang is not available in this
environment, so this package provides a YANG-shaped schema system built in
Python: containers/lists/leaves with typed leaves, instance data trees
addressed by slash paths with list keys (``interfaces/interface[name=eth0]/
mtu``), validation, and structural diffs that drive the transaction engine.

Module definitions live in :mod:`holo_tpu.yang.modules` and mirror the
paths of the IETF modules the reference implements (ietf-interfaces,
ietf-routing, ietf-ospf, …) so northbound clients see familiar addressing.
A YANG-text front-end parser can be layered on later without changing the
provider-facing API.
"""

from holo_tpu.yang.schema import Container, Leaf, LeafList, List, Schema
from holo_tpu.yang.data import DataTree, DiffOp, diff_trees

__all__ = [
    "Container",
    "Leaf",
    "LeafList",
    "List",
    "Schema",
    "DataTree",
    "DiffOp",
    "diff_trees",
]

"""Alternate data-tree encodings: XML and a compact binary (LYB-lite).

The reference's gRPC client negotiates JSON / XML / LYB for GetRequest
payloads (holo/proto + holo-yang/src/serde/).  JSON is our native tree
form; this module adds:

- :func:`to_xml` / :func:`from_xml` — YANG-XML-shaped encoding: one
  element per node, repeated elements for list entries and leaf-lists
  (namespace declarations are omitted — the YANG-lite schema is
  single-namespace-per-mount, like the daemon's module set);
- :func:`to_lyb` / :func:`from_lyb` — a deterministic length-prefixed
  binary encoding of the same structure.  This is OUR compact format in
  the role libyang's LYB plays for the reference (the on-the-wire bytes
  are not libyang-compatible).
"""

from __future__ import annotations

import json
import re
import struct
from xml.etree import ElementTree as ET

_XML_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_.-]*$")


def _scalar_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _node_to_xml(parent: ET.Element, name: str, value) -> None:
    if not _XML_NAME.match(str(name)):
        # Ad-hoc state maps key entries by values (prefixes, addresses)
        # that are not legal element names: emit a keyed entry element.
        el = ET.SubElement(parent, "entry", key=str(name))
        if isinstance(value, dict):
            for cname, cval in sorted(value.items(), key=lambda kv: str(kv[0])):
                _node_to_xml(el, cname, cval)
        else:
            el.text = _scalar_str(value)
        return
    if isinstance(value, dict):
        el = ET.SubElement(parent, name)
        for cname, cval in sorted(value.items(), key=lambda kv: str(kv[0])):
            _node_to_xml(el, cname, cval)
    elif isinstance(value, list):
        for item in value:
            if isinstance(item, dict):
                el = ET.SubElement(parent, name)
                for cname, cval in sorted(item.items()):
                    _node_to_xml(el, cname, cval)
            else:
                ET.SubElement(parent, name).text = _scalar_str(item)
    else:
        ET.SubElement(parent, name).text = _scalar_str(value)


def to_xml(root: dict, root_tag: str = "data") -> str:
    """Nested dict/list tree -> XML text.

    Dicts are containers, lists repeat their element (YANG-XML list
    semantics).  CONFIG trees store lists as {key: entry} maps — run
    them through :func:`config_to_plain` first so keyed maps become
    key-leaf-carrying entry lists (otherwise key values would end up as
    element names, which is not well-formed for IPs/prefixes)."""
    top = ET.Element(root_tag)
    for name, value in sorted(root.items()):
        _node_to_xml(top, name, value)
    ET.indent(top)
    return ET.tostring(top, encoding="unicode")


def config_to_plain(schema_node, value):
    """Schema-aware normalization of a DataTree fragment: every keyed
    list map {key: entry} becomes a list of entries with the key leaf
    re-injected, recursively.  ``schema_node`` is the yang.schema node
    the fragment sits at (a Schema root Container, List, or None for
    unmodeled/ad-hoc state, which passes through untouched)."""
    from holo_tpu.yang.schema import Container, List

    if isinstance(schema_node, List) and isinstance(value, dict):
        out = []
        for key, entry in sorted(value.items(), key=lambda kv: str(kv[0])):
            if not isinstance(entry, dict):
                entry = {}
            plain = {
                cname: config_to_plain(
                    schema_node.children.get(cname), cval
                )
                for cname, cval in entry.items()
            }
            plain.setdefault(schema_node.key, _scalar_str(key))
            out.append(plain)
        return out
    if isinstance(schema_node, (Container, List)) and isinstance(value, dict):
        return {
            cname: config_to_plain(schema_node.children.get(cname), cval)
            for cname, cval in value.items()
        }
    return value


def _xml_to_value(el: ET.Element):
    children = list(el)
    if not children:
        return el.text or ""
    out: dict = {}
    for c in children:
        v = _xml_to_value(c)
        tag = c.get("key") if c.tag == "entry" else c.tag
        if tag in out:
            prev = out[tag]
            if not isinstance(prev, list):
                out[tag] = [prev]
            out[tag].append(v)
        else:
            out[tag] = v
    return out


def from_xml(text: str) -> dict:
    """XML text -> plain nested dict (lists where elements repeat)."""
    top = ET.fromstring(text)
    out: dict = {}
    for c in top:
        v = _xml_to_value(c)
        if c.tag in out:
            prev = out[c.tag]
            if not isinstance(prev, list):
                out[c.tag] = [prev]
            out[c.tag].append(v)
        else:
            out[c.tag] = v
    return out


# ===== LYB-lite =====

_T_DICT, _T_LIST, _T_STR, _T_INT, _T_BOOL, _T_NONE = range(6)


def _w_bytes(out: bytearray, b: bytes) -> None:
    out += struct.pack(">I", len(b)) + b


def _encode(out: bytearray, v) -> None:
    if isinstance(v, dict):
        out.append(_T_DICT)
        out += struct.pack(">I", len(v))
        for k in sorted(v, key=str):
            _w_bytes(out, str(k).encode())
            _encode(out, v[k])
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        out += struct.pack(">I", len(v))
        for item in v:
            _encode(out, item)
    elif isinstance(v, bool):
        out.append(_T_BOOL)
        out.append(1 if v else 0)
    elif isinstance(v, int):
        out.append(_T_INT)
        out += struct.pack(">q", v)
    elif v is None:
        out.append(_T_NONE)
    else:
        out.append(_T_STR)
        _w_bytes(out, str(v).encode())


def to_lyb(root: dict) -> bytes:
    out = bytearray(b"HLYB\x01")
    _encode(out, root)
    return bytes(out)


def _decode(buf: bytes, pos: int):
    t = buf[pos]
    pos += 1
    if t == _T_DICT:
        (n,) = struct.unpack_from(">I", buf, pos)
        pos += 4
        out = {}
        for _ in range(n):
            (klen,) = struct.unpack_from(">I", buf, pos)
            pos += 4
            k = buf[pos : pos + klen].decode()
            pos += klen
            out[k], pos = _decode(buf, pos)
        return out, pos
    if t == _T_LIST:
        (n,) = struct.unpack_from(">I", buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            v, pos = _decode(buf, pos)
            items.append(v)
        return items, pos
    if t == _T_STR:
        (n,) = struct.unpack_from(">I", buf, pos)
        pos += 4
        return buf[pos : pos + n].decode(), pos + n
    if t == _T_INT:
        (v,) = struct.unpack_from(">q", buf, pos)
        return v, pos + 8
    if t == _T_BOOL:
        return bool(buf[pos]), pos + 1
    if t == _T_NONE:
        return None, pos
    raise ValueError(f"bad LYB tag {t}")


def from_lyb(data: bytes) -> dict:
    if data[:5] != b"HLYB\x01":
        raise ValueError("not an HLYB v1 payload")
    out, _pos = _decode(data, 5)
    return out

"""Pluggable SPF backends.

``SpfBackend.compute`` is the single dispatch point the protocol layer calls
from its SPF-delay FSM (the reference's compute site: holo-ospf/src/spf.rs:428-435).
The scalar backend is the default (reference semantics, zero marshaling
latency — the right choice for small LSDBs); the TPU backend wins on large
LSDBs and on batched what-if / multi-root workloads, which the scalar path
can only do serially.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np

from holo_tpu.ops.graph import Topology, build_ell
from holo_tpu.ops.spf_engine import (
    DeviceGraph,
    device_graph_from_ell,
    spf_multiroot,
    spf_one,
    spf_whatif_batch,
)
from holo_tpu.spf.scalar import spf_reference


@dataclass
class SpfResult:
    """Backend-independent SPF output in host (numpy) space."""

    dist: np.ndarray  # int32[N]
    parent: np.ndarray  # int32[N]
    hops: np.ndarray  # int32[N]
    nexthop_words: np.ndarray  # uint32[N, W]


@dataclass
class MultiRootResult:
    """Multi-root SPF output: SPT shape only (see compute_multiroot)."""

    dist: np.ndarray  # int32[R, N]
    parent: np.ndarray  # int32[R, N]
    hops: np.ndarray  # int32[R, N]


class SpfBackend:
    """Interface: one SPF run, a what-if batch, or a multi-root batch."""

    name = "abstract"

    def compute(self, topo: Topology, edge_mask: np.ndarray | None = None) -> SpfResult:
        raise NotImplementedError

    def compute_whatif(self, topo: Topology, edge_masks: np.ndarray) -> list[SpfResult]:
        raise NotImplementedError


class ScalarSpfBackend(SpfBackend):
    """Default backend: exact reference-semantics Dijkstra on the host CPU."""

    name = "scalar"

    def __init__(self, n_atoms: int = 64):
        self.n_atoms = n_atoms

    def _one(self, topo: Topology, edge_mask) -> SpfResult:
        out = spf_reference(topo, edge_mask)
        return SpfResult(
            dist=out.dist,
            parent=out.parent,
            hops=out.hops,
            nexthop_words=out.nexthop_words(max(self.n_atoms, topo.n_atoms())),
        )

    def compute(self, topo, edge_mask=None):
        return self._one(topo, edge_mask)

    def compute_whatif(self, topo, edge_masks):
        return [self._one(topo, m) for m in edge_masks]

    def compute_multiroot(self, topo, roots: np.ndarray) -> "MultiRootResult":
        import copy

        dists, parents, hops = [], [], []
        for r in roots:
            t = copy.copy(topo)
            t.root = int(r)
            out = spf_reference(t)
            dists.append(out.dist)
            parents.append(out.parent)
            hops.append(out.hops)
        return MultiRootResult(
            dist=np.stack(dists), parent=np.stack(parents), hops=np.stack(hops)
        )


class TpuSpfBackend(SpfBackend):
    """JAX/XLA backend: jitted tensor SPF, cached per topology generation.

    Marshaling (Topology → ELL → DeviceGraph) happens once per LSDB
    generation and is reused across runs/batches; jit caches compile per
    (N, K, W) shape bucket.
    """

    name = "tpu"

    def __init__(
        self,
        n_atoms: int = 64,
        max_iters: int | None = None,
        engine: str = "gather",
        one_engine: str = "seq",
    ):
        """``engine``: 'gather' (ELL gathers; handles any topology) or
        'blocked' (block-sparse Pallas kernels; fastest on large LSDBs,
        requires unique (src,dst) pairs and distances < 2**27 — falls back
        to gather per topology when those preconditions fail).

        ``one_engine`` picks the gather-path fixpoint formulation
        ('fused' | 'packed' | 'seq' — see :func:`spf_one_fused`); all are
        bit-identical, differing only in TPU round/gather scheduling.
        'seq' is the default: it is the fastest measured formulation on
        the only platform benchmarked so far (JAX-CPU; BENCH_r03) — flip
        per-platform only once a TPU run shows another engine winning."""
        self.n_atoms = n_atoms
        self.max_iters = max_iters
        self.engine = engine
        self.one_engine = one_engine
        self._blocked_cache: dict[tuple, object] = {}
        self._jit_blocked = None  # built lazily (pallas import)
        # Small LRU of marshaled graphs: an instance typically alternates
        # between its LSDB topology and derived ones (hop graphs for
        # flooding reduction), which must not evict each other.
        self._cache: dict[tuple, DeviceGraph] = {}
        from holo_tpu.ops.spf_engine import _ONE_ENGINES

        one = _ONE_ENGINES[one_engine]
        self._jit_one = jax.jit(lambda g, r, m: one(g, r, m, self.max_iters))
        self._jit_batch = jax.jit(
            lambda g, r, ms: spf_whatif_batch(
                g, r, ms, self.max_iters, engine=one_engine
            )
        )
        self._jit_multiroot = jax.jit(
            lambda g, rs, m: spf_multiroot(g, rs, m, self.max_iters)
        )

    def prepare(self, topo: Topology) -> DeviceGraph:
        # Keyed by (process-unique uid, generation): in-place mutators must
        # topo.touch(), and uid reuse across freed objects cannot occur.
        key = topo.cache_key
        g = self._cache.get(key)
        if g is None:
            ell = build_ell(topo, n_atoms=max(self.n_atoms, topo.n_atoms()))
            g = device_graph_from_ell(ell)
            self._cache[key] = g
            while len(self._cache) > 4:
                self._cache.pop(next(iter(self._cache)))
        return g

    def _full_mask(self, topo: Topology, edge_mask) -> np.ndarray:
        if edge_mask is None:
            return np.ones(topo.n_edges, bool)
        return np.asarray(edge_mask, bool)

    def compute(self, topo, edge_mask=None):
        if self.engine == "blocked":
            res = self._whatif_blocked(
                topo, self._full_mask(topo, edge_mask)[None, :]
            )
            if res is not None:
                return res[0]
        g = self.prepare(topo)
        out = self._jit_one(g, topo.root, self._full_mask(topo, edge_mask))
        return SpfResult(
            dist=np.asarray(out.dist),
            parent=np.asarray(out.parent),
            hops=np.asarray(out.hops),
            nexthop_words=np.asarray(out.nexthops),
        )

    def prepare_blocked(self, topo: Topology):
        """Marshal (and cache) the blocked planes; None if unsupported.

        The cache key includes the root: unlike the gather planes, the
        blocked planes bake the root in (BFS permutation + rootp).
        """
        key = (*topo.cache_key, topo.root)
        if key in self._blocked_cache:
            return self._blocked_cache[key]
        from holo_tpu.ops.blocked_spf import marshal_block_spf

        try:
            g = marshal_block_spf(topo, n_atoms=max(self.n_atoms, topo.n_atoms()))
        except ValueError:
            g = None  # preconditions unmet: gather engine handles it
        self._blocked_cache[key] = g
        while len(self._blocked_cache) > 4:
            self._blocked_cache.pop(next(iter(self._blocked_cache)))
        return g

    def _whatif_blocked(self, topo, edge_masks):
        from holo_tpu.ops.blocked_spf import failed_edges_perm, whatif_spf_blocked

        g = self.prepare_blocked(topo)
        if g is None:
            return None
        try:
            fdst, fid = failed_edges_perm(
                np.asarray(g.orig2perm), topo, np.asarray(edge_masks, bool)
            )
        except ValueError:
            return None  # too many failed edges per scenario
        if self._jit_blocked is None:
            from functools import partial

            self._jit_blocked = jax.jit(
                partial(whatif_spf_blocked, max_iters=self.max_iters)
            )
        out = self._jit_blocked(g, fdst, fid)
        dist, parent, hops, nh = (
            np.asarray(out.dist),
            np.asarray(out.parent),
            np.asarray(out.hops),
            np.asarray(out.nexthops),
        )
        return [
            SpfResult(dist=dist[i], parent=parent[i], hops=hops[i], nexthop_words=nh[i])
            for i in range(dist.shape[0])
        ]

    def compute_whatif(self, topo, edge_masks):
        if self.engine == "blocked":
            res = self._whatif_blocked(topo, edge_masks)
            if res is not None:
                return res
        g = self.prepare(topo)
        out = self._jit_batch(g, topo.root, np.asarray(edge_masks, bool))
        # One bulk device→host transfer per plane: per-scenario slicing of
        # device arrays would pay the host round-trip B×4 times.
        dist, parent, hops, nh = (
            np.asarray(out.dist),
            np.asarray(out.parent),
            np.asarray(out.hops),
            np.asarray(out.nexthops),
        )
        return [
            SpfResult(dist=dist[i], parent=parent[i], hops=hops[i], nexthop_words=nh[i])
            for i in range(edge_masks.shape[0])
        ]

    def compute_multiroot(self, topo, roots: np.ndarray) -> "MultiRootResult":
        """Distances/parents/hops from many roots (one device program).

        Next-hop bitmasks are intentionally NOT returned: direct atoms are
        marshaled relative to ``topo.root``, so they are meaningless for any
        other root.  Multi-root users (IS-IS flooding reduction, TI-LFA)
        need the SPT shape only.
        """
        g = self.prepare(topo)
        mask = np.ones(topo.n_edges, bool)
        out = self._jit_multiroot(g, np.asarray(roots, np.int32), mask)
        return MultiRootResult(
            dist=np.asarray(out.dist),
            parent=np.asarray(out.parent),
            hops=np.asarray(out.hops),
        )

"""Pluggable SPF backends.

``SpfBackend.compute`` is the single dispatch point the protocol layer calls
from its SPF-delay FSM (the reference's compute site: holo-ospf/src/spf.rs:428-435).
The scalar backend is the default (reference semantics, zero marshaling
latency — the right choice for small LSDBs); the TPU backend wins on large
LSDBs and on batched what-if / multi-root workloads, which the scalar path
can only do serially.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass

import jax
import numpy as np

from holo_tpu import telemetry
from holo_tpu.analysis.runtime import (
    assert_live,
    consumes_donated,
    note_donated,
    sanctioned_transfer,
)
from holo_tpu.ops.graph import Topology
from holo_tpu.resilience import faults
from holo_tpu.resilience.breaker import CircuitBreaker
from holo_tpu.ops.spf_engine import (
    DeviceGraph,
    mp_pad,
    note_delta,
    shared_graph_cache,
    spf_multipath_batch,
    spf_multiroot,
    spf_one,
    spf_one_incremental,
    spf_one_incremental_multipath,
    spf_one_multipath,
    spf_whatif_batch,
)
from holo_tpu.ops.tropical import (
    repair_rows_host,
    tropical_multiroot,
    tropical_spf_one,
    tropical_spf_one_incremental,
    tropical_spf_one_incremental_multipath,
    tropical_spf_one_multipath,
    tropical_whatif_batch,
)

#: engine names that dispatch through the tropical tile planes
_TROPICAL_ENGINES = ("tropical", "mp_tropical")
from holo_tpu.spf.scalar import spf_multipath_reference, spf_reference
from holo_tpu.telemetry import convergence, profiling

# Device-dispatch observability (the tentpole signal set): wall time per
# dispatch, device->host readback time, jit recompiles vs shape-cache
# hits (a silent recompile storm is the classic invisible regression),
# and marshaled-graph cache behavior.  Shape tracking is done HERE (a
# seen-signature set per backend) rather than poking jit internals, so
# it works identically on every jax version and platform.
_DISPATCH_SECONDS = telemetry.histogram(
    "holo_spf_dispatch_seconds",
    "Wall time of one SPF dispatch (incl. readback)",
    ("backend", "kind"),
)
_TRANSFER_SECONDS = telemetry.histogram(
    "holo_spf_transfer_seconds",
    "Device->host readback time per dispatch",
    ("kind",),
)
_JIT_COMPILES = telemetry.counter(
    "holo_spf_jit_compiles_total",
    "Dispatches that hit a new (engine, shape) bucket (XLA recompile)",
    ("kind",),
)
_JIT_HITS = telemetry.counter(
    "holo_spf_jit_cache_hits_total",
    "Dispatches served from an already-compiled shape bucket",
    ("kind",),
)
_GRAPH_CACHE = telemetry.counter(
    "holo_spf_graph_cache_total",
    "Marshaled DeviceGraph cache lookups",
    ("result",),
)
_BATCH_SCENARIOS = telemetry.counter(
    "holo_spf_scenarios_total",
    "Scenario-SPFs computed (batch rows count individually)",
    ("kind",),
)
_SHARD_DISPATCHES = telemetry.counter(
    "holo_spf_shard_dispatch_total",
    "Dispatches routed through the process-mesh sharded path "
    "(parallel/mesh.py layout contract)",
    ("kind",),
)


def _mesh():
    """The process dispatch mesh (parallel/mesh.py), or None."""
    from holo_tpu.parallel.mesh import process_mesh

    return process_mesh()


def _mesh_key():
    from holo_tpu.parallel.mesh import mesh_cache_key

    return mesh_cache_key()


@dataclass
class SpfResult:
    """Backend-independent SPF output in host (numpy) space.

    The multipath planes (ISSUE 10) are present iff the dispatch asked
    for them (``multipath_k > 1``); ``None`` otherwise — the k=1 path
    is byte-for-byte the single-parent dispatch (the
    ``multipath_overhead`` gate's contract)."""

    dist: np.ndarray  # int32[N]
    parent: np.ndarray  # int32[N]
    hops: np.ndarray  # int32[N]
    nexthop_words: np.ndarray  # uint32[N, W]
    parents: np.ndarray | None = None  # int32[N, Kp]; sentinel N
    pdist: np.ndarray | None = None  # int32[N, Kp]; INF past the set
    pweight: np.ndarray | None = None  # int32[N, Kp]
    npaths: np.ndarray | None = None  # int32[N]
    nh_weights: np.ndarray | None = None  # int32[N, A]


def _host_tensors(out, n: int):
    """Materialize device SPF tensors into the host contract: vertex
    axis sliced back to N and the sentinels renormalized.

    Node-sharded residents pad rows to a multiple of the mesh's node
    axis, so the device program's "no parent" sentinel is the PADDED
    row count R (and unreachable hops R+1) — map them back to N / N+1
    so sharded output is byte-identical to the single-device path.  On
    an unpadded graph every step is a no-op (slice of full extent;
    minimum against a value no tensor reaches)."""
    dist = np.asarray(out.dist)[..., :n]
    parent = np.minimum(np.asarray(out.parent)[..., :n], np.int32(n))
    hops = np.minimum(np.asarray(out.hops)[..., :n], np.int32(n + 1))
    nh = np.asarray(out.nexthops)[..., :n, :]
    return dist, parent, hops, nh


def _host_mp(mp, n: int) -> dict:
    """Multipath-plane readback under the same sharded-row contract as
    :func:`_host_tensors`: vertex axis sliced to N, the padded-row
    parent sentinel R renormalized to N.  SpfResult field kwargs."""
    return {
        "parents": np.minimum(
            np.asarray(mp.parents)[..., :n, :], np.int32(n)
        ),
        "pdist": np.asarray(mp.pdist)[..., :n, :],
        "pweight": np.asarray(mp.pweight)[..., :n, :],
        "npaths": np.asarray(mp.npaths)[..., :n],
        "nh_weights": np.asarray(mp.nh_weights)[..., :n, :],
    }


@dataclass
class MultiRootResult:
    """Multi-root SPF output: SPT shape only (see compute_multiroot)."""

    dist: np.ndarray  # int32[R, N]
    parent: np.ndarray  # int32[R, N]
    hops: np.ndarray  # int32[R, N]


@dataclass
class _InFlightOne:
    """Phase-1 state of a split (pipelined) kind=one dispatch — see
    ``TpuSpfBackend.launch_one`` / ``finish_one``."""

    out: object  # device SpfTensors, dispatch possibly still in flight
    topo: Topology
    t0: float
    engine: str
    bucket: tuple | None  # tuner bucket; None = "never feed the tuner"
    mode: str  # "full" | "delta"
    n_atoms: int
    delta_kind: str = ""
    kp: int = 1  # pow2 multipath width; 1 = single-parent kernel
    remember: bool = False
    sharded: bool = False
    remarshal: bool = False
    fresh: bool = False  # fresh XLA compile: not a tuner sample
    # Observatory shape key (ISSUE 12) — deliberately separate from
    # ``bucket`` so observing never overrides the tuner's None sentinel.
    obucket: tuple | None = None
    # Wall of the launch phase alone: tuner samples use launch_s +
    # finish wall, EXCLUDING the time the entry sat parked in the
    # pipeline's in-flight slot while the worker served other keys —
    # parked time is scheduling, not engine cost, and would bias both
    # the engine medians and the delta/full depth ratio.
    launch_s: float = 0.0


class SpfBackend:
    """Interface: one SPF run, a what-if batch, or a multi-root batch."""

    name = "abstract"

    def compute(self, topo: Topology, edge_mask: np.ndarray | None = None) -> SpfResult:
        raise NotImplementedError

    def compute_whatif(self, topo: Topology, edge_masks: np.ndarray) -> list[SpfResult]:
        raise NotImplementedError


class ScalarSpfBackend(SpfBackend):
    """Default backend: exact reference-semantics Dijkstra on the host CPU."""

    name = "scalar"

    def __init__(self, n_atoms: int = 64):
        self.n_atoms = n_atoms

    def _one(self, topo: Topology, edge_mask, kp: int = 1) -> SpfResult:
        n_atoms = max(self.n_atoms, topo.n_atoms())
        if kp > 1:
            out, omp = spf_multipath_reference(
                topo, kp, edge_mask, n_lanes=((n_atoms + 31) // 32) * 32
            )
            return SpfResult(
                dist=out.dist,
                parent=out.parent,
                hops=out.hops,
                nexthop_words=out.nexthop_words(n_atoms),
                parents=omp.parents,
                pdist=omp.pdist,
                pweight=omp.pweight,
                npaths=omp.npaths,
                nh_weights=omp.nh_weights,
            )
        out = spf_reference(topo, edge_mask)
        return SpfResult(
            dist=out.dist,
            parent=out.parent,
            hops=out.hops,
            nexthop_words=out.nexthop_words(n_atoms),
        )

    def compute(self, topo, edge_mask=None, multipath_k: int = 1):
        # Same dispatch histogram as the TPU backend (kind axis shared):
        # a default-config daemon still reports SPF timing; only the
        # transfer/recompile signals are device-specific.
        t0 = profiling.clock()
        with telemetry.span("spf.dispatch", kind="one", backend="scalar"):
            res = self._one(topo, edge_mask, mp_pad(multipath_k))
        _DISPATCH_SECONDS.labels(backend="scalar", kind="one").observe(
            profiling.clock() - t0
        )
        _BATCH_SCENARIOS.labels(kind="one").inc()
        convergence.note_dispatch("spf", "scalar")
        return res

    def compute_whatif(self, topo, edge_masks, multipath_k: int = 1):
        t0 = profiling.clock()
        kp = mp_pad(multipath_k)
        with telemetry.span(
            "spf.dispatch", kind="whatif", backend="scalar",
            batch=len(edge_masks),
        ):
            res = [self._one(topo, m, kp) for m in edge_masks]
        _DISPATCH_SECONDS.labels(backend="scalar", kind="whatif").observe(
            profiling.clock() - t0
        )
        _BATCH_SCENARIOS.labels(kind="whatif").inc(len(res))
        convergence.note_dispatch("spf", "scalar")
        return res

    def compute_multiroot(self, topo, roots: np.ndarray) -> "MultiRootResult":
        import copy

        dists, parents, hops = [], [], []
        for r in roots:
            t = copy.copy(topo)
            t.root = int(r)
            out = spf_reference(t)
            dists.append(out.dist)
            parents.append(out.parent)
            hops.append(out.hops)
        return MultiRootResult(
            dist=np.stack(dists), parent=np.stack(parents), hops=np.stack(hops)
        )


# Partitioned-resident cache namespaces (one per backend, process-wide
# unique for the process lifetime — see TpuSpfBackend._part_ns).
_PART_NS_IDS = itertools.count()


class TpuSpfBackend(SpfBackend):
    """JAX/XLA backend: jitted tensor SPF, cached per topology generation.

    Marshaling (Topology → ELL → DeviceGraph) happens once per LSDB
    generation and is reused across runs/batches; jit caches compile per
    (N, K, W) shape bucket.
    """

    name = "tpu"

    def __init__(
        self,
        n_atoms: int = 64,
        max_iters: int | None = None,
        engine: str = "gather",
        one_engine: str = "seq",
        breaker: CircuitBreaker | None = None,
        incremental: bool = True,
        prev_capacity: int = 32,
        partition_threshold: int | None = None,
        partition_parts: int | None = None,
        partition_max_part: int = 4096,
    ):
        """``engine``: 'gather' (ELL gathers; handles any topology) or
        'blocked' (block-sparse Pallas kernels; fastest on large LSDBs,
        requires unique (src,dst) pairs and distances < 2**27 — falls back
        to gather per topology when those preconditions fail).

        ``one_engine`` picks the gather-path fixpoint formulation
        ('fused' | 'packed' | 'seq' — see :func:`spf_one_fused`); all are
        bit-identical, differing only in TPU round/gather scheduling.
        'seq' is the default: it is the fastest measured formulation on
        the only platform benchmarked so far (JAX-CPU; BENCH_r03) — flip
        per-platform only once a TPU run shows another engine winning.

        ``breaker`` guards every device dispatch: XLA exceptions and
        deadline overruns fall back to the scalar oracle (bit-identical
        by the parity contract), and repeated failures open the circuit
        so a dead relay stops being retried on the SPF hot path.

        ``incremental`` arms the DeltaPath dispatch: topologies carrying
        delta lineage (``Topology.link_delta`` at the LSDB seam) are
        served by an in-place device-graph update plus the seeded
        incremental kernel instead of a full re-marshal + full-batch
        recompute.  False forces the full-rebuild path everywhere (the
        bench's comparison arm).  ``prev_capacity`` bounds the retained
        previous-tensor entries — one live (topology, root) chain per
        entry, so size it >= the number of areas/MTs the instance
        computes per SPF cycle or their chains silently degrade to
        ``full-no-prev``.

        ``partition_threshold`` arms the hierarchical partitioned path
        (ISSUE 15): kind=one/whatif dispatches on topologies with at
        least that many vertices route through
        :class:`holo_tpu.ops.partition.PartitionedSpfEngine` — the
        graph is cut (natively via ``Topology.partition_hint``, else
        the deterministic BFS/greedy cut into ``partition_parts`` parts
        or parts of ≤ ``partition_max_part`` vertices), solved as one
        batched dispatch of small per-partition programs, and stitched
        exactly through the boundary-contraction skeleton.  None (the
        default) keeps every dispatch monolithic.  Bit-identical to the
        monolithic kernels and scalar oracle on every arm (the parity
        contract); breaker fallback and DeltaPath compose."""
        self.n_atoms = n_atoms
        self.max_iters = max_iters
        self.engine = engine
        self.one_engine = one_engine
        self.incremental = incremental
        self.prev_capacity = int(prev_capacity)
        self.breaker = (
            breaker if breaker is not None else CircuitBreaker("spf-dispatch")
        )
        self._oracle = ScalarSpfBackend(n_atoms)
        self._blocked_cache: dict[tuple, object] = {}
        self._jit_blocked = None  # built lazily (pallas import)
        # (kind, shape...) signatures already dispatched: a miss here is
        # a fresh XLA compile for this backend instance.
        self._compiled_shapes: set[tuple] = set()
        # Previous SpfTensors per (topology key, n_atoms, root): the
        # device-resident seed state of the incremental kernel.  The
        # entry is DONATED into the kernel that consumes it.
        self._prev_one: dict[tuple, object] = {}
        # Gather-path jits, one per fixpoint engine (lazily built):
        # the engine auto-tuner (holo_tpu.pipeline.tuner) flips the
        # formulation per shape bucket at dispatch time, so the pinned
        # ``one_engine`` is only the untuned default.  All engines are
        # bit-identical (parity-gated), so a flip is a latency choice,
        # never a semantic one.
        self._one_jits: dict[str, object] = {}
        self._batch_jits: dict[str, object] = {}
        # Multipath (ISSUE 10) jits, one per pow2 parent-set width kp:
        # the widened kernel is dispatched ONLY when a dispatch asks
        # for multipath_k > 1 — the k=1 path rides the unchanged
        # single-parent programs (the multipath_overhead contract).
        self._mp_jits: dict[int, object] = {}
        self._mp_batch_jits: dict[int, object] = {}
        self._mp_incr_jits: dict[int, object] = {}
        # Tropical (ISSUE 13) jits: the blocked min-plus programs take
        # the tile planes as an extra operand, so they live in their
        # own caches; the tuner flips between the families per shape
        # bucket (all bit-identical — a flip is a latency choice).
        self._trop_jits: dict[tuple, object] = {}
        self._jit_multiroot = jax.jit(
            lambda g, rs, m: spf_multiroot(g, rs, m, self.max_iters)
        )
        self._jit_incr = jax.jit(
            lambda g, r, prev, seeds: spf_one_incremental(
                g, r, prev, seeds, self.max_iters
            ),
            donate_argnums=(2,),
        )
        # What the last prepare() actually did ('hit'/'delta'/'miss'):
        # the depth auto-tuner attributes full-rebuild walls to cache
        # misses only (a warm hit is not a re-marshal cost).
        self._last_prepare_how = ""
        # Mesh-sharded dispatch programs, built lazily per (kind,
        # engine, mesh identity): outputs pinned to the batch sharding
        # so GSPMD propagates the scenario/root split through the whole
        # program.
        self._shard_jits: dict[tuple, object] = {}
        # Partitioned-SPF state (ISSUE 15): the engine is lazy (first
        # partitioned dispatch); residents ride the process-wide
        # DeviceGraphCache as per-partition entries — one lock/LRU/
        # eviction surface with the monolithic DeltaPath residents —
        # keyed per (backend namespace, root, n_atoms, mesh) chain.
        self.partition_threshold = partition_threshold
        self.partition_parts = partition_parts
        self.partition_max_part = int(partition_max_part)
        self._part_engine = None
        # Monotonic, never reused (id(self) can be recycled after GC,
        # letting a new backend adopt a dead backend's residents).
        self._part_ns = f"part:{next(_PART_NS_IDS)}"
        # Device-residency byte ledger (ISSUE 17 satellite): weakref
        # registration only — the ledger walks _prev_one lazily at
        # scrape time, and a dropped backend never leaks through it.
        from holo_tpu.telemetry import residency

        residency.register_spf_backend(self)

    def _jit_one_for(self, engine: str):
        fn = self._one_jits.get(engine)
        if fn is None:
            from holo_tpu.ops.spf_engine import _ONE_ENGINES

            one = _ONE_ENGINES[engine]
            fn = self._one_jits[engine] = jax.jit(
                lambda g, r, m: one(g, r, m, self.max_iters)
            )
        return fn

    def _jit_batch_for(self, engine: str):
        fn = self._batch_jits.get(engine)
        if fn is None:
            fn = self._batch_jits[engine] = jax.jit(
                lambda g, r, ms: spf_whatif_batch(
                    g, r, ms, self.max_iters, engine=engine
                )
            )
        return fn

    def _jit_mp_for(self, kp: int):
        fn = self._mp_jits.get(kp)
        if fn is None:
            fn = self._mp_jits[kp] = jax.jit(
                lambda g, r, m, _kp=kp: spf_one_multipath(
                    g, r, _kp, m, self.max_iters
                )
            )
        return fn

    def _jit_mp_batch_for(self, kp: int):
        fn = self._mp_batch_jits.get(kp)
        if fn is None:
            fn = self._mp_batch_jits[kp] = jax.jit(
                lambda g, r, ms, _kp=kp: spf_multipath_batch(
                    g, r, ms, _kp, self.max_iters
                )
            )
        return fn

    def _jit_mp_incr_for(self, kp: int):
        """Incremental multipath jit: the previous SpfTensors plus the
        two multipath planes that actually carry state (``npaths``,
        ``nh_weights``) are donated — same ownership discipline as
        ``_jit_incr``, widened.  The parent-set planes are closed-form
        in the settled distances and never read by the kernel, so they
        are not passed (HL301: a donated-but-unused arg is pruned and
        its alias can never realize)."""
        fn = self._mp_incr_jits.get(kp)
        if fn is None:
            fn = self._mp_incr_jits[kp] = jax.jit(
                lambda g, r, prev, np_prev, aw_prev, seeds, _kp=kp: (
                    spf_one_incremental_multipath(
                        g, r, prev, np_prev, aw_prev, seeds,
                        _kp, self.max_iters,
                    )
                ),
                donate_argnums=(2, 3, 4),
            )
        return fn

    def _jit_trop(self, key: str, build):
        fn = self._trop_jits.get(key)
        if fn is None:
            fn = self._trop_jits[key] = build()
        return fn

    @property
    def _jit_trop_one(self):
        return self._jit_trop(
            "one",
            lambda: jax.jit(
                lambda g, tt, r, m, rr: tropical_spf_one(
                    g, tt, r, m, rr, self.max_iters
                )
            ),
        )

    @property
    def _jit_trop_batch(self):
        return self._jit_trop(
            "whatif",
            lambda: jax.jit(
                lambda g, tt, r, ms, rr: tropical_whatif_batch(
                    g, tt, r, ms, rr, self.max_iters
                )
            ),
        )

    def _jit_trop_mp_for(self, kp: int):
        return self._jit_trop(
            f"mp{kp}",
            lambda: jax.jit(
                lambda g, tt, r, m, rr, _kp=kp: tropical_spf_one_multipath(
                    g, tt, r, _kp, m, rr, self.max_iters
                )
            ),
        )

    @property
    def _jit_trop_incr(self):
        return self._jit_trop(
            "incr",
            lambda: jax.jit(
                lambda g, tt, r, prev, seeds: tropical_spf_one_incremental(
                    g, tt, r, prev, seeds, self.max_iters
                ),
                donate_argnums=(3,),
            ),
        )

    def _jit_trop_mp_incr_for(self, kp: int):
        # Donation mirrors _jit_mp_incr_for: prev plus the two live
        # multipath planes only — the parent-set planes never realize.
        return self._jit_trop(
            f"mp-incr{kp}",
            lambda: jax.jit(
                lambda g, tt, r, prev, np_prev, aw_prev, seeds, _kp=kp: (
                    tropical_spf_one_incremental_multipath(
                        g, tt, r, prev, np_prev, aw_prev, seeds,
                        _kp, self.max_iters,
                    )
                ),
                donate_argnums=(3, 4, 5),
            ),
        )

    @property
    def _jit_trop_multiroot(self):
        return self._jit_trop(
            "multiroot",
            lambda: jax.jit(
                lambda g, tt, rs, m, rr: tropical_multiroot(
                    g, tt, rs, m, rr, self.max_iters
                )
            ),
        )

    def _trop_operands(self, topo, g, mask=None):
        """(tiles, repair rows) for one tropical dispatch — call inside
        the sanctioned marshal window (the tile device_put and the
        repair-row lowering are part of that transfer).  The repair
        rows carry the destinations of masked-out edges, padded with
        the resident's PADDED row count (drop sentinel)."""
        tt = shared_graph_cache().get_tropical(
            topo, max(self.n_atoms, topo.n_atoms())
        )
        rows = int(g.in_src.shape[0])
        if mask is None:
            rr = np.zeros(0, np.int32)
        else:
            rr = repair_rows_host(
                topo.edge_dst, np.asarray(mask, bool)[None, :], rows
            )[0]
        return tt, rr

    def _one_step(self, engine: str, kp: int, g, tt, root, mask, rr):
        """(jit, args) of one single-SPF dispatch for the picked
        engine — the gather/tropical/mp/mp_tropical fan-in shared by
        the sync and split-phase paths."""
        if kp > 1:
            if engine == "mp_tropical":
                return self._jit_trop_mp_for(kp), (g, tt, root, mask, rr)
            return self._jit_mp_for(kp), (g, root, mask)
        if engine == "tropical":
            return self._jit_trop_one, (g, tt, root, mask, rr)
        return self._jit_one_for(engine), (g, root, mask)

    def _incr_step(self, topo, g, n_atoms, kp, pad, prev_key, prev, seeds_p):
        """Dispatch ONE incremental (DeltaPath) kernel — the
        gather/tropical x single/multipath fan-in shared by the sync
        and split-phase paths.  Must run inside the caller's
        ``spf.one.delta`` sanctioned window (the tile attach may
        device_put).  The previous tensors are DONATED into the
        kernel: our ``_prev_one`` reference is dropped here, before
        dispatch, so a failed dispatch can never leave a consumed
        entry behind.  Returns ``(step, out, trop, tt, sig, fresh)``."""
        trop = self._trop_incremental(topo, kp)
        tt = (
            shared_graph_cache().get_tropical(topo, n_atoms)
            if trop
            else None
        )
        sig = (
            g.in_src.shape, g.direct_nh_words.shape[2], pad,
            _mesh_key(), kp,
            None if tt is None else tt.tiles.shape,
        )
        fresh = self._track_compile("delta", "incr", *sig)
        del self._prev_one[prev_key]
        if kp > 1:
            np_prev, aw_prev = prev[1].npaths, prev[1].nh_weights
            if trop:
                step = self._jit_trop_mp_incr_for(kp)
                out = step(
                    g, tt, topo.root, prev[0], np_prev, aw_prev, seeds_p
                )
            else:
                step = self._jit_mp_incr_for(kp)
                out = step(g, topo.root, prev[0], np_prev, aw_prev, seeds_p)
        elif trop:
            step = self._jit_trop_incr
            out = step(g, tt, topo.root, prev, seeds_p)
        else:
            step = self._jit_incr
            out = step(g, topo.root, prev, seeds_p)
        # Runtime half of HL109: under the test-mode donation guard
        # the consumed previous tensors are actually poisoned, so any
        # use-after-donate the static rule missed raises at read time
        # on the CPU platform exactly as it would corrupt on device.
        # The whole previous state is poisoned — including the
        # multipath parent-set planes that are recomputed rather than
        # donated — because ownership transfers wholesale here even
        # where the jit-level donation is narrower.
        note_donated("spf.one.delta", prev)
        return step, out, trop, tt, sig, fresh

    def _incr_cost_args(self, trop, tt, g, root, out, seeds_p, kp):
        """record_cost re-trace args for a fresh incremental compile —
        the donated prev args are gone, so this run's own output
        tensors stand in (same shapes/dtypes)."""
        root_args = (g, tt, root) if trop else (g, root)
        return (
            (*root_args, out[0], out[1].npaths, out[1].nh_weights, seeds_p)
            if kp > 1
            else (*root_args, out, seeds_p)
        )

    # Kept as properties: external probes (tests, cost tooling) read
    # the pinned-engine jits.  Pinned tropical returns the tile-plane
    # jit — NOTE its call signature is (g, tt, root, mask, rr), not
    # the gather engines' (g, root, mask).
    @property
    def _jit_one(self):
        if self.one_engine == "tropical":
            return self._jit_trop_one
        return self._jit_one_for(self.one_engine)

    @property
    def _jit_batch(self):
        if self.one_engine == "tropical":
            return self._jit_trop_batch
        return self._jit_batch_for(self.one_engine)

    def _pick_engine(self, kind: str, topo, batch: int = 1, kp: int = 1):
        """(engine, shape bucket | None) for this dispatch: the
        process engine tuner's per-shape choice when one is armed, else
        the pinned ``one_engine``.  Lazy import keeps the unarmed path
        at a sys.modules hit (pipeline_overhead gate).

        Multipath dispatches (``kp > 1``) choose between the packed
        row-gather kernel (``mp``) and its tropical DAG-tile variant
        (``mp_tropical``, kind=one only — ISSUE 13), still under a
        bucket carrying kp in the shape key (the tuner learns k as
        part of the shape: k=1 engine medians never mix with k=8
        walls)."""
        from holo_tpu.pipeline.tuner import active_tuner, shape_bucket

        t = active_tuner()
        if t is None or self.engine == "blocked":
            if kp > 1:
                pinned_trop = (
                    self.one_engine == "tropical" and kind == "one"
                )
                return ("mp_tropical" if pinned_trop else "mp"), None
            return self.one_engine, None
        bucket = shape_bucket(
            topo.n_vertices, topo.n_edges, batch, _mesh_key(), k=kp
        )
        return t.pick(kind, bucket), bucket

    @staticmethod
    def _tuner_observe(kind, bucket, engine, seconds) -> None:
        if bucket is None:
            return
        from holo_tpu.pipeline.tuner import active_tuner

        t = active_tuner()
        if t is not None:
            t.observe(kind, bucket, engine, seconds)

    @staticmethod
    def _tuner_cost(kind, bucket, engine, entry) -> None:
        if bucket is None or entry is None:
            return
        from holo_tpu.pipeline.tuner import active_tuner

        t = active_tuner()
        if t is not None:
            t.cost_prior(kind, bucket, engine, entry)

    def _obs_bucket(self, topo, batch: int, kp: int, bucket):
        """The observatory's shape key for this dispatch (ISSUE 12):
        the tuner bucket when one was computed, else the same pow2
        quantization derived directly — sketches must key on shape
        even when no tuner is armed.  Kept SEPARATE from the tuner's
        bucket variable: ``_pick_engine`` returns ``bucket=None`` as a
        deliberate "never feed the tuner" sentinel (blocked-engine
        backends, unarmed tuner), and arming a passive observability
        feature must not start mutating engine-selection state.
        Returns None while the observatory is disarmed."""
        if not profiling.observing():
            return None
        if bucket is not None:
            return bucket
        from holo_tpu.pipeline.tuner import shape_bucket

        return shape_bucket(
            topo.n_vertices, topo.n_edges, batch, _mesh_key(), k=kp
        )

    @staticmethod
    def _obs_cost(site, kind, engine, bucket, entry) -> None:
        """Forward a fresh-compile cost entry to the observatory's
        roofline join (the ``cost_prior`` twin for sketches)."""
        if entry is None or not profiling.observing():
            return
        from holo_tpu.telemetry import observatory

        observatory.note_cost(site, kind, engine, bucket, entry)

    def _depth_bucket(self, topo, kp: int = 1):
        """The DeltaPath depth-tuning bucket (kind=one, batch=1).
        ``kp`` rides the shape key: the widened kernel's delta/full
        walls must not contaminate the k=1 bucket's depth ratio."""
        from holo_tpu.pipeline.tuner import shape_bucket

        return shape_bucket(
            topo.n_vertices, topo.n_edges, 1, _mesh_key(), k=kp
        )

    def _trop_incremental(self, topo, kp: int) -> bool:
        """Route this chain's engine-fixed incremental kernel through
        the tropical tiles?  Yes when the backend is pinned tropical,
        or when the tuner's measured full-dispatch winner for this
        shape bucket is the tropical family — the incremental program
        should relax on the same representation the full program
        proved fastest at this shape."""
        if self.one_engine == "tropical":
            return True
        from holo_tpu.pipeline.tuner import active_tuner

        t = active_tuner()
        if t is None:
            return False
        return (
            t.current_winner("one", self._depth_bucket(topo, kp))
            in _TROPICAL_ENGINES
        )

    def _tuner_depth_observe(
        self, topo, arm: str, seconds: float, kp: int = 1
    ) -> None:
        """Feed a measured delta-path / full-rebuild wall into the
        persisted tuner table (the per-shape max_delta_depth input)."""
        from holo_tpu.pipeline.tuner import active_tuner

        t = active_tuner()
        if t is None:
            return
        b = self._depth_bucket(topo, kp)
        if arm == "delta":
            t.observe_delta(b, seconds)
        else:
            t.observe_full(b, seconds)

    def _sharded_whatif(self, mesh, engine: str | None = None):
        if engine is None:
            engine = self.one_engine
        if mesh.size == 1:
            # Degenerate mesh: the plain program IS the sharded program
            # (mesh.constrain_batch would be a no-op) — reuse its jit
            # cache so the 1-device mesh costs nothing but the routing.
            return self._jit_batch_for(engine)
        from holo_tpu.parallel.mesh import mesh_cache_key, sharded_whatif_jit

        key = ("whatif", engine, mesh_cache_key(mesh))
        fn = self._shard_jits.get(key)
        if fn is None:
            fn = sharded_whatif_jit(mesh, self.max_iters, engine)
            self._shard_jits[key] = fn
        return fn

    def _sharded_trop_whatif(self, mesh):
        if mesh.size == 1:  # see _sharded_whatif
            return self._jit_trop_batch
        from holo_tpu.parallel.mesh import (
            mesh_cache_key,
            sharded_tropical_whatif_jit,
        )

        key = ("whatif-tropical", mesh_cache_key(mesh))
        fn = self._shard_jits.get(key)
        if fn is None:
            fn = sharded_tropical_whatif_jit(mesh, self.max_iters)
            self._shard_jits[key] = fn
        return fn

    def _sharded_trop_multiroot(self, mesh):
        if mesh.size == 1:
            return self._jit_trop_multiroot
        from holo_tpu.parallel.mesh import (
            mesh_cache_key,
            sharded_tropical_multiroot_jit,
        )

        key = ("multiroot-tropical", mesh_cache_key(mesh))
        fn = self._shard_jits.get(key)
        if fn is None:
            fn = sharded_tropical_multiroot_jit(mesh, self.max_iters)
            self._shard_jits[key] = fn
        return fn

    def _sharded_mp_whatif(self, mesh, kp: int):
        if mesh.size == 1:  # see _sharded_whatif
            return self._jit_mp_batch_for(kp)
        from holo_tpu.parallel.mesh import (
            mesh_cache_key,
            sharded_multipath_jit,
        )

        key = ("mp-whatif", kp, mesh_cache_key(mesh))
        fn = self._shard_jits.get(key)
        if fn is None:
            fn = sharded_multipath_jit(mesh, kp, self.max_iters)
            self._shard_jits[key] = fn
        return fn

    def _sharded_multiroot(self, mesh):
        if mesh.size == 1:  # see _sharded_whatif
            return self._jit_multiroot
        from holo_tpu.parallel.mesh import constrain_batch, mesh_cache_key

        key = ("multiroot", mesh_cache_key(mesh))
        fn = self._shard_jits.get(key)
        if fn is None:

            @jax.jit
            def step(g, rs, m):
                out = spf_multiroot(g, rs, m, self.max_iters)
                return constrain_batch(mesh, out)

            fn = self._shard_jits[key] = step
        return fn

    def prepare(
        self,
        topo: Topology,
        need_edge_ids: bool = False,
        allow_delta: bool | None = None,
    ) -> DeviceGraph:
        # The process-wide shared cache (keyed by the topology's
        # (process-unique uid, generation) identity — in-place mutators
        # must topo.touch()): an instance running SPF + FRR marshals its
        # DeviceGraph once, not once per engine.  The per-engine counter
        # keeps the historical series alive alongside the shared
        # holo_spf_marshal_cache_total triple; a 'delta' result means
        # the resident graph was updated in place instead of rebuilt.
        if allow_delta is None:
            allow_delta = self.incremental
        g, how = shared_graph_cache().get(
            topo,
            max(self.n_atoms, topo.n_atoms()),
            need_edge_ids=need_edge_ids,
            allow_delta=allow_delta,
        )
        _GRAPH_CACHE.labels(result=how).inc()
        self._last_prepare_how = how
        return g

    def _remember(self, topo: Topology, n_atoms: int, out, kp: int = 1) -> None:
        """Retain this run's device tensors as the next delta's seed.

        Idempotent per key: a repeated dispatch of the same (topology
        generation, root) produces bit-identical tensors, so the
        already-stored set stays — the no-delta steady state then holds
        one buffer set instead of churning a fresh one per dispatch
        (the incremental_overhead <2% gate measures exactly this).

        ``kp`` joins the key: a multipath chain seeds from multipath
        tensors ((SpfTensors, MultipathTensors) pairs) and a k=1 chain
        from plain SpfTensors — a ``max-paths`` reconfigure mid-chain
        degrades that root's next delta to ``full-no-prev``, never to a
        wrong-width donation."""
        key = (
            *topo.cache_key, int(n_atoms), int(topo.root), _mesh_key(),
            int(kp),
        )
        if key in self._prev_one:
            return
        # The legitimate re-deposit seam of the donation handoff: the
        # FRESH output tensors take the consumed previous set's place.
        # consumes_donated is the shared HL109 vocabulary — the static
        # rule exempts this window, the runtime guard counts it.
        with consumes_donated("spf.prev.redeposit"):
            self._prev_one[key] = out
            while len(self._prev_one) > self.prev_capacity:
                self._prev_one.pop(next(iter(self._prev_one)))

    def _track_compile(self, kind: str, engine: str, *shape) -> bool:
        """Returns True when this (engine, shape) bucket is fresh — a
        real XLA compile, and the moment to capture its cost analysis.
        ``engine`` is the fixpoint formulation actually dispatched (the
        tuner may differ from the pinned one_engine per shape bucket).
        Callers append the process-mesh identity to ``shape``: the same
        shapes under a different sharding are a different XLA program,
        and the cost-analysis table keys on the same signature."""
        sig = (kind, engine, *shape)
        if sig in self._compiled_shapes:
            _JIT_HITS.labels(kind=kind).inc()
            return False
        self._compiled_shapes.add(sig)
        _JIT_COMPILES.labels(kind=kind).inc()
        return True

    def _full_mask(self, topo: Topology, edge_mask) -> np.ndarray:
        if edge_mask is None:
            return np.ones(topo.n_edges, bool)
        return np.asarray(edge_mask, bool)

    # Public entry points run under the circuit breaker: an XLA failure
    # or deadline overrun transparently re-runs the batch on the scalar
    # oracle (RIB output unchanged by construction — the parity suites
    # pin the two backends bit-identical), and repeated failures open
    # the circuit so a dead device stops being retried per-SPF.

    @staticmethod
    def _noted_fallback(fn):
        """Run the scalar fallback and tag the active convergence
        events with ``fallback`` (AFTER the oracle's own ``scalar``
        note, so the sticky fallback verdict is what the event closes
        with — storm distributions split on it)."""
        try:
            return fn()
        finally:
            convergence.note_dispatch("spf", "fallback")

    def compute(self, topo, edge_mask=None, multipath_k: int = 1):
        kp = mp_pad(multipath_k)
        if self._use_partitioned(topo):
            return self.compute_partitioned(
                topo, edge_mask, multipath_k=kp
            )
        return self.breaker.call(
            lambda: self._device_compute(topo, edge_mask, kp),
            lambda: self._noted_fallback(
                lambda: self._oracle.compute(
                    topo, edge_mask, multipath_k=kp
                )
            ),
            context="spf.one",
        )

    def compute_whatif(self, topo, edge_masks, multipath_k: int = 1):
        kp = mp_pad(multipath_k)
        if self._use_partitioned(topo):
            return self.breaker.call(
                lambda: [
                    self._device_partitioned(topo, m, kp)
                    for m in edge_masks
                ],
                lambda: self._noted_fallback(
                    lambda: self._oracle.compute_whatif(
                        topo, edge_masks, multipath_k=kp
                    )
                ),
                context="spf.whatif",
            )
        return self.breaker.call(
            lambda: self._device_whatif(topo, edge_masks, kp),
            lambda: self._noted_fallback(
                lambda: self._oracle.compute_whatif(
                    topo, edge_masks, multipath_k=kp
                )
            ),
            context="spf.whatif",
        )

    # -- partitioned dispatch (ISSUE 15) --------------------------------

    def _use_partitioned(self, topo) -> bool:
        return (
            self.partition_threshold is not None
            and topo.n_vertices >= self.partition_threshold
            and self.engine != "blocked"
        )

    def compute_partitioned(self, topo, edge_mask=None, multipath_k: int = 1):
        """Explicit partitioned dispatch (auto-routed from ``compute``
        when ``partition_threshold`` arms it) — breaker-guarded with
        the bit-identical scalar oracle as the fallback arm, exactly
        like the monolithic paths."""
        kp = mp_pad(multipath_k)
        return self.breaker.call(
            lambda: self._device_partitioned(topo, edge_mask, kp),
            lambda: self._noted_fallback(
                lambda: self._oracle.compute(
                    topo, edge_mask, multipath_k=kp
                )
            ),
            context="spf.partitioned",
        )

    def _part_engine_for(self):
        if self._part_engine is None:
            from holo_tpu.ops.partition import PartitionedSpfEngine

            self._part_engine = PartitionedSpfEngine(
                max_iters=self.max_iters
            )
        return self._part_engine

    def _part_key(self, topo, n_atoms: int) -> tuple:
        return (self._part_ns, int(topo.root), int(n_atoms), _mesh_key())

    def partition_residents(self) -> list:
        """This backend's live partitioned residents (tests/bench)."""
        from holo_tpu.ops.spf_engine import shared_graph_cache

        return list(
            shared_graph_cache()
            .partitioned_entries(self._part_ns)
            .values()
        )

    def _part_resident_for(self, topo, n_atoms: int, need_edge_ids: bool):
        """The partitioned resident serving this topology's chain,
        re-marshaled when the chain broke (or never existed).  Returns
        ``(resident, how)`` with how in {'hit', 'miss'} — the delta
        path claims the resident separately."""
        from holo_tpu.ops.spf_engine import shared_graph_cache

        eng = self._part_engine_for()
        key = self._part_key(topo, n_atoms)
        cache = shared_graph_cache()
        res = cache.get_partitioned(key)
        if (
            res is not None
            and res.topo_key == topo.cache_key
            and not (need_edge_ids and res.ids_stale)
        ):
            return res, "hit"
        res = eng.marshal(
            topo,
            n_atoms,
            n_parts=self.partition_parts,
            max_part=(
                None
                if self.partition_parts is not None
                else self.partition_max_part
            ),
        )
        cache.put_partitioned(key, res)
        return res, "miss"

    def _device_partitioned(self, topo, edge_mask, kp: int = 1):
        faults.crashpoint("spf.dispatch")
        mesh = _mesh()
        if mesh is not None:
            faults.crashpoint("spf.shard")
        from holo_tpu.ops.spf_engine import shared_graph_cache

        eng = self._part_engine_for()
        n_atoms = max(self.n_atoms, topo.n_atoms())
        t0 = profiling.clock()
        obucket = self._obs_bucket(topo, 1, kp, None)
        key = self._part_key(topo, n_atoms)
        result = None
        how = None
        delta = getattr(topo, "delta_base", None)
        with profiling.dispatch_context(
            kind="partitioned", engine="partitioned", bucket=obucket
        ), telemetry.span(
            "spf.dispatch", kind="partitioned", backend="tpu"
        ):
            res = shared_graph_cache().get_partitioned(key)
            if (
                edge_mask is None
                and delta is not None
                and self.incremental
                and res is not None
            ):
                # Bounded re-solve: affected partitions + skeleton.
                with profiling.stage("spf.partitioned", "delta"):
                    served = eng.try_delta(topo, res, kp)
                if served is not None:
                    result, _info = served
                    note_delta(delta.kind, "partitioned-incremental")
            if result is None:
                with profiling.stage("spf.partitioned", "marshal"):
                    with sanctioned_transfer("spf.partition.marshal"):
                        res, how = self._part_resident_for(
                            topo, n_atoms, edge_mask is not None
                        )
                with profiling.stage("spf.partitioned", "solve"):
                    result = eng.solve(topo, res, edge_mask, kp)
                if delta is not None and edge_mask is None:
                    note_delta(delta.kind, "partitioned-full")
            mpkw = {
                f: result[f]
                for f in (
                    "parents", "pdist", "pweight", "npaths", "nh_weights"
                )
                if f in result
            }
            out = SpfResult(
                dist=result["dist"],
                parent=result["parent"],
                hops=result["hops"],
                nexthop_words=result["nexthop_words"],
                **mpkw,
            )
        t1 = profiling.clock()
        _DISPATCH_SECONDS.labels(backend="tpu", kind="partitioned").observe(
            t1 - t0
        )
        kind = "one" if edge_mask is None else "whatif"
        if edge_mask is None and how == "hit":
            # Feed the tuner's partitioned rows (same shape key as the
            # kind=one monolithic walls, so partitioned_advantage
            # compares like with like) — FULL solves on a WARM resident
            # only: a per-mask what-if wall, a bounded delta re-solve,
            # or a marshal-miss dispatch (one-off re-marshal + XLA
            # compile wall) is not comparable to the kind=one
            # steady-state median, which excludes the same costs.
            from holo_tpu.pipeline.tuner import active_tuner

            tun = active_tuner()
            if tun is not None:
                tun.observe_partitioned(
                    self._depth_bucket(topo, kp), t1 - t0
                )
        _BATCH_SCENARIOS.labels(kind=kind).inc()
        if mesh is not None:
            _SHARD_DISPATCHES.labels(kind=kind).inc()
        convergence.note_dispatch("spf", "device")
        return out

    def partition_stats(self) -> dict:
        """Resident summaries for the telemetry leaf / bench rows."""
        from holo_tpu.ops.spf_engine import shared_graph_cache

        return {
            str(k[1:]): r.stats()
            for k, r in shared_graph_cache()
            .partitioned_entries(self._part_ns)
            .items()
        }

    def compute_multiroot(self, topo, roots: np.ndarray) -> "MultiRootResult":
        return self.breaker.call(
            lambda: self._device_multiroot(topo, roots),
            lambda: self._noted_fallback(
                lambda: self._oracle.compute_multiroot(topo, roots)
            ),
            context="spf.multiroot",
        )

    def _device_compute(self, topo, edge_mask=None, kp: int = 1):
        faults.crashpoint("spf.dispatch")
        mesh = _mesh()
        if mesh is not None:
            # The shard-dispatch chaos seam: a device lost from the
            # mesh / an XLA failure on any shard surfaces here and the
            # breaker serves the WHOLE batch from the scalar oracle.
            faults.crashpoint("spf.shard")
        if self.engine == "blocked" and kp == 1:
            # The blocked-Pallas experiment has no multipath planes;
            # kp > 1 rides the gather-path multipath kernel below.
            res = self._whatif_blocked(
                topo, self._full_mask(topo, edge_mask)[None, :]
            )
            if res is not None:
                return res[0]
        if edge_mask is None:
            res = self._try_incremental(topo, kp)
            if res is not None:
                return res
        t0 = profiling.clock()
        engine, bucket = self._pick_engine("one", topo, kp=kp)
        obucket = self._obs_bucket(topo, 1, kp, bucket)
        with profiling.dispatch_context(
            kind="one", engine=engine, bucket=obucket
        ), telemetry.span("spf.dispatch", kind="one", backend="tpu"):
            # THE sanctioned marshal boundary: host graph + root + mask
            # move to device here and nowhere else (transfer_guard
            # "disallow" everywhere outside these windows).
            with profiling.stage("spf.one", "marshal"):
                with sanctioned_transfer("spf.one.marshal"):
                    # A REAL scenario mask gathers through in_edge_id:
                    # structurally delta-updated residents must rebuild
                    # for it (the mask-free call keeps riding them).
                    g = self.prepare(
                        topo, need_edge_ids=edge_mask is not None
                    )
                    remarshal = self._last_prepare_how == "miss"
                    mask = self._full_mask(topo, edge_mask)
                    tt = rr = None
                    if engine in _TROPICAL_ENGINES:
                        tt, rr = self._trop_operands(topo, g, edge_mask)
                    step, args = self._one_step(
                        engine, kp, g, tt, topo.root, mask, rr
                    )
                    sig = (
                        g.in_src.shape, g.direct_nh_words.shape[2],
                        topo.n_edges, _mesh_key(), engine, kp,
                        None if tt is None else tt.tiles.shape,
                        None if rr is None else rr.shape,
                    )
                    fresh = self._track_compile("one", engine, *sig)
                    out = step(*args)
            if fresh:
                entry = profiling.record_cost(
                    "spf.one", step, *args, shape_sig=sig,
                )
                self._tuner_cost("one", bucket, engine, entry)
                self._obs_cost("spf.one", "one", engine, obucket, entry)
            with profiling.stage("spf.one", "device"):
                faults.delaypoint("spf.dispatch")
                with profiling.annotation("spf.one.device"):
                    if not profiling.device_stages("spf.one", out):
                        profiling.sync(out)
            t1 = profiling.clock()
            with profiling.stage("spf.one", "readback"):
                with sanctioned_transfer("spf.one.unmarshal"):
                    sp = out[0] if kp > 1 else out
                    dist, parent, hops, nh = _host_tensors(
                        sp, topo.n_vertices
                    )
                    mpkw = _host_mp(out[1], topo.n_vertices) if kp > 1 else {}
                    res = SpfResult(
                        dist=dist, parent=parent, hops=hops,
                        nexthop_words=nh, **mpkw,
                    )
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="one").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="one").observe(t2 - t0)
        _BATCH_SCENARIOS.labels(kind="one").inc()
        if mesh is not None:
            _SHARD_DISPATCHES.labels(kind="one").inc()
        convergence.note_dispatch("spf", "device")
        if not fresh:
            # Fresh-compile dispatches carry one-off XLA compile wall:
            # feeding them to the tuner would let compile spikes outvote
            # the steady-state cost the decision is about.
            self._tuner_observe("one", bucket, engine, t2 - t0)
        if remarshal and edge_mask is None:
            # A full re-marshal paid: the depth tuner's "full" arm (the
            # cost a deeper delta chain would have avoided).
            self._tuner_depth_observe(topo, "full", t2 - t0, kp)
        if edge_mask is None and self.incremental:
            # Disarmed backends skip retention: they could never
            # consume the tensors, and the incremental_overhead gate
            # compares exactly this armed-vs-disarmed difference.
            self._remember(
                topo, max(self.n_atoms, topo.n_atoms()), out, kp
            )
        return res

    def _try_incremental(self, topo, kp: int = 1) -> SpfResult | None:
        """DeltaPath dispatch: the resident device graph absorbs the
        topology delta in place and the incremental kernel recomputes
        seeded from the previous run's tensors — O(affected) rounds and
        a delta-sized transfer instead of a full marshal.  Returns None
        (→ full-rebuild path) when the chain cannot be served; every
        disposition lands in ``holo_spf_delta_total{kind,path}``.
        ``kp > 1`` rides the widened incremental kernel, seeded from
        (and donating) the chain's retained multipath tensors."""
        delta = getattr(topo, "delta_base", None)
        if delta is None or not self.incremental:
            return None
        n_atoms = max(self.n_atoms, topo.n_atoms())
        prev_key = (
            *delta.base_key, int(n_atoms), int(topo.root), _mesh_key(),
            int(kp),
        )
        prev = self._prev_one.get(prev_key)
        if prev is None:
            note_delta(delta.kind, "full-no-prev")
            return None
        t0 = profiling.clock()
        obucket = self._obs_bucket(topo, 1, kp, None)
        with profiling.dispatch_context(
            kind="delta", engine="incr", bucket=obucket
        ), telemetry.span(
            "spf.dispatch", kind="one", backend="tpu", mode="delta"
        ):
            with profiling.stage("spf.one", "delta"):
                # The delta-sized sanctioned boundary: scatter/seed
                # rows move host->device here — the full-graph marshal
                # transfer is exactly what this path avoids.  The
                # apply (host lowering + donated scatter) runs INSIDE
                # the dispatch timer and the delta stage so the
                # full-vs-incremental _DISPATCH_SECONDS comparison
                # carries symmetric costs (the full path's timer
                # includes its marshal).
                with sanctioned_transfer("spf.one.delta"):
                    from holo_tpu.ops.spf_engine import _pad_pow2

                    g, how = shared_graph_cache().get(
                        topo, n_atoms, allow_delta=True
                    )
                    if how == "miss":
                        # The cache refused the delta (depth/overflow/
                        # missing base — reasons already counted in
                        # holo_spf_delta_total) and paid a full
                        # re-marshal: this dispatch belongs to the
                        # full-rebuild path, which now hits the fresh
                        # entry; its prepare() alone counts the
                        # per-dispatch _GRAPH_CACHE disposition.  (The
                        # rare aborted mode=delta span records the
                        # attempt; path="incremental" must mean the
                        # resident actually served it.)
                        return None
                    _GRAPH_CACHE.labels(result=how).inc()
                    seeds = delta.seed_rows()
                    pad = _pad_pow2(seeds.shape[0])
                    # Pad sentinel = the resident's PADDED row count
                    # (node-sharded residents pad rows past N): truly
                    # out of range for the aff-scatter's mode="drop".
                    seeds_p = np.full(
                        pad, int(g.in_src.shape[0]), np.int32
                    )
                    seeds_p[: seeds.shape[0]] = seeds
                    step, out, trop, tt, sig, fresh = self._incr_step(
                        topo, g, n_atoms, kp, pad, prev_key, prev,
                        seeds_p,
                    )
            if fresh:
                cost_args = self._incr_cost_args(
                    trop, tt, g, topo.root, out, seeds_p, kp
                )
                entry = profiling.record_cost(
                    "spf.delta", step, *cost_args, shape_sig=sig,
                )
                self._obs_cost("spf.one", "delta", "incr", obucket, entry)
            with profiling.stage("spf.one", "device"):
                faults.delaypoint("spf.dispatch")
                # Donation-guard force boundary: a leaked donated alias
                # in the output set fails HERE, named, not as a generic
                # deleted-array error inside the readback.
                assert_live("spf.one.readback", out)
                with profiling.annotation("spf.one.delta.device"):
                    if not profiling.device_stages("spf.one", out):
                        profiling.sync(out)
            t1 = profiling.clock()
            with profiling.stage("spf.one", "readback"):
                with sanctioned_transfer("spf.one.unmarshal"):
                    sp = out[0] if kp > 1 else out
                    dist, parent, hops, nh = _host_tensors(
                        sp, topo.n_vertices
                    )
                    mpkw = _host_mp(out[1], topo.n_vertices) if kp > 1 else {}
                    res = SpfResult(
                        dist=dist, parent=parent, hops=hops,
                        nexthop_words=nh, **mpkw,
                    )
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="one").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="one").observe(t2 - t0)
        _BATCH_SCENARIOS.labels(kind="one").inc()
        if _mesh() is not None:
            _SHARD_DISPATCHES.labels(kind="one").inc()
        convergence.note_dispatch("spf", "device")
        note_delta(delta.kind, "incremental")
        # The depth tuner's "delta" arm: what an in-place update +
        # seeded recompute actually costs at this shape.
        self._tuner_depth_observe(topo, "delta", t2 - t0, kp)
        self._remember(topo, n_atoms, out, kp)
        return res

    def prepare_blocked(self, topo: Topology):
        """Marshal (and cache) the blocked planes; None if unsupported.

        The cache key includes the root: unlike the gather planes, the
        blocked planes bake the root in (BFS permutation + rootp).
        """
        key = (*topo.cache_key, topo.root)
        if key in self._blocked_cache:
            return self._blocked_cache[key]
        from holo_tpu.ops.blocked_spf import marshal_block_spf

        try:
            g = marshal_block_spf(topo, n_atoms=max(self.n_atoms, topo.n_atoms()))
        except ValueError:
            g = None  # preconditions unmet: gather engine handles it
        self._blocked_cache[key] = g
        while len(self._blocked_cache) > 4:
            self._blocked_cache.pop(next(iter(self._blocked_cache)))
        return g

    def _whatif_blocked(self, topo, edge_masks):
        from holo_tpu.ops.blocked_spf import failed_edges_perm, whatif_spf_blocked

        with sanctioned_transfer("spf.blocked.marshal"):
            g = self.prepare_blocked(topo)
            if g is None:
                return None
            try:
                fdst, fid = failed_edges_perm(
                    np.asarray(g.orig2perm), topo,
                    np.asarray(edge_masks, bool),
                )
            except ValueError:
                return None  # too many failed edges per scenario
        if self._jit_blocked is None:
            from functools import partial

            self._jit_blocked = jax.jit(
                partial(whatif_spf_blocked, max_iters=self.max_iters)
            )
        t0 = profiling.clock()
        bl_bucket = self._obs_bucket(topo, len(edge_masks), 1, None)
        with profiling.dispatch_context(
            kind="blocked", engine="blocked", bucket=bl_bucket
        ), telemetry.span(
            "spf.dispatch", kind="blocked", backend="tpu",
            batch=len(edge_masks),
        ):
            with profiling.stage("spf.blocked", "marshal"):
                fresh = self._track_compile(
                    "blocked", "blocked", fdst.shape, fid.shape
                )
                with sanctioned_transfer("spf.blocked.dispatch"):
                    out = self._jit_blocked(g, fdst, fid)
            if fresh:
                entry = profiling.record_cost(
                    "spf.blocked", self._jit_blocked, g, fdst, fid,
                    shape_sig=(fdst.shape, fid.shape),
                )
                self._obs_cost(
                    "spf.blocked", "blocked", "blocked", bl_bucket, entry
                )
            with profiling.stage("spf.blocked", "device"):
                with profiling.annotation("spf.blocked.device"):
                    profiling.sync(out)
            t1 = profiling.clock()
            with profiling.stage("spf.blocked", "readback"):
                with sanctioned_transfer("spf.blocked.unmarshal"):
                    dist, parent, hops, nh = (
                        np.asarray(out.dist),
                        np.asarray(out.parent),
                        np.asarray(out.hops),
                        np.asarray(out.nexthops),
                    )
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="blocked").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="blocked").observe(t2 - t0)
        _BATCH_SCENARIOS.labels(kind="blocked").inc(dist.shape[0])
        return [
            SpfResult(dist=dist[i], parent=parent[i], hops=hops[i], nexthop_words=nh[i])
            for i in range(dist.shape[0])
        ]

    def _device_whatif(self, topo, edge_masks, kp: int = 1):
        faults.crashpoint("spf.dispatch")
        mesh = _mesh()
        if mesh is not None:
            faults.crashpoint("spf.shard")
        if self.engine == "blocked" and kp == 1:
            # The blocked-Pallas experiment marshals its own planes and
            # stays single-device; the mesh path rides the gather
            # engines (the headline since r02).
            res = self._whatif_blocked(topo, edge_masks)
            if res is not None:
                return res
        B = len(edge_masks)
        t0 = profiling.clock()
        engine, bucket = self._pick_engine("whatif", topo, B, kp=kp)
        obucket = self._obs_bucket(topo, B, kp, bucket)
        with profiling.dispatch_context(
            kind="whatif", engine=engine, bucket=obucket
        ), telemetry.span(
            "spf.dispatch", kind="whatif", backend="tpu", batch=B,
        ):
            with profiling.stage("spf.whatif", "marshal"):
                with sanctioned_transfer("spf.whatif.marshal"):
                    # What-if masks gather through in_edge_id: entries
                    # whose ids went stale under a structural delta are
                    # rebuilt (need_edge_ids).
                    g = self.prepare(topo, need_edge_ids=True)
                    masks = np.asarray(edge_masks, bool)
                    tt = rr = None
                    if engine == "tropical":
                        tt = shared_graph_cache().get_tropical(
                            topo, max(self.n_atoms, topo.n_atoms())
                        )
                        rr = repair_rows_host(
                            topo.edge_dst, masks, int(g.in_src.shape[0])
                        )
                    if mesh is not None:
                        # THE sharded scenario axis: masks placed
                        # batch-sharded (padded to the axis size with
                        # no-failure rows), outputs pinned to the batch
                        # sharding — GSPMD fans the B scenarios out
                        # over the mesh's batch devices while the
                        # cache-resident graph planes ride row-sharded
                        # over node (the mesh.py layout contract).
                        from holo_tpu.parallel.mesh import (
                            shard_repair_rows,
                            shard_scenarios,
                        )

                        masks_dev = shard_scenarios(mesh, masks)
                        if engine == "tropical":
                            rr = shard_repair_rows(
                                mesh, rr, int(g.in_src.shape[0])
                            )
                            step = self._sharded_trop_whatif(mesh)
                        elif kp > 1:
                            step = self._sharded_mp_whatif(mesh, kp)
                        else:
                            step = self._sharded_whatif(mesh, engine)
                    else:
                        masks_dev = masks
                        if engine == "tropical":
                            step = self._jit_trop_batch
                        elif kp > 1:
                            step = self._jit_mp_batch_for(kp)
                        else:
                            step = self._jit_batch_for(engine)
                    args = (
                        (g, tt, topo.root, masks_dev, rr)
                        if engine == "tropical"
                        else (g, topo.root, masks_dev)
                    )
                    sig = (
                        g.in_src.shape, g.direct_nh_words.shape[2],
                        masks_dev.shape, _mesh_key(), engine, kp,
                        None if tt is None else tt.tiles.shape,
                        None if rr is None else rr.shape,
                    )
                    fresh = self._track_compile("whatif", engine, *sig)
                    out = step(*args)
            if fresh:
                entry = profiling.record_cost(
                    "spf.whatif", step, *args, shape_sig=sig,
                )
                self._tuner_cost("whatif", bucket, engine, entry)
                self._obs_cost(
                    "spf.whatif", "whatif", engine, obucket, entry
                )
            with profiling.stage("spf.whatif", "device"):
                faults.delaypoint("spf.dispatch")
                with profiling.annotation("spf.whatif.device"):
                    if not profiling.device_stages("spf.whatif", out):
                        profiling.sync(out)
            t1 = profiling.clock()
            # One bulk device→host transfer per plane: per-scenario slicing
            # of device arrays would pay the host round-trip B×4 times.
            with profiling.stage("spf.whatif", "readback"):
                with sanctioned_transfer("spf.whatif.unmarshal"):
                    sp = out[0] if kp > 1 else out
                    dist, parent, hops, nh = _host_tensors(
                        sp, topo.n_vertices
                    )
                    mpkw = _host_mp(out[1], topo.n_vertices) if kp > 1 else {}
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="whatif").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="whatif").observe(t2 - t0)
        _BATCH_SCENARIOS.labels(kind="whatif").inc(B)
        if mesh is not None:
            _SHARD_DISPATCHES.labels(kind="whatif").inc()
        convergence.note_dispatch("spf", "device")
        if not fresh:  # see _device_compute: no compile-spike samples
            self._tuner_observe("whatif", bucket, engine, t2 - t0)
        # Slice off the batch-pad rows (sharded dispatch pads B up to a
        # multiple of the mesh batch axis) — [:B] is a no-op otherwise.
        return [
            SpfResult(
                dist=dist[i], parent=parent[i], hops=hops[i],
                nexthop_words=nh[i],
                **{f: plane[i] for f, plane in mpkw.items()},
            )
            for i in range(B)
        ]

    def _device_multiroot(self, topo, roots: np.ndarray) -> "MultiRootResult":
        """Distances/parents/hops from many roots (one device program).

        Next-hop bitmasks are intentionally NOT returned: direct atoms are
        marshaled relative to ``topo.root``, so they are meaningless for any
        other root.  Multi-root users (IS-IS flooding reduction, TI-LFA)
        need the SPT shape only.
        """
        faults.crashpoint("spf.dispatch")
        mesh = _mesh()
        if mesh is not None:
            faults.crashpoint("spf.shard")
        R = len(roots)
        t0 = profiling.clock()
        # The multiroot program has no tuner kind of its own: it rides
        # the tropical tiles when the backend is pinned tropical (the
        # root axis becomes the contraction's dense lanes), else the
        # proven seq formulation.
        mr_engine = "tropical" if self.one_engine == "tropical" else "seq"
        mr_bucket = self._obs_bucket(topo, R, 1, None)
        with profiling.dispatch_context(
            kind="multiroot", engine=mr_engine, bucket=mr_bucket
        ), telemetry.span(
            "spf.dispatch", kind="multiroot", backend="tpu", roots=R
        ):
            with profiling.stage("spf.multiroot", "marshal"):
                with sanctioned_transfer("spf.multiroot.marshal"):
                    g = self.prepare(topo)
                    tt = None
                    rr = np.zeros(0, np.int32)
                    if mr_engine == "tropical":
                        tt, rr = self._trop_operands(topo, g)
                    roots_i32 = np.asarray(roots, np.int32)
                    if mesh is not None:
                        # The all-roots plane rides the same batch
                        # axis: roots sharded over it (padded with
                        # root 0; pad rows sliced off below).
                        from holo_tpu.parallel.mesh import shard_roots

                        roots_dev = shard_roots(mesh, roots_i32)
                        step = (
                            self._sharded_trop_multiroot(mesh)
                            if mr_engine == "tropical"
                            else self._sharded_multiroot(mesh)
                        )
                    else:
                        roots_dev = roots_i32
                        step = (
                            self._jit_trop_multiroot
                            if mr_engine == "tropical"
                            else self._jit_multiroot
                        )
                    sig = (
                        g.in_src.shape, g.direct_nh_words.shape[2],
                        roots_dev.shape[0], topo.n_edges, _mesh_key(),
                        None if tt is None else tt.tiles.shape,
                    )
                    fresh = self._track_compile(
                        "multiroot", mr_engine, *sig
                    )
                    mask = np.ones(topo.n_edges, bool)
                    args = (
                        (g, tt, roots_dev, mask, rr)
                        if mr_engine == "tropical"
                        else (g, roots_dev, mask)
                    )
                    out = step(*args)
            if fresh:
                entry = profiling.record_cost(
                    "spf.multiroot", step, *args, shape_sig=sig,
                )
                self._obs_cost(
                    "spf.multiroot", "multiroot", mr_engine, mr_bucket,
                    entry,
                )
            with profiling.stage("spf.multiroot", "device"):
                with profiling.annotation("spf.multiroot.device"):
                    if not profiling.device_stages("spf.multiroot", out):
                        profiling.sync(out)
            t1 = profiling.clock()
            with profiling.stage("spf.multiroot", "readback"):
                with sanctioned_transfer("spf.multiroot.unmarshal"):
                    dist, parent, hops, _nh = _host_tensors(
                        out, topo.n_vertices
                    )
                    res = MultiRootResult(
                        dist=dist[:R], parent=parent[:R], hops=hops[:R]
                    )
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="multiroot").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="multiroot").observe(t2 - t0)
        _BATCH_SCENARIOS.labels(kind="multiroot").inc(R)
        if mesh is not None:
            _SHARD_DISPATCHES.labels(kind="multiroot").inc()
        convergence.note_dispatch("spf", "device")
        return res

    # -- split-phase dispatch (the pipeline seam, ISSUE 9) --------------
    #
    # launch_one() performs everything host-side-blocking (chaos seams,
    # marshal or DeltaPath in-place update + donation, the ASYNC jit
    # call) and returns an in-flight handle; finish_one() pays the
    # device completion + readback and the accounting.  Between the
    # two, the device executes while the pipeline worker launches the
    # next entry — the overlap the double buffer exists for.  The
    # phases emit separate `spf.launch` / `spf.finish` spans instead of
    # one enclosing `spf.dispatch` span: the worker interleaves other
    # items' phases on its one thread, and a straddling span would
    # cross the tracer's thread-local nesting.  Results are bit-
    # identical to _device_compute by construction (same jits, same
    # readback; parity-gated in tests/test_pipeline.py).

    def launch_one(self, topo, edge_mask=None, multipath_k: int = 1) -> "_InFlightOne":
        faults.crashpoint("spf.dispatch")
        mesh = _mesh()
        if mesh is not None:
            faults.crashpoint("spf.shard")
        kp = mp_pad(multipath_k)
        n_atoms = max(self.n_atoms, topo.n_atoms())
        if edge_mask is None:
            h = self._launch_incremental(topo, n_atoms, kp)
            if h is not None:
                return h
        t0 = profiling.clock()
        engine, bucket = self._pick_engine("one", topo, kp=kp)
        obucket = self._obs_bucket(topo, 1, kp, bucket)
        with profiling.dispatch_context(
            kind="one", engine=engine, bucket=obucket
        ), telemetry.span(
            "spf.launch", kind="one", backend="tpu", engine=engine
        ):
            with profiling.stage("spf.one", "marshal"):
                with sanctioned_transfer("spf.one.marshal"):
                    g = self.prepare(
                        topo, need_edge_ids=edge_mask is not None
                    )
                    remarshal = self._last_prepare_how == "miss"
                    mask = self._full_mask(topo, edge_mask)
                    tt = rr = None
                    if engine in _TROPICAL_ENGINES:
                        tt, rr = self._trop_operands(topo, g, edge_mask)
                    step, args = self._one_step(
                        engine, kp, g, tt, topo.root, mask, rr
                    )
                    sig = (
                        g.in_src.shape, g.direct_nh_words.shape[2],
                        topo.n_edges, _mesh_key(), engine, kp,
                        None if tt is None else tt.tiles.shape,
                        None if rr is None else rr.shape,
                    )
                    fresh = self._track_compile("one", engine, *sig)
                    out = step(*args)
            if fresh:
                entry = profiling.record_cost(
                    "spf.one", step, *args, shape_sig=sig,
                )
                self._tuner_cost("one", bucket, engine, entry)
                self._obs_cost("spf.one", "one", engine, obucket, entry)
        return _InFlightOne(
            out=out, topo=topo, t0=t0, engine=engine, bucket=bucket,
            mode="full", n_atoms=n_atoms, kp=kp,
            remember=edge_mask is None and self.incremental,
            sharded=mesh is not None,
            remarshal=remarshal and edge_mask is None,
            fresh=fresh, obucket=obucket,
            launch_s=profiling.clock() - t0,
        )

    def _launch_incremental(
        self, topo, n_atoms, kp: int = 1
    ) -> "_InFlightOne | None":
        """Split-phase DeltaPath launch: same contract (and the same
        donation discipline — the previous tensors leave ``_prev_one``
        BEFORE the kernel call) as :meth:`_try_incremental`; the
        pipeline's per-key ownership handoff guarantees no queued delta
        for this chain launches until finish_one re-deposited the new
        tensors."""
        delta = getattr(topo, "delta_base", None)
        if delta is None or not self.incremental:
            return None
        prev_key = (
            *delta.base_key, int(n_atoms), int(topo.root), _mesh_key(),
            int(kp),
        )
        prev = self._prev_one.get(prev_key)
        if prev is None:
            note_delta(delta.kind, "full-no-prev")
            return None
        t0 = profiling.clock()
        obucket = self._obs_bucket(topo, 1, kp, None)
        with profiling.dispatch_context(
            kind="delta", engine="incr", bucket=obucket
        ), telemetry.span(
            "spf.launch", kind="one", backend="tpu", mode="delta"
        ):
            with profiling.stage("spf.one", "delta"):
                with sanctioned_transfer("spf.one.delta"):
                    from holo_tpu.ops.spf_engine import _pad_pow2

                    g, how = shared_graph_cache().get(
                        topo, n_atoms, allow_delta=True
                    )
                    if how == "miss":
                        # Cache refused the delta (reasons already
                        # counted): this dispatch belongs to the full
                        # path, which hits the fresh entry.
                        return None
                    _GRAPH_CACHE.labels(result=how).inc()
                    seeds = delta.seed_rows()
                    pad = _pad_pow2(seeds.shape[0])
                    seeds_p = np.full(
                        pad, int(g.in_src.shape[0]), np.int32
                    )
                    seeds_p[: seeds.shape[0]] = seeds
                    step, out, trop, tt, sig, fresh = self._incr_step(
                        topo, g, n_atoms, kp, pad, prev_key, prev,
                        seeds_p,
                    )
            if fresh:
                cost_args = self._incr_cost_args(
                    trop, tt, g, topo.root, out, seeds_p, kp
                )
                entry = profiling.record_cost(
                    "spf.delta", step, *cost_args, shape_sig=sig,
                )
                self._obs_cost("spf.one", "delta", "incr", obucket, entry)
        return _InFlightOne(
            out=out, topo=topo, t0=t0, engine="incr", bucket=None,
            mode="delta", delta_kind=delta.kind, n_atoms=n_atoms, kp=kp,
            remember=True, sharded=_mesh() is not None, obucket=obucket,
            launch_s=profiling.clock() - t0,
        )

    def finish_one(self, h: "_InFlightOne") -> SpfResult:
        t_fs = profiling.clock()
        with profiling.dispatch_context(
            kind="delta" if h.mode == "delta" else "one",
            engine=h.engine, bucket=h.obucket,
        ), telemetry.span(
            "spf.finish", kind="one", backend="tpu", mode=h.mode
        ):
            with profiling.stage("spf.one", "device"):
                faults.delaypoint("spf.dispatch")
                # Donation-guard force boundary (see _try_incremental).
                assert_live("spf.one.readback", h.out)
                with profiling.annotation("spf.one.device"):
                    if not profiling.device_stages("spf.one", h.out):
                        profiling.sync(h.out)
            t1 = profiling.clock()
            with profiling.stage("spf.one", "readback"):
                with sanctioned_transfer("spf.one.unmarshal"):
                    sp = h.out[0] if h.kp > 1 else h.out
                    dist, parent, hops, nh = _host_tensors(
                        sp, h.topo.n_vertices
                    )
                    mpkw = (
                        _host_mp(h.out[1], h.topo.n_vertices)
                        if h.kp > 1
                        else {}
                    )
                    res = SpfResult(
                        dist=dist, parent=parent, hops=hops,
                        nexthop_words=nh, **mpkw,
                    )
        t2 = profiling.clock()
        _TRANSFER_SECONDS.labels(kind="one").observe(t2 - t1)
        _DISPATCH_SECONDS.labels(backend="tpu", kind="one").observe(
            t2 - h.t0
        )
        _BATCH_SCENARIOS.labels(kind="one").inc()
        if h.sharded:
            _SHARD_DISPATCHES.labels(kind="one").inc()
        convergence.note_dispatch("spf", "device")
        # Tuner samples exclude the parked interval between the two
        # phases (see _InFlightOne.launch_s); the dispatch histogram
        # above keeps the true end-to-end wall.
        unparked = h.launch_s + (t2 - t_fs)
        if h.mode == "delta":
            note_delta(h.delta_kind, "incremental")
            self._tuner_depth_observe(h.topo, "delta", unparked, h.kp)
        else:
            if not h.fresh:  # see _device_compute: no compile spikes
                self._tuner_observe("one", h.bucket, h.engine, unparked)
            if h.remarshal:
                self._tuner_depth_observe(h.topo, "full", unparked, h.kp)
        if h.remember and self.incremental:
            self._remember(h.topo, h.n_atoms, h.out, h.kp)
        return res


# -- jaxpr-audit registrations (HL3xx) ----------------------------------
# The per-instance jit caches above (_jit_one_for/_jit_incr/_jit_mp_*)
# are the gather-path dispatch seams; each registers an equivalent
# module-level construction (same kernel fn, same arg order, same
# donate_argnums, max_iters=None) so the audit proves the contracts the
# instance jits rely on.  Thunks run only when the audit arms.
from holo_tpu.analysis.kernels import register_kernel as _register_kernel  # noqa: E402


def _audit_specs():
    from holo_tpu.ops.spf_engine import (
        _AUDIT_B,
        _AUDIT_E,
        audit_graph_spec,
        audit_mp_spec,
        audit_spf_spec,
    )
    import jax.numpy as jnp

    s = jax.ShapeDtypeStruct
    return {
        "g": audit_graph_spec(),
        "sp": audit_spf_spec(),
        "mp": audit_mp_spec(),
        "root": s((), jnp.int32),
        "roots": s((_AUDIT_B,), jnp.int32),
        "mask": s((_AUDIT_E,), jnp.bool_),
        "masks": s((_AUDIT_B, _AUDIT_E), jnp.bool_),
        "seeds": s((256,), jnp.int32),
    }


def _register_one_engines() -> None:
    from holo_tpu.ops.spf_engine import _ONE_ENGINES

    for eng in sorted(_ONE_ENGINES):
        _register_kernel(
            f"spf.one.{eng}",
            builder=(
                # The jit lives inside an inert audit thunk: it is
                # built at most once per engine, when the HL3xx audit
                # arms — never on the dispatch path this rule guards.
                # holo-lint: disable=HL103
                lambda e=eng: jax.jit(
                    lambda g, r, m, _e=e: __import__(
                        "holo_tpu.ops.spf_engine", fromlist=["_ONE_ENGINES"]
                    )._ONE_ENGINES[_e](g, r, m, None)
                )
            ),
            specs=lambda: (
                lambda a: (a["g"], a["root"], a["mask"])
            )(_audit_specs()),
            buckets=4,  # engine picked per jit; shapes ride the resident
        )


_register_one_engines()

_register_kernel(
    "spf.whatif.batch",
    builder=lambda: jax.jit(
        lambda g, r, ms: spf_whatif_batch(g, r, ms, None, engine="seq")
    ),
    specs=lambda: (
        lambda a: (a["g"], a["root"], a["masks"])
    )(_audit_specs()),
    buckets=16,  # pow2 scenario-lane pads per shape
)

_register_kernel(
    "spf.multiroot",
    builder=lambda: jax.jit(lambda g, rs, m: spf_multiroot(g, rs, m, None)),
    specs=lambda: (
        lambda a: (a["g"], a["roots"], a["mask"])
    )(_audit_specs()),
    buckets=16,
)

_register_kernel(
    "spf.one.incremental",
    builder=lambda: jax.jit(
        lambda g, r, prev, seeds: spf_one_incremental(g, r, prev, seeds, None),
        donate_argnums=(2,),
    ),
    specs=lambda: (
        lambda a: (a["g"], a["root"], a["sp"], a["seeds"])
    )(_audit_specs()),
    donate=(2,),
    buckets=16,  # pow2 seed-row pads per shape
)

_register_kernel(
    "spf.one.multipath.k2",
    builder=lambda: jax.jit(
        lambda g, r, m: spf_one_multipath(g, r, 2, m, None)
    ),
    specs=lambda: (
        lambda a: (a["g"], a["root"], a["mask"])
    )(_audit_specs()),
    buckets=4,  # kp collapses onto {1, 2, 4, 8}
)

_register_kernel(
    "spf.multipath.batch.k2",
    builder=lambda: jax.jit(
        lambda g, r, ms: spf_multipath_batch(g, r, ms, 2, None)
    ),
    specs=lambda: (
        lambda a: (a["g"], a["root"], a["masks"])
    )(_audit_specs()),
    buckets=32,  # kp x scenario-lane buckets
)

_register_kernel(
    "spf.one.incremental.multipath.k2",
    builder=lambda: jax.jit(
        lambda g, r, prev, np_p, aw_p, seeds: spf_one_incremental_multipath(
            g, r, prev, np_p, aw_p, seeds, 2, None
        ),
        donate_argnums=(2, 3, 4),
    ),
    specs=lambda: (
        lambda a: (
            a["g"], a["root"], a["sp"],
            a["mp"].npaths, a["mp"].nh_weights, a["seeds"],
        )
    )(_audit_specs()),
    donate=(2, 3, 4),
    buckets=32,
)

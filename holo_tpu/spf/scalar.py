"""Scalar SPF oracle: the reference Dijkstra semantics, exactly.

This is a faithful re-implementation (in our own graph model, not a port) of
the candidate-list Dijkstra in holo-ospf/src/spf.rs:587-729:

- candidate list ordered by (distance, vertex id); vertex indices are assigned
  in tie-break order by the marshaling layer (networks before routers —
  holo-ospf/src/ospfv2/spf.rs:42-45), so plain integer order is correct here;
- on a strictly better path the candidate is re-created (hops and next-hop
  set taken from the new parent — spf.rs:685-706);
- on an equal-cost path only the next-hop set is extended (spf.rs:710-717);
- hops increments only when the linked vertex is a router (spf.rs:673-677);
- next hops: computed directly when the parent has hops == 0 (the parent is
  the root or a transit network adjacent to the root), otherwise inherited
  from the parent (spf.rs:744-767).

Direct next hops are modeled as "atoms" (ids into the protocol layer's
(interface, address) table) carried per edge in
``Topology.edge_direct_atom``; the scalar and TPU backends therefore agree on
the exact same next-hop universe.
"""

from __future__ import annotations

from dataclasses import dataclass
from heapq import heappop, heappush

import numpy as np

from holo_tpu.ops.graph import INF, Topology


@dataclass
class ScalarSpfOut:
    dist: np.ndarray  # int32[N], INF unreachable
    parent: np.ndarray  # int32[N], N if none (root/unreachable)
    hops: np.ndarray  # int32[N], N+1 if unreachable
    nexthops: list  # list[frozenset[int]] of atom ids per vertex

    def nexthop_words(self, n_atoms: int) -> np.ndarray:
        """Pack next-hop sets into uint32 bitmask words [N, W]."""
        w = max((n_atoms + 31) // 32, 1)
        out = np.zeros((len(self.nexthops), w), np.uint32)
        for v, atoms in enumerate(self.nexthops):
            for a in atoms:
                if a >= n_atoms:
                    raise ValueError(f"atom id {a} >= n_atoms {n_atoms}")
                out[v, a // 32] |= np.uint32(1) << np.uint32(a % 32)
        return out


def spf_reference(topo: Topology, edge_mask: np.ndarray | None = None) -> ScalarSpfOut:
    """Run the reference-semantics Dijkstra from ``topo.root``."""
    n = topo.n_vertices
    # Out-adjacency: vertex -> [(dst, cost, direct_atom)].
    adj: list[list[tuple[int, int, int]]] = [[] for _ in range(n)]
    for e in range(topo.n_edges):
        if edge_mask is not None and not edge_mask[e]:
            continue
        adj[int(topo.edge_src[e])].append(
            (int(topo.edge_dst[e]), int(topo.edge_cost[e]), int(topo.edge_direct_atom[e]))
        )

    root = topo.root
    dist = np.full(n, INF, np.int32)
    parent = np.full(n, n, np.int32)
    hops = np.full(n, n + 1, np.int32)
    nexthops: list[frozenset] = [frozenset()] * n

    # cand: vid -> [dist, hops, set(atoms), first_parent]; heap of (dist, vid)
    # with lazy deletion emulates BTreeMap<(dist, vid)>::pop_first.
    cand: dict[int, list] = {root: [0, 0, set(), n]}
    heap: list[tuple[int, int]] = [(0, root)]
    in_spt = np.zeros(n, bool)

    while heap:
        d, v = heappop(heap)
        ent = cand.get(v)
        if ent is None or in_spt[v] or ent[0] != d:
            continue  # stale heap entry
        del cand[v]
        in_spt[v] = True
        dist[v] = d
        hops[v] = ent[1]
        nexthops[v] = frozenset(ent[2])
        parent[v] = ent[3]
        v_hops = ent[1]
        v_nh = nexthops[v]

        for dst, cost, atom in adj[v]:
            if in_spt[dst]:
                continue
            nd = d + cost
            nhops = v_hops + (1 if topo.is_router[dst] else 0)
            c = cand.get(dst)
            if c is not None:
                if nd > c[0]:
                    continue
                if nd < c[0]:
                    # Re-created from the improving parent: fresh hops and
                    # next-hop set (spf.rs:685-706).
                    c[0], c[1], c[2], c[3] = nd, nhops, set(), v
                    heappush(heap, (nd, dst))
                # equal: keep existing dist/hops/first-parent, extend below
            else:
                c = [nd, nhops, set(), v]
                cand[dst] = c
                heappush(heap, (nd, dst))
            # Next-hop contribution (spf.rs:710-717 + calc_nexthops).
            if v_hops == 0:
                if atom >= 0:
                    c[2].add(atom)
            else:
                c[2] |= v_nh

    parent[root] = n
    return ScalarSpfOut(dist=dist, parent=parent, hops=hops, nexthops=nexthops)

"""Scalar SPF oracle: the reference Dijkstra semantics, exactly.

This is a faithful re-implementation (in our own graph model, not a port) of
the candidate-list Dijkstra in holo-ospf/src/spf.rs:587-729:

- candidate list ordered by (distance, vertex id); vertex indices are assigned
  in tie-break order by the marshaling layer (networks before routers —
  holo-ospf/src/ospfv2/spf.rs:42-45), so plain integer order is correct here;
- on a strictly better path the candidate is re-created (hops and next-hop
  set taken from the new parent — spf.rs:685-706);
- on an equal-cost path only the next-hop set is extended (spf.rs:710-717);
- hops increments only when the linked vertex is a router (spf.rs:673-677);
- next hops: computed directly when the parent has hops == 0 (the parent is
  the root or a transit network adjacent to the root), otherwise inherited
  from the parent (spf.rs:744-767).

Direct next hops are modeled as "atoms" (ids into the protocol layer's
(interface, address) table) carried per edge in
``Topology.edge_direct_atom``; the scalar and TPU backends therefore agree on
the exact same next-hop universe.
"""

from __future__ import annotations

from dataclasses import dataclass
from heapq import heappop, heappush

import numpy as np

from holo_tpu.ops.graph import INF, MP_SAT, Topology


@dataclass
class ScalarSpfOut:
    dist: np.ndarray  # int32[N], INF unreachable
    parent: np.ndarray  # int32[N], N if none (root/unreachable)
    hops: np.ndarray  # int32[N], N+1 if unreachable
    nexthops: list  # list[frozenset[int]] of atom ids per vertex

    def nexthop_words(self, n_atoms: int) -> np.ndarray:
        """Pack next-hop sets into uint32 bitmask words [N, W]."""
        w = max((n_atoms + 31) // 32, 1)
        out = np.zeros((len(self.nexthops), w), np.uint32)
        for v, atoms in enumerate(self.nexthops):
            for a in atoms:
                if a >= n_atoms:
                    raise ValueError(f"atom id {a} >= n_atoms {n_atoms}")
                out[v, a // 32] |= np.uint32(1) << np.uint32(a % 32)
        return out


def spf_reference(topo: Topology, edge_mask: np.ndarray | None = None) -> ScalarSpfOut:
    """Run the reference-semantics Dijkstra from ``topo.root``."""
    n = topo.n_vertices
    # Out-adjacency: vertex -> [(dst, cost, direct_atom)].
    adj: list[list[tuple[int, int, int]]] = [[] for _ in range(n)]
    for e in range(topo.n_edges):
        if edge_mask is not None and not edge_mask[e]:
            continue
        adj[int(topo.edge_src[e])].append(
            (int(topo.edge_dst[e]), int(topo.edge_cost[e]), int(topo.edge_direct_atom[e]))
        )

    root = topo.root
    dist = np.full(n, INF, np.int32)
    parent = np.full(n, n, np.int32)
    hops = np.full(n, n + 1, np.int32)
    nexthops: list[frozenset] = [frozenset()] * n

    # cand: vid -> [dist, hops, set(atoms), first_parent]; heap of (dist, vid)
    # with lazy deletion emulates BTreeMap<(dist, vid)>::pop_first.
    cand: dict[int, list] = {root: [0, 0, set(), n]}
    heap: list[tuple[int, int]] = [(0, root)]
    in_spt = np.zeros(n, bool)

    while heap:
        d, v = heappop(heap)
        ent = cand.get(v)
        if ent is None or in_spt[v] or ent[0] != d:
            continue  # stale heap entry
        del cand[v]
        in_spt[v] = True
        dist[v] = d
        hops[v] = ent[1]
        nexthops[v] = frozenset(ent[2])
        parent[v] = ent[3]
        v_hops = ent[1]
        v_nh = nexthops[v]

        for dst, cost, atom in adj[v]:
            if in_spt[dst]:
                continue
            nd = d + cost
            nhops = v_hops + (1 if topo.is_router[dst] else 0)
            c = cand.get(dst)
            if c is not None:
                if nd > c[0]:
                    continue
                if nd < c[0]:
                    # Re-created from the improving parent: fresh hops and
                    # next-hop set (spf.rs:685-706).
                    c[0], c[1], c[2], c[3] = nd, nhops, set(), v
                    heappush(heap, (nd, dst))
                # equal: keep existing dist/hops/first-parent, extend below
            else:
                c = [nd, nhops, set(), v]
                cand[dst] = c
                heappush(heap, (nd, dst))
            # Next-hop contribution (spf.rs:710-717 + calc_nexthops).
            if v_hops == 0:
                if atom >= 0:
                    c[2].add(atom)
            else:
                c[2] |= v_nh

    parent[root] = n
    return ScalarSpfOut(dist=dist, parent=parent, hops=hops, nexthops=nexthops)


@dataclass
class ScalarMultipathOut:
    """Multi-parent frontier planes — the independent scalar oracle of
    :class:`holo_tpu.ops.spf_engine.MultipathTensors` (loops + dicts,
    no shared vectorized code); tests pin the two bit-identical."""

    parents: np.ndarray  # int32[N, Kp]; sentinel N past the set
    pdist: np.ndarray  # int32[N, Kp]; INF past the set
    pweight: np.ndarray  # int32[N, Kp]; 0 past the set
    npaths: np.ndarray  # int32[N]; saturated at MP_SAT, 0 unreachable
    nh_weights: np.ndarray  # int32[N, A]; saturated at MP_SAT


def spf_multipath_reference(
    topo: Topology,
    kp: int,
    edge_mask: np.ndarray | None = None,
    n_lanes: int | None = None,
) -> tuple[ScalarSpfOut, ScalarMultipathOut]:
    """Reference multipath SPF (ISSUE 10 oracle).

    Semantics (shared contract with the device kernel, documented on
    :class:`~holo_tpu.ops.spf_engine.MultipathTensors`):

    - ``npaths[v] = min(sum over DAG parents u of npaths[u], MP_SAT)``
      computed over already-clamped parent values in ``(dist, vertex)``
      topological order — valid because every DAG edge either strictly
      increases dist or is a zero-cost network→router edge, whose
      network source orders before the router under the vertex-ordering
      contract (networks first).
    - per-atom weights: a hops==0 DAG parent contributes ``npaths[u]``
      on its slot's direct atom; any other DAG parent contributes its
      own (clamped) weight row.
    - parent sets: distinct sources of admissible in-edges (DAG edges,
      plus strictly-downward ``dist[u] < dist[v]`` loop-free diversity
      edges), each at its cheapest path cost, ranked by
      ``(path cost, source id)``, truncated to ``kp``.
    """
    n = topo.n_vertices
    base = spf_reference(topo, edge_mask)
    dist, hops = base.dist, base.hops
    sat = int(MP_SAT)
    n_atoms = max(topo.n_atoms(), 1) if n_lanes is None else int(n_lanes)

    # In-edges per vertex under the mask: (src, cost, atom).
    radj: list[list[tuple[int, int, int]]] = [[] for _ in range(n)]
    for e in range(topo.n_edges):
        if edge_mask is not None and not edge_mask[e]:
            continue
        radj[int(topo.edge_dst[e])].append(
            (
                int(topo.edge_src[e]),
                int(topo.edge_cost[e]),
                int(topo.edge_direct_atom[e]),
            )
        )

    root = int(topo.root)
    npaths = np.zeros(n, np.int64)
    nh_weights = np.zeros((n, n_atoms), np.int64)
    order = sorted(
        (v for v in range(n) if int(dist[v]) < int(INF)),
        key=lambda v: (int(dist[v]), v),
    )
    for v in order:
        if v == root:
            npaths[v] = 1
            continue
        total = 0
        for u, c, atom in radj[v]:
            if int(dist[u]) >= int(INF) or int(dist[u]) + c != int(dist[v]):
                continue  # not a DAG edge
            total += int(npaths[u])
            if int(hops[u]) == 0:
                if atom >= 0:
                    nh_weights[v, atom] += int(npaths[u])
            else:
                nh_weights[v] += nh_weights[u]
        npaths[v] = min(total, sat)
        np.minimum(nh_weights[v], sat, out=nh_weights[v])

    parents = np.full((n, kp), n, np.int32)
    pdist = np.full((n, kp), INF, np.int32)
    pweight = np.zeros((n, kp), np.int32)
    for v in range(n):
        if v == root or int(dist[v]) >= int(INF):
            continue
        best: dict[int, int] = {}  # source -> cheapest admissible cost
        for u, c, _atom in radj[v]:
            du = int(dist[u])
            if du >= int(INF):
                continue
            cost = du + c
            if cost == int(dist[v]) or du < int(dist[v]):
                if u not in best or cost < best[u]:
                    best[u] = cost
        ranked = sorted(best.items(), key=lambda it: (it[1], it[0]))[:kp]
        for j, (u, cost) in enumerate(ranked):
            parents[v, j] = u
            pdist[v, j] = cost
            pweight[v, j] = int(npaths[u])

    return base, ScalarMultipathOut(
        parents=parents,
        pdist=pdist,
        pweight=pweight,
        npaths=npaths.astype(np.int32),
        nh_weights=nh_weights.astype(np.int32),
    )

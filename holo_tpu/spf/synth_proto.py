"""Protocol-marshaled benchmark topologies (BASELINE.md configs 2+3).

Unlike :mod:`holo_tpu.spf.synth` (which builds ``Topology`` objects
directly), these builders populate REAL protocol instances — an OSPFv3
multi-area LSDB of ``LsaRouterV3``/Intra-Area-Prefix LSAs, and IS-IS
L1/L2 LSP databases — and extract the benchmark topologies through each
protocol's own SPF marshaling path (``OspfV3Instance._area_spf``,
``IsisInstance.run_spf``).  What the bench then times on the shared
engine is exactly what the protocols dispatch in production
(reference parity: the per-protocol graph/vertex-ordering rules live in
the marshal, not the engine).
"""

from __future__ import annotations

from ipaddress import IPv4Address, IPv4Network, IPv6Address, IPv6Network

import numpy as np


class _CaptureBackend:
    """Delegates compute() while recording every dispatched Topology."""

    def __init__(self, inner):
        self.inner = inner
        self.name = inner.name
        self.topos: list = []

    def compute(self, topo, multipath_k: int = 1):
        self.topos.append(topo)
        return self.inner.compute(topo, multipath_k=multipath_k)


def _spanning_edges(n: int, extra: int, rng) -> list[tuple[int, int, int]]:
    """Connected random graph: tree + ``extra`` chords, uniform-ish
    costs (the fat-tree analog at arbitrary n)."""
    edges = []
    for v in range(1, n):
        u = int(rng.integers(0, v))
        edges.append((u, v, 1 + int(rng.integers(0, 16))))
    for _ in range(extra):
        u = int(rng.integers(0, n))
        v = int(rng.integers(0, n))
        if u != v:
            edges.append((u, v, 1 + int(rng.integers(0, 16))))
    return edges


def ospfv3_multiarea_topologies(
    n_routers: int = 10_000, n_areas: int = 4, seed: int = 0
) -> list:
    """BASELINE config 2: one ABR instance attached to ``n_areas`` areas
    totalling ``n_routers`` routers; returns the per-area ``Topology``
    objects produced by the instance's own ``_area_spf`` marshal."""
    from holo_tpu.protocols.ospf import packet_v3 as P
    from holo_tpu.protocols.ospf.instance_v3 import (
        OspfV3Instance,
        V3IfConfig,
    )
    from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
    from holo_tpu.spf.backend import ScalarSpfBackend
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    rng = np.random.default_rng(seed)
    loop = EventLoop(clock=VirtualClock())
    inst = OspfV3Instance(
        name="bench-v3", router_id=IPv4Address("0.0.0.1"), netio=None
    )
    loop.register(inst)
    capture = _CaptureBackend(ScalarSpfBackend())
    inst.backend = capture

    per_area = n_routers // n_areas
    now = loop.clock.now()
    for a in range(n_areas):
        area_id = IPv4Address(a)
        iface = inst.add_interface(
            f"be{a}", V3IfConfig(cost=1, area_id=area_id),
            IPv6Address(f"fe80::a:{a + 1}"), [],
        )
        iface.up = True
        area = inst.areas[area_id]
        # Router ids: root is 0.0.0.1; area routers start at base+1.
        base = (a + 1) << 16
        rids = [IPv4Address(base + i + 1) for i in range(per_area)]

        def rl(nbr_rid, metric, ifid=1, nbr_ifid=1):
            return P.RouterLinkV3(
                link_type=P.RouterLinkType.POINT_TO_POINT,
                metric=metric, iface_id=ifid, nbr_iface_id=nbr_ifid,
                nbr_router_id=nbr_rid,
            )

        links: dict[IPv4Address, list] = {rid: [] for rid in rids}
        for u, v, cost in _spanning_edges(per_area, per_area // 2, rng):
            links[rids[u]].append(rl(rids[v], cost))
            links[rids[v]].append(rl(rids[u], cost))
        # The ABR (root) attaches to the area's first router.
        root_links = [rl(rids[0], 1)]
        links[rids[0]].append(rl(inst.router_id, 1))
        # Adjacency state for the root's next-hop atom.
        iface.neighbors[rids[0]] = Neighbor(
            router_id=rids[0],
            src=IPv6Address(f"fe80::b:{a + 1}"),
            state=NsmState.FULL,
            iface_id=1,
        )

        def install(ltype, lsid, adv, body):
            lsa = P.Lsa(1, ltype, IPv4Address(lsid), adv, -1000, body)
            area.lsdb.install(lsa, now)

        install(P.LsaType.ROUTER, 0, inst.router_id,
                P.LsaRouterV3(links=root_links))
        for rid in rids:
            install(P.LsaType.ROUTER, 0, rid,
                    P.LsaRouterV3(links=links[rid]))
            install(
                P.LsaType.INTRA_AREA_PREFIX, 1, rid,
                P.LsaIntraAreaPrefix(
                    ref_type=int(P.LsaType.ROUTER), ref_lsid=IPv4Address(0),
                    ref_adv_rtr=rid,
                    prefixes=[
                        (IPv6Network((int(rid) << 64) | (0x2001 << 112),
                                     64), 1)
                    ],
                ),
            )

    for area in inst.areas.values():
        out = inst._area_spf(area)
        assert out is not None, "marshal produced no topology"
    topos = capture.topos
    assert len(topos) == n_areas
    return topos


def isis_l1l2_topologies(
    n_l2: int = 9_000, n_l1: int = 1_000, ecmp_width: int = 64,
    seed: int = 0,
) -> list:
    """BASELINE config 3: IS-IS L1/L2 at 10k nodes with a
    ``ecmp_width``-way equal-cost segment at the L2 root; returns the
    [L1, L2] ``Topology`` objects from ``IsisInstance.run_spf``'s own
    marshal, asserting the root really extracts ``ecmp_width`` distinct
    next hops."""
    from holo_tpu.ops.graph import INF
    from holo_tpu.protocols.isis.instance import (
        Adjacency,
        AdjacencyState,
        IsisIfConfig,
        IsisInstance,
        LspEntry,
    )
    from holo_tpu.protocols.isis.packet import (
        ExtIpReach,
        ExtIsReach,
        Lsp,
        LspId,
    )
    from holo_tpu.spf.backend import ScalarSpfBackend
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    rng = np.random.default_rng(seed + 1)

    def sysid(i: int) -> bytes:
        return i.to_bytes(6, "big")

    def build_level(level: int, n: int, ecmp: int) -> tuple:
        loop = EventLoop(clock=VirtualClock())
        inst = IsisInstance(
            f"bench-l{level}", sysid(1), netio=None, level=level
        )
        loop.register(inst)
        capture = _CaptureBackend(ScalarSpfBackend())
        inst.backend = capture
        now = loop.clock.now()

        # Edge list over router indices 1..n (router 1 is the root).
        # ECMP segment: root -> spines (2..ecmp+1) -> core (ecmp+2),
        # all metric 1, so everything behind the core is ecmp-way.
        edges: list[tuple[int, int, int]] = []
        core = ecmp + 2
        for s in range(2, ecmp + 2):
            edges.append((1, s, 1))
            edges.append((s, core, 1))
        for v in range(core + 1, n + 1):
            u = core if v == core + 1 else int(rng.integers(core, v))
            edges.append((u, v, 1 + int(rng.integers(0, 16))))
        nbrs: dict[int, list[tuple[int, int]]] = {}
        for u, v, c in edges:
            nbrs.setdefault(u, []).append((v, c))
            nbrs.setdefault(v, []).append((u, c))

        for i in range(1, n + 1):
            tlvs = {
                "ext_is_reach": [
                    ExtIsReach(sysid(j) + b"\x00", c)
                    for j, c in nbrs.get(i, [])
                ],
                "ext_ip_reach": [
                    ExtIpReach(IPv4Network((10 << 24) | (i << 8), 32), 1)
                ],
            }
            lsp = Lsp(level, 1200, LspId(sysid(i)), 5, tlvs=tlvs)
            inst.lsdb[lsp.lsp_id] = LspEntry(lsp, now)

        # Root adjacencies: one p2p circuit per spine (the 64-way fan).
        for s in range(2, ecmp + 2):
            ifname = f"e{s}"
            inst.add_interface(
                ifname, IsisIfConfig(metric=1),
                IPv4Address((172 << 24) | (s << 8) | 1),
                IPv4Network((172 << 24) | (s << 8), 30),
            )
            iface = inst.interfaces[ifname]
            iface.adj = Adjacency(
                sysid=sysid(s), state=AdjacencyState.UP,
                addr=IPv4Address((172 << 24) | (s << 8) | 2),
            )
        inst.run_spf()
        assert len(capture.topos) == 1
        return inst, capture.topos[0]

    l1_inst, l1_topo = build_level(1, n_l1, min(ecmp_width, 8))
    l2_inst, l2_topo = build_level(2, n_l2, ecmp_width)
    # The acceptance criterion: a destination behind the core really
    # resolves to ecmp_width distinct next hops in the instance's OWN
    # route table (64-way ECMP extraction).
    far = IPv4Network((10 << 24) | (n_l2 << 8), 32)
    route = l2_inst.routes.get(far)
    assert route is not None, "far prefix unreachable in L2"
    if l2_inst.max_paths is None or l2_inst.max_paths >= ecmp_width:
        assert len(route[1]) == ecmp_width, (
            f"expected {ecmp_width}-way ECMP, got {len(route[1])}"
        )
    return [l1_topo, l2_topo]

"""Convergence-storm harness: seeded flap storms over a synthetic
multi-thousand-router OSPFv2 LSDB inside a REAL instance.

The scenario-diversity grading rig of ROADMAP item 4 (in the spirit of
"Advanced Models for the OSPF Routing Protocol", arXiv:2203.09882):
like :mod:`holo_tpu.spf.synth_proto`, the topology scales in the LSDB —
one device-under-test :class:`OspfInstance` holds Router-LSAs for
``n_routers`` synthetic routers — while the causal machinery around it
is entirely real: LSA installs run through ``_install_and_flood`` (so
the RFC 8405 SPF-delay FSM, trigger classification, and the convergence
observatory's origin stamps all fire), routes flow over the ibus into a
real :class:`RibManager`, and BFD/carrier events drive its O(1)
local-repair flips.

Storm events come from the existing :class:`FaultPlan` seed streams
(same seed → same timeline, virtual-clock deterministic):

- **lsa** — a non-structural link flaps; both endpoint Router-LSAs
  reinstall with bumped sequence numbers.  With probability
  ``plan.drop_prob`` the arrival is LOST and retransmitted
  ``RXMT_DELAY`` later — convergence latency then includes the
  retransmit penalty, exactly the 10%-loss tail the storm measures.
- **bfd** — a BFD session to one of the DUT's two ECMP gateways drops
  (and later recovers): the RIB flips survivors in O(1).
- **carrier** — a DUT interface loses (and regains) carrier.
- **ifconfig** — the DUT's gateway link metric changes (config event;
  forces a full SPF).

The dual-gateway construction (root → g0/g1 → shared hubs → the rest)
guarantees 2-way ECMP for every destination behind the hubs, so
bfd/carrier repairs always have survivors to flip to.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass
from ipaddress import IPv4Address, IPv4Network

import numpy as np

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    InstanceConfig,
    OspfInstance,
)
from holo_tpu.protocols.ospf.interface import IfType, IsmState
from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
from holo_tpu.protocols.ospf.packet import (
    Lsa,
    LsaRouter,
    LsaType,
    Options,
    RouterLink,
    RouterLinkType,
)
from holo_tpu.resilience.faults import FaultInjector, FaultPlan
from holo_tpu.routing.rib import MockKernel, RibManager
from holo_tpu.telemetry import convergence
from holo_tpu.utils.ibus import (
    TOPIC_BFD_STATE,
    TOPIC_INTERFACE_UPD,
    BfdStateUpd,
    Ibus,
)
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import Actor, EventLoop, VirtualClock
from holo_tpu.utils.southbound import InterfaceUpdMsg

#: modeled LS-retransmit penalty for a "lost" LSA arrival
RXMT_DELAY = 5.0

_MASK24 = IPv4Address("255.255.255.0")


class _DiscardIo(NetIo):
    """Flood sink: the synthetic neighbors have no receive side."""

    def send(self, ifname, src, dst, data) -> None:
        pass


def _rid(i: int) -> IPv4Address:
    """Synthetic router id for index ``i`` (root is index 0)."""
    return IPv4Address((10 << 24) | (i + 1))


def _p2p(nbr: IPv4Address, data: IPv4Address, metric: int) -> RouterLink:
    return RouterLink(RouterLinkType.POINT_TO_POINT, nbr, data, metric)


def _stub(prefix: IPv4Network, metric: int = 1) -> RouterLink:
    return RouterLink(
        RouterLinkType.STUB_NETWORK,
        prefix.network_address,
        prefix.netmask,
        metric,
    )


@dataclass
class _ApplyLsas:
    """Storm-actor message: install LSAs under a causal context (the
    ``event_id`` field is what the EventLoop delivery hook activates —
    lost arrivals redeliver this same message after RXMT_DELAY)."""

    lsas: list
    event_id: tuple | None = None


class StormNet:
    """One DUT instance + RIB over a virtual-clock loop, plus the
    python-side link model the storm mutates."""

    DUT = "storm-dut"
    ACTOR = "storm-driver"

    def __init__(
        self,
        n_routers: int = 2000,
        seed: int = 0,
        spf_backend=None,
        prefix_every: int = 8,
        hubs: int = 6,
        loop=None,
        max_paths: int | None = None,
    ):
        """``loop`` defaults to a fresh virtual-clock EventLoop (the
        deterministic storm configuration); passing a
        :class:`~holo_tpu.utils.preempt.ThreadedLoop` instead hosts the
        whole network on a real pump thread — the configuration the
        pump-kill chaos test drives.  ``max_paths`` (ISSUE 10) arms the
        multipath dispatch on the DUT: the dual-gateway ECMP pairs then
        install as real next-hop SETS with UCMP weights."""
        assert n_routers >= hubs + 8, "need root + 2 gateways + hubs + some"
        self.n_routers = n_routers
        self.loop = loop if loop is not None else EventLoop(
            clock=VirtualClock()
        )
        self.bus = Ibus(self.loop)
        self.kernel = MockKernel()
        self.rib = RibManager(self.bus, self.kernel)
        self.rib.name = "routing"
        self.loop.register(self.rib)
        cfg = InstanceConfig(router_id=_rid(0), max_paths=max_paths)
        self.inst = OspfInstance(
            name=self.DUT,
            config=cfg,
            netio=_DiscardIo(),
            spf_backend=spf_backend,
        )
        self.loop.register(self.inst)
        self.inst.attach_ibus(self.bus, routing_actor="routing")
        self.loop.register(_StormActor(self), name=self.ACTOR)

        rng = np.random.default_rng(seed)
        # Link model: adjacency dict rid-index -> {peer-index: metric}.
        # Indices: 0 root, 1..2 gateways, 3..3+hubs-1 hubs, rest leaves.
        self.adj: dict[int, dict[int, int]] = {i: {} for i in range(n_routers)}
        self.g0, self.g1 = 1, 2
        self.hub0 = 3
        self.n_hubs = hubs

        def link(a: int, b: int, cost: int) -> None:
            self.adj[a][b] = cost
            self.adj[b][a] = cost

        link(0, self.g0, 1)
        link(0, self.g1, 1)
        for j in range(hubs):
            h = self.hub0 + j
            link(self.g0, h, 1)
            link(self.g1, h, 1)
            if j:
                link(h - 1, h, 1)
        first_leaf = self.hub0 + hubs
        for i in range(first_leaf, n_routers):
            # Spanning attachment to a hub or an earlier leaf, plus a
            # sprinkling of extra edges for path diversity.
            parent = int(rng.integers(self.hub0, i))
            link(i, parent, int(rng.integers(1, 5)))
            if rng.random() < 0.3:
                extra = int(rng.integers(self.hub0, i))
                if extra != i and extra not in self.adj[i]:
                    link(i, extra, int(rng.integers(1, 8)))
        # Flappable edges: leaf/hub-side only — never the root/gateway
        # structure the ECMP construction depends on.
        self.flappable = sorted(
            (a, b)
            for a, nbrs in self.adj.items()
            for b in nbrs
            if a < b and a >= self.hub0
        )
        self.down: set[tuple[int, int]] = set()
        # Per-prefix stub owners (every prefix_every-th leaf).
        self.stub_owners = list(range(first_leaf, n_routers, prefix_every))
        self._seq: dict[int, int] = {}

        # DUT interfaces + FULL neighbors toward the gateways (next-hop
        # resolution; the ISM/NSM machinery is bypassed exactly like
        # synth_proto does for OSPFv3).
        self.g0_addr = IPv4Address("10.255.0.2")
        self.g1_addr = IPv4Address("10.255.1.2")
        for ifname, net, our, nbr_idx, nbr_addr in (
            ("e0", "10.255.0.0/30", "10.255.0.1", self.g0, self.g0_addr),
            ("e1", "10.255.1.0/30", "10.255.1.1", self.g1, self.g1_addr),
        ):
            iface = self.inst.add_interface(
                ifname,
                IfConfig(if_type=IfType.POINT_TO_POINT, cost=1),
                IPv4Network(net),
                IPv4Address(our),
            )
            iface.state = IsmState.POINT_TO_POINT
            iface.neighbors[_rid(nbr_idx)] = Neighbor(
                router_id=_rid(nbr_idx), src=nbr_addr, state=NsmState.FULL
            )
        self.area = self.inst.areas[next(iter(self.inst.areas))]
        inner = getattr(self.loop, "loop", self.loop)  # ThreadedLoop hosts
        now = inner.clock.now()
        for i in range(n_routers):
            self.area.lsdb.install(self._router_lsa(i), now)
        # First full SPF + RIB sync (outside any storm measurement); a
        # ThreadedLoop host converges on its own pump thread instead.
        self.inst._schedule_spf()
        if hasattr(self.loop, "advance"):
            self.loop.advance(30.0)

    # -- LSA construction

    def _router_lsa(self, i: int) -> Lsa:
        seq = self._seq.get(i, 0) + 1
        self._seq[i] = seq
        links: list[RouterLink] = []
        if i == 0:
            links.append(
                _p2p(_rid(self.g0), IPv4Address("10.255.0.1"),
                     self.adj[0][self.g0])
            )
            links.append(
                _p2p(_rid(self.g1), IPv4Address("10.255.1.1"),
                     self.adj[0][self.g1])
            )
        else:
            for peer, metric in sorted(self.adj[i].items()):
                if (min(i, peer), max(i, peer)) in self.down:
                    continue
                links.append(_p2p(_rid(peer), IPv4Address(0), metric))
        if i and i in self._stub_set():
            links.append(
                _stub(IPv4Network(((172 << 24) | (i << 8), 24)), 1)
            )
        lsa = Lsa(
            age=1,
            options=Options(0x02),
            type=LsaType.ROUTER,
            lsid=_rid(i),
            adv_rtr=_rid(i),
            seq_no=seq,
            body=LsaRouter(links=links),
        )
        # §13.2 change detection compares the encoded body bytes —
        # synthetic LSAs must carry a real wire image.
        lsa.encode()
        return lsa

    def _stub_set(self) -> set[int]:
        s = getattr(self, "_stub_cache", None)
        if s is None:
            s = self._stub_cache = set(self.stub_owners)
        return s

    # -- storm event primitives (called by run_storm)

    def _deliver(self, lsas: list, eid, delay: float = 0.0) -> None:
        msg = _ApplyLsas(lsas, (eid,) if eid is not None else None)
        if delay > 0.0:
            t = self.loop.timer(self.ACTOR, lambda m=msg: m)
            t.start(delay)
        else:
            self.loop.send(self.ACTOR, msg)

    def apply_lsas(self, lsas: list) -> None:
        """Runs inside the storm actor (causal context already active
        via the delivery hook)."""
        for lsa in lsas:
            self.inst._install_and_flood(self.area, lsa)
        # The synthetic neighbors ack instantly: drop retransmit state
        # so the storm's timer load stays bounded.
        for area in self.inst.areas.values():
            for iface in area.interfaces.values():
                for nbr in iface.neighbors.values():
                    nbr.ls_rxmt.clear()

    def flap(self, edge: tuple[int, int], lost: bool) -> int | None:
        """Toggle ``edge``; both endpoint LSAs (re)install as one causal
        LSA-arrival event.  ``lost`` defers the arrival by RXMT_DELAY."""
        if edge in self.down:
            self.down.discard(edge)
            state = "up"
        else:
            self.down.add(edge)
            state = "down"
        eid = convergence.begin(
            convergence.TRIGGER_LSA, edge=f"{edge[0]}-{edge[1]}", state=state
        )
        a, b = edge
        self._deliver(
            [self._router_lsa(a), self._router_lsa(b)],
            eid,
            delay=RXMT_DELAY if lost else 0.0,
        )
        return eid

    def bfd(self, gateway: int, state: str) -> None:
        addr = self.g0_addr if gateway == self.g0 else self.g1_addr
        ifname = "e0" if gateway == self.g0 else "e1"
        eid = convergence.begin(
            convergence.TRIGGER_BFD, state=state, ifname=ifname
        )
        with convergence.activation(eid):
            self.bus.publish(
                TOPIC_BFD_STATE, BfdStateUpd((ifname, addr), state)
            )

    def carrier(self, ifname: str, operative: bool) -> None:
        eid = convergence.begin(
            convergence.TRIGGER_CARRIER, ifname=ifname, operative=operative
        )
        with convergence.activation(eid):
            self.bus.publish(
                TOPIC_INTERFACE_UPD,
                InterfaceUpdMsg(ifname=ifname, ifindex=0,
                                operative=operative),
            )

    def ifconfig_metric(self) -> None:
        """Config event on the DUT: the e0 gateway link metric flips
        between 1 and 2 — a full-SPF-forcing change with real route
        movement (ECMP collapses to g1 and back)."""
        cur = self.adj[0][self.g0]
        self.adj[0][self.g0] = 2 if cur == 1 else 1
        self.adj[self.g0][0] = self.adj[0][self.g0]
        eid = convergence.begin(convergence.TRIGGER_IFCONFIG, ifname="e0")
        self._deliver([self._router_lsa(0)], eid)


class _StormActor(Actor):
    """Applies deferred/immediate LSA batches on the loop (the delivery
    hook re-activates each message's causal event context)."""

    def __init__(self, net: StormNet):
        self.net = net

    def handle(self, msg) -> None:
        if isinstance(msg, _ApplyLsas):
            self.net.apply_lsas(msg.lsas)


def _percentiles(values: list[float]) -> dict:
    if not values:
        return {"count": 0}
    arr = np.sort(np.asarray(values, np.float64))
    pick = lambda q: float(arr[min(len(arr) - 1, int(q * (len(arr) - 1)))])
    return {
        "count": len(arr),
        "p50": round(pick(0.50), 6),
        "p95": round(pick(0.95), 6),
        "p99": round(pick(0.99), 6),
        "max": round(float(arr[-1]), 6),
    }


def storm_report(timelines: list[dict]) -> dict:
    """Aggregate completed causal timelines into per-trigger
    event-to-FIB latency distributions, split by dispatch mode
    (batched-device vs scalar-fallback vs plain scalar)."""
    per: dict[tuple, list[float]] = {}
    outcomes: dict[str, int] = {}
    for rec in timelines:
        outcomes[rec["outcome"]] = outcomes.get(rec["outcome"], 0) + 1
        if rec["outcome"] != "converged":
            continue
        fib_t = next(
            (t for step, t, _ in rec["timeline"] if step in ("fib", "fallback")),
            None,
        )
        if fib_t is None:
            continue
        modes = set(rec["dispatch"].values())
        mode = (
            "fallback"
            if rec["fallback"]
            else ("device" if "device" in modes else "scalar")
        )
        per.setdefault((rec["trigger"], mode), []).append(fib_t)
        per.setdefault((rec["trigger"], "all"), []).append(fib_t)
    report: dict = {"outcomes": outcomes, "triggers": {}}
    for (trigger, mode), vals in sorted(per.items()):
        report["triggers"].setdefault(trigger, {})[mode] = _percentiles(vals)
    return report


def _instrument_dispatch_wall(net: StormNet):
    """Wrap the DUT backend's ``compute`` to attribute REAL (wall-clock)
    SPF dispatch seconds to the active causal triggers.

    The storm's event-to-FIB latencies are virtual-clock quantities —
    deterministic, but blind to how long the device work actually takes
    (the virtual clock does not advance while Python computes).  This
    sink is the DeltaPath headline instrument: the per-trigger
    dispatch-wall distribution is what the incremental path must shrink
    while the virtual timelines (and FIB digests) stay byte-identical.

    Returns ``(sink, restore)``; the harness calls ``restore`` when the
    storm ends so a caller-supplied backend leaves unwrapped (backends
    are parameters — reuse across storms must not nest timers).
    """
    sink: dict[str, list[float]] = {}
    backend = net.inst.backend
    inner = backend.compute

    def timed(topo, edge_mask=None, multipath_k: int = 1):
        t0 = time.perf_counter()
        res = inner(topo, edge_mask, multipath_k=multipath_k)
        dt = time.perf_counter() - t0
        for trig in set(convergence.active_triggers()) or {"untracked"}:
            sink.setdefault(trig, []).append(dt)
        return res

    backend.compute = timed

    def restore():
        backend.compute = inner

    return sink, restore


def storm_digest(timelines: list[dict]) -> str:
    """Canonical digest of the causal timelines for the determinism
    gate (same seed → same digest).  Trace span ids are stripped: the
    tracer's id counter is process-global and survives across runs."""

    def clean(rec: dict) -> dict:
        out = dict(rec)
        out["timeline"] = [
            [step, t, {k: v for k, v in attrs.items() if k != "span_id"}]
            for step, t, attrs in rec["timeline"]
        ]
        return out

    text = json.dumps([clean(r) for r in timelines], sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()


def run_convergence_storm(
    n_routers: int = 2000,
    events: int = 200,
    seed: int = 7,
    spf_backend=None,
    tracker_capacity: int = 4096,
    drop_prob: float = 0.10,
    settle: float = 60.0,
    prefix_every: int = 8,
    max_paths: int | None = None,
    event_hook=None,
) -> tuple[dict, str, "StormNet"]:
    """One seeded convergence storm end to end.  Returns ``(report,
    digest, net)``; the report carries per-trigger p50/p95/p99/max
    event-to-FIB distributions split by dispatch mode.

    The event mix and every stochastic choice come from
    ``FaultPlan(seed)`` per-site streams, and time is virtual — two
    runs with one seed produce byte-identical digests.

    ``event_hook(net, index, now)`` — optional observer called after
    each event's inter-event gap has elapsed (and once more after the
    settle window, with ``index == events``).  The gNMI fan-out bench
    rides this seam: a subscriber fleet joins/leaves and the shared
    delta engine ticks at these deterministic virtual times.  The hook
    only READS daemon state — the storm's causal timelines and FIB
    digests are unaffected by its presence."""
    plan = FaultPlan(seed=seed, drop_prob=drop_prob)
    inj = FaultInjector(plan)
    net = StormNet(
        n_routers=n_routers, seed=seed, spf_backend=spf_backend,
        prefix_every=prefix_every, max_paths=max_paths,
    )
    tracker = convergence.configure(
        tracker_capacity, clock=net.loop.clock.now
    )
    dispatch_wall, restore_dispatch = _instrument_dispatch_wall(net)
    try:
        mix_rng = inj._rng("storm.mix")
        loss_rng = inj._rng("storm.loss")
        gap_rng = inj._rng("storm.gap")
        bfd_down = carrier_down = False
        for ev_i in range(events):
            roll = mix_rng.random()
            if roll < 0.70:
                edge = net.flappable[
                    mix_rng.randrange(len(net.flappable))
                ]
                net.flap(edge, lost=loss_rng.random() < plan.drop_prob)
            elif roll < 0.82:
                net.bfd(net.g0, "up" if bfd_down else "down")
                bfd_down = not bfd_down
            elif roll < 0.90:
                net.carrier("e1", operative=carrier_down)
                carrier_down = not carrier_down
            else:
                net.ifconfig_metric()
            # Bursty inter-event gaps: mostly sub-second (a real flap
            # storm), occasionally a multi-second lull that lets the
            # delay FSM drain.
            gap = (
                0.05 + gap_rng.random() * 0.8
                if gap_rng.random() < 0.8
                else 2.0 + gap_rng.random() * 4.0
            )
            net.loop.advance(gap)
            if event_hook is not None:
                event_hook(net, ev_i, net.loop.clock.now())
        net.loop.advance(settle)
        if event_hook is not None:
            event_hook(net, events, net.loop.clock.now())
        swept = tracker.sweep()
        timelines = tracker.timelines()
        report = storm_report(timelines)
        report["events"] = events
        report["swept-open"] = swept
        report["n-routers"] = n_routers
        report["spf-runs"] = net.inst.spf_run_count
        report["fib-size"] = len(net.kernel.fib)
        # Multipath surface (ISSUE 10): cumulative installs that carried
        # real next-hop SETS / UCMP weight groups (cumulative, so a
        # storm that happens to END mid-failure — repairs holding
        # single-survivor sets — still reports the multipath activity).
        report["fib-multipath"] = getattr(
            net.kernel, "multipath_installs", 0
        )
        report["fib-weighted"] = getattr(net.kernel, "weighted_installs", 0)
        # REAL per-trigger dispatch seconds (never in the digest: wall
        # time is nondeterministic by nature; the determinism gate is
        # the virtual timelines + FIB digest above).
        report["dispatch-wall"] = {
            trig: _percentiles(vals)
            for trig, vals in sorted(dispatch_wall.items())
        }
        return report, storm_digest(timelines), net
    finally:
        restore_dispatch()
        convergence.configure(0)

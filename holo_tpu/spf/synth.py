"""Synthetic LSDB generators for tests and benchmarks.

Produce :class:`Topology` objects honoring the OSPF vertex model the SPF
engine assumes (SURVEY.md §3.3):

- vertex indices in tie-break order: transit networks first, then routers
  (holo-ospf/src/ospfv2/spf.rs:42-45 orders Network < Router);
- router→router (p2p) and router→network links cost >= 1;
- network→router links cost 0 (RFC 2328 §16.1);
- ``edge_direct_atom`` assigned exactly where the reference computes next
  hops directly (parent hops == 0: edges out of the root, and edges out of
  root-adjacent transit networks — holo-ospf/src/spf.rs:744-767).
"""

from __future__ import annotations

import numpy as np

from holo_tpu.ops.graph import Topology


def clone_topology(
    topo: Topology,
    keep: np.ndarray | None = None,
    extra=None,
    cost: dict | None = None,
) -> Topology:
    """Fresh-identity copy of ``topo`` with optional edge mutations —
    the shared mutation helper for DeltaPath tests, fuzzing, and the
    bench chains.  ``keep``: bool[E] edge filter; ``extra``: rows of
    (src, dst, cost, atom) to append; ``cost``: {edge index: new cost}
    over the (post-filter) edge array.  The result has its own
    uid/generation
    (a distinct marshal-cache identity) and NO delta lineage."""
    src, dst, c, atom = (
        topo.edge_src, topo.edge_dst, topo.edge_cost, topo.edge_direct_atom
    )
    if keep is not None:
        src, dst, c, atom = src[keep], dst[keep], c[keep], atom[keep]
    else:
        src, dst, c, atom = src.copy(), dst.copy(), c.copy(), atom.copy()
    if cost is not None:
        for i, v in cost.items():
            c[i] = v
    if extra is not None:
        e = np.asarray(extra, np.int32).reshape(-1, 4)
        src = np.concatenate([src, e[:, 0]])
        dst = np.concatenate([dst, e[:, 1]])
        c = np.concatenate([c, e[:, 2]])
        atom = np.concatenate([atom, e[:, 3]])
    return Topology(
        n_vertices=topo.n_vertices,
        is_router=topo.is_router.copy(),
        edge_src=src, edge_dst=dst, edge_cost=c, edge_direct_atom=atom,
        root=topo.root,
        # The native partition hint is per-vertex state: mutation
        # chains keep it, or diff_topologies refuses to link the delta
        # (the partitioned resident's cut geometry would go stale).
        partition_hint=(
            None
            if topo.partition_hint is None
            else topo.partition_hint.copy()
        ),
    )


def assign_direct_atoms(topo: Topology) -> int:
    """Assign next-hop atom ids in-place; returns the atom count.

    One atom per root out-edge (p2p neighbor / attached network interface),
    plus one per (root-adjacent network → attached router) pair — i.e. the
    distinct (interface, neighbor address) next hops OSPF can produce for
    intra-area destinations.
    """
    atom = np.full(topo.n_edges, -1, np.int32)
    next_id = 0
    root_nets = set()
    for e in range(topo.n_edges):
        if topo.edge_src[e] == topo.root:
            atom[e] = next_id
            next_id += 1
            dst = int(topo.edge_dst[e])
            if not topo.is_router[dst]:
                root_nets.add(dst)
    for e in range(topo.n_edges):
        s = int(topo.edge_src[e])
        if s in root_nets and topo.edge_dst[e] != topo.root:
            atom[e] = next_id
            next_id += 1
    topo.edge_direct_atom = atom
    topo.touch()
    return next_id


def random_ospf_topology(
    n_routers: int,
    n_networks: int = 0,
    extra_p2p: int | None = None,
    max_cost: int = 20,
    seed: int = 0,
    root: int | None = None,
) -> Topology:
    """Random connected OSPF-style topology.

    Routers are joined by a random spanning tree plus ``extra_p2p`` random
    p2p links (both directions, independent costs — OSPF link costs are
    per-direction).  Each transit network connects 2-5 random routers.
    """
    rng = np.random.default_rng(seed)
    n = n_networks + n_routers  # networks occupy indices [0, n_networks)
    is_router = np.zeros(n, bool)
    is_router[n_networks:] = True
    rtr = lambda i: n_networks + i

    src, dst, cost = [], [], []

    def add(a, b, c):
        src.append(a)
        dst.append(b)
        cost.append(c)

    # Random spanning tree over routers.
    order = rng.permutation(n_routers)
    for i in range(1, n_routers):
        a, b = rtr(order[i]), rtr(order[rng.integers(0, i)])
        add(a, b, int(rng.integers(1, max_cost + 1)))
        add(b, a, int(rng.integers(1, max_cost + 1)))

    if extra_p2p is None:
        extra_p2p = n_routers
    seen = set(zip(src, dst))
    for _ in range(extra_p2p):
        a, b = rng.integers(0, n_routers, 2)
        if a == b:
            continue
        a, b = rtr(a), rtr(b)
        if (a, b) in seen:
            continue
        seen.add((a, b))
        seen.add((b, a))
        add(a, b, int(rng.integers(1, max_cost + 1)))
        add(b, a, int(rng.integers(1, max_cost + 1)))

    # Transit networks.
    for net in range(n_networks):
        k = int(rng.integers(2, 6))
        members = rng.choice(n_routers, size=min(k, n_routers), replace=False)
        for m in members:
            add(rtr(m), net, int(rng.integers(1, max_cost + 1)))
            add(net, rtr(m), 0)

    topo = Topology(
        n_vertices=n,
        is_router=is_router,
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        edge_cost=np.array(cost, np.int32),
        root=rtr(0) if root is None else root,
    )
    assign_direct_atoms(topo)
    return topo


def fat_tree_topology(k: int = 20, seed: int = 0) -> Topology:
    """Three-tier fat-tree of p2p router links (the 10k-node benchmark shape).

    k pods × (k/2 edge + k/2 agg) + (k/2)^2 core routers; k=20 → 300 core +
    20×20 pod routers = 700... scaled variant: use ``k`` and ``hosts`` to hit
    target sizes.  Costs are uniform 1 (typical DC) with per-direction
    symmetric entries.
    """
    rng = np.random.default_rng(seed)
    half = k // 2
    n_core = half * half
    n_agg = k * half
    n_edge = k * half
    n = n_core + n_agg + n_edge
    core = lambda i: i
    agg = lambda p, i: n_core + p * half + i
    edge = lambda p, i: n_core + n_agg + p * half + i

    src, dst, cost = [], [], []

    def add2(a, b):
        c1 = int(rng.integers(1, 4))
        c2 = int(rng.integers(1, 4))
        src.extend((a, b))
        dst.extend((b, a))
        cost.extend((c1, c2))

    for p in range(k):
        for i in range(half):
            for j in range(half):
                add2(agg(p, i), edge(p, j))  # intra-pod full bipartite
            for j in range(half):
                add2(agg(p, i), core(i * half + j))  # agg i ↔ its core group

    topo = Topology(
        n_vertices=n,
        is_router=np.ones(n, bool),
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        edge_cost=np.array(cost, np.int32),
        root=edge(0, 0),
    )
    assign_direct_atoms(topo)
    return topo


def ring_topology(n_routers: int, max_cost: int = 10, seed: int = 0) -> Topology:
    """Router ring (the canonical LFA-coverage-gap shape: with uniform
    costs half the ring has no per-neighbor LFA and needs rLFA/TI-LFA)."""
    rng = np.random.default_rng(seed)
    src, dst, cost = [], [], []
    for i in range(n_routers):
        j = (i + 1) % n_routers
        src.extend((i, j))
        dst.extend((j, i))
        cost.extend(
            (int(rng.integers(1, max_cost + 1)), int(rng.integers(1, max_cost + 1)))
        )
    topo = Topology(
        n_vertices=n_routers,
        is_router=np.ones(n_routers, bool),
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        edge_cost=np.array(cost, np.int32),
        root=0,
    )
    assign_direct_atoms(topo)
    return topo


def grid_topology(rows: int, cols: int, max_cost: int = 10, seed: int = 0) -> Topology:
    """rows×cols router grid with per-direction random costs."""
    rng = np.random.default_rng(seed)
    n = rows * cols
    vid = lambda r, c: r * cols + c
    src, dst, cost = [], [], []

    def add2(a, b):
        src.extend((a, b))
        dst.extend((b, a))
        cost.extend(
            (int(rng.integers(1, max_cost + 1)), int(rng.integers(1, max_cost + 1)))
        )

    for r in range(rows):
        for c in range(cols):
            if c + 1 < cols:
                add2(vid(r, c), vid(r, c + 1))
            if r + 1 < rows:
                add2(vid(r, c), vid(r + 1, c))
    topo = Topology(
        n_vertices=n,
        is_router=np.ones(n, bool),
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        edge_cost=np.array(cost, np.int32),
        root=0,
    )
    assign_direct_atoms(topo)
    return topo


def multiarea_topology(
    n_areas: int,
    rows: int,
    cols: int,
    gateways: int = 4,
    max_cost: int = 10,
    inter_cost: int = 5,
    seed: int = 0,
    hint: bool = True,
) -> Topology:
    """Hub-and-spoke multi-area synth (ISSUE 15): ``n_areas`` grid
    areas of ``rows x cols`` routers, area 0 the backbone, every other
    area joined to it through ``gateways`` gateway-router pairs — the
    OSPF area-0 shape the hierarchical partitioned SPF is designed for
    (small per-area boundary sets, cut edges only at gateways).

    Vertex ids are area-major (area a owns [a*rows*cols, (a+1)*rows*
    cols)), so the flat BFS/greedy cut re-discovers the areas when the
    native hint is withheld (``hint=False`` — the "flat" bench arm).
    Fully vectorized: usable at 100k+ vertices.  Root is backbone
    vertex 0; direct next-hop atoms assigned as usual."""
    rng = np.random.default_rng(seed)
    per = rows * cols
    n = n_areas * per
    vid = np.arange(per).reshape(rows, cols)
    h_src = vid[:, :-1].ravel()
    h_dst = vid[:, 1:].ravel()
    v_src = vid[:-1, :].ravel()
    v_dst = vid[1:, :].ravel()
    a_src = np.concatenate([h_src, h_dst, v_src, v_dst])
    a_dst = np.concatenate([h_dst, h_src, v_dst, v_src])
    e_per = a_src.shape[0]
    src = (
        a_src[None, :] + (np.arange(n_areas) * per)[:, None]
    ).ravel()
    dst = (
        a_dst[None, :] + (np.arange(n_areas) * per)[:, None]
    ).ravel()
    cost = rng.integers(1, max_cost + 1, src.shape[0])
    # Gateways: area a>0 vertex g*cols (left-edge spread) <-> backbone
    # vertex g*cols + a (distinct backbone attach points per area).
    g = np.arange(min(gateways, rows))
    gs, gd, gc = [src], [dst], [cost]
    for a in range(1, n_areas):
        leaf = a * per + g * cols
        hub = (g * cols + a) % per
        gs.append(np.concatenate([leaf, hub]))
        gd.append(np.concatenate([hub, leaf]))
        gc.append(
            rng.integers(1, inter_cost + 1, 2 * g.shape[0])
        )
    src = np.concatenate(gs).astype(np.int32)
    dst = np.concatenate(gd).astype(np.int32)
    cost = np.concatenate(gc).astype(np.int32)
    del e_per
    topo = Topology(
        n_vertices=n,
        is_router=np.ones(n, bool),
        edge_src=src,
        edge_dst=dst,
        edge_cost=cost,
        root=0,
        partition_hint=(
            np.repeat(np.arange(n_areas, dtype=np.int32), per)
            if hint
            else None
        ),
    )
    assign_direct_atoms(topo)
    return topo


def whatif_link_failure_masks(topo: Topology, n_scenarios: int, seed: int = 0) -> np.ndarray:
    """bool[B, E] masks, each failing one bidirectional link (both directions).

    Scenario 0 is always the no-failure base case.
    """
    rng = np.random.default_rng(seed)
    pair_of = {}
    for e in range(topo.n_edges):
        pair_of[(int(topo.edge_src[e]), int(topo.edge_dst[e]))] = e
    masks = np.ones((n_scenarios, topo.n_edges), bool)
    for b in range(1, n_scenarios):
        e = int(rng.integers(0, topo.n_edges))
        masks[b, e] = False
        rev = pair_of.get((int(topo.edge_dst[e]), int(topo.edge_src[e])))
        if rev is not None:
            masks[b, rev] = False
    return masks

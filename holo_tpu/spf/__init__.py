"""SPF backends: scalar CPU reference (default) and TPU/JAX engine (opt-in).

Mirrors the reference's dispatch shape: the SPF-delay FSM's compute call
(holo-ospf/src/spf.rs:428-435) is the single point where a backend is invoked,
so protocols are backend-agnostic.  The scalar backend IS the semantics spec;
the TPU backend must match it bit-for-bit (tests/test_spf_parity.py).
"""

from holo_tpu.spf.backend import ScalarSpfBackend, SpfBackend, SpfResult, TpuSpfBackend
from holo_tpu.spf.scalar import spf_reference

__all__ = [
    "SpfBackend",
    "SpfResult",
    "ScalarSpfBackend",
    "TpuSpfBackend",
    "spf_reference",
]

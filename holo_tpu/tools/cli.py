"""Dev tools CLI.  See package docstring for commands."""

from __future__ import annotations

import argparse
import json
import sys


def cmd_schema(args) -> int:
    from holo_tpu.yang.modules import full_schema
    from holo_tpu.yang.schema import Container, Leaf, LeafList, List

    def walk(node, indent=0):
        pad = "  " * indent
        if isinstance(node, Leaf):
            extra = f" [{node.type}]"
            if node.default is not None:
                extra += f" = {node.default}"
            print(f"{pad}{node.name}{extra}")
        elif isinstance(node, LeafList):
            print(f"{pad}{node.name}* [{node.type}]")
        elif isinstance(node, List):
            print(f"{pad}{node.name}[{node.key}]/")
            for c in node.children.values():
                walk(c, indent + 1)
        elif isinstance(node, Container):
            print(f"{pad}{node.name}/")
            for c in node.children.values():
                walk(c, indent + 1)

    schema = full_schema()
    roots = [args.module] if args.module else sorted(schema.roots)
    for name in roots:
        node = schema.roots.get(name)
        if node is None:
            print(f"no module {name!r}", file=sys.stderr)
            return 1
        walk(node)
    return 0


def cmd_coverage(args) -> int:
    from holo_tpu.yang.modules import full_schema
    from holo_tpu.yang.schema import Container, Leaf, LeafList, List

    def count(node):
        leaves = lists = containers = 0
        if isinstance(node, (Leaf, LeafList)):
            return 1, 0, 0
        if isinstance(node, List):
            lists = 1
        elif isinstance(node, Container):
            containers = 1
        for c in getattr(node, "children", {}).values():
            l2, li2, c2 = count(c)
            leaves += l2
            lists += li2
            containers += c2
        return leaves, lists, containers

    total = [0, 0, 0]
    for name, node in sorted(full_schema().roots.items()):
        l, li, c = count(node)
        total[0] += l
        total[1] += li
        total[2] += c
        print(f"{name:20s} leaves={l:3d} lists={li:2d} containers={c:2d}")
    print(f"{'TOTAL':20s} leaves={total[0]:3d} lists={total[1]:2d} "
          f"containers={total[2]:2d}")
    return 0


def cmd_validate(args) -> int:
    from holo_tpu.yang.data import DataTree
    from holo_tpu.yang.modules import full_schema
    from holo_tpu.yang.schema import SchemaError

    text = open(args.file).read() if args.file != "-" else sys.stdin.read()
    try:
        DataTree.from_json(full_schema(), text)
    except (SchemaError, json.JSONDecodeError) as e:
        print(f"INVALID: {e}")
        return 1
    print("valid")
    return 0


def cmd_replay(args) -> int:
    from ipaddress import IPv4Address, IPv4Network

    from holo_tpu.protocols.ospf.instance import IfConfig, InstanceConfig, OspfInstance
    from holo_tpu.protocols.ospf.interface import IfType
    from holo_tpu.utils.event_recorder import replay
    from holo_tpu.utils.runtime import EventLoop, VirtualClock

    setup = json.load(open(args.setup))
    loop = EventLoop(clock=VirtualClock())

    class NullIo:
        def send(self, *a):
            pass

    inst = OspfInstance(
        name=setup.get("actor", "ospfv2"),
        config=InstanceConfig(router_id=IPv4Address(setup["router-id"])),
        netio=NullIo(),
    )
    loop.register(inst)
    for ifname, icfg in setup.get("interfaces", {}).items():
        inst.add_interface(
            ifname,
            IfConfig(
                area_id=IPv4Address(icfg.get("area", "0.0.0.0")),
                if_type=(
                    IfType.POINT_TO_POINT
                    if icfg.get("type") == "point-to-point"
                    else IfType.BROADCAST
                ),
                cost=icfg.get("cost", 10),
            ),
            IPv4Network(icfg["prefix"], strict=False),
            IPv4Address(icfg["address"]),
        )
    n = replay(args.events, loop)
    print(f"replayed {n} events")
    for aid, area in inst.areas.items():
        print(f"area {aid}: {len(area.lsdb.entries)} LSAs")
        for key in sorted(area.lsdb.entries, key=str):
            e = area.lsdb.entries[key]
            print(f"  {key.type.name:16s} {key.lsid} adv={key.adv_rtr} "
                  f"seq={e.lsa.seq_no}")
    print(f"routes ({len(inst.routes)}):")
    for prefix, route in sorted(inst.routes.items(), key=lambda kv: str(kv[0])):
        nhs = sorted(f"{nh.ifname}:{nh.addr}" for nh in route.nexthops)
        print(f"  {prefix} dist={route.dist} via {nhs}")
    return 0


def cmd_conformance(args) -> int:
    from pathlib import Path

    if getattr(args, "protocol", "ospf") == "isis":
        from holo_tpu.tools.conformance_isis import (
            REFERENCE_CONFORMANCE_ISIS as corpus,
            run_topology,
        )
    else:
        from holo_tpu.tools.conformance import (
            REFERENCE_CONFORMANCE as corpus,
            run_topology,
        )

    if args.topo_dir:
        dirs = [Path(args.topo_dir)]
    elif corpus.exists():
        dirs = sorted(p for p in corpus.iterdir() if p.is_dir())
    else:
        print(f"conformance corpus not found at {corpus}", file=sys.stderr)
        return 2
    total = ok = 0
    failed = False
    for topo in dirs:
        results = run_topology(topo)
        bad = {rt: p for rt, p in results.items() if p}
        total += len(results)
        ok += len(results) - len(bad)
        print(f"{topo.name}: {len(results) - len(bad)}/{len(results)} conformant")
        for rt, problems in bad.items():
            failed = True
            for p in problems:
                print(f"    {rt}: {p}")
    print(f"TOTAL: {ok}/{total} routers bit-identical")
    return 1 if failed else 0


def _print_table(headers, rows, top=None, indent="  ") -> None:
    """The one fixed-width table renderer ``trace`` / ``postmortem`` /
    ``explain`` share (previously two hand-rolled variants).  ``top``
    truncates AFTER the caller's sort — cost-center ranking lives with
    the data, not the renderer."""
    if top is not None:
        rows = rows[:top]
    rows = [[str(c) for c in r] for r in rows]
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    print(
        (indent + "  ".join(h.ljust(w) for h, w in zip(headers, widths)))
        .rstrip()
    )
    for r in rows:
        print(
            (indent + "  ".join(c.ljust(w) for c, w in zip(r, widths)))
            .rstrip()
        )


def _snapshot_cost_rows(snap: dict) -> list[tuple]:
    """Metric-snapshot rows ranked as cost centers: histograms by total
    seconds, scalars by value, descending."""
    rows = []
    for name, v in snap.items():
        if isinstance(v, dict):
            rows.append((name, v.get("count", 0), float(v.get("sum", 0.0))))
        else:
            rows.append((name, "", float(v)))
    rows.sort(key=lambda r: (-r[2], r[0]))
    return [(n, c, f"{s:g}") for n, c, s in rows]


def cmd_trace(args) -> int:
    """Run a synthetic SPF + FRR workload with span tracing and dump the
    spans as Chrome trace-event JSON (load in chrome://tracing or
    https://ui.perfetto.dev) — the quickest way to SEE where a dispatch
    spends its time.  A daemon produces the same artifact at stop via
    ``[telemetry] trace-dump`` or ``HOLO_TPU_TRACE_DUMP=<path>``."""
    from holo_tpu import telemetry
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import grid_topology, whatif_link_failure_masks

    topo = grid_topology(args.rows, args.rows, seed=1)
    backend = TpuSpfBackend()
    with telemetry.span("trace.workload", instance="synth"):
        for _ in range(max(args.repeat, 1)):
            backend.compute(topo)
        masks = whatif_link_failure_masks(topo, 8, seed=2)
        backend.compute_whatif(topo, masks)
        FrrEngine("tpu").compute(topo)
    n = telemetry.tracer().dump(args.output)
    print(f"wrote {n} spans to {args.output}")
    snap = telemetry.snapshot(prefix="holo_spf")
    print(f"top {args.top} cost centers:")
    _print_table(
        ("metric", "count", "total"),
        _snapshot_cost_rows(snap),
        top=args.top,
    )
    return 0


def _explain_workload(k: int, batch: int, reps: int, seed: int) -> None:
    """The explain CLI's seeded dispatch mix: repeated single-SPF runs
    (the tuner's explore rounds), what-if batches, the multipath
    k ∈ {1,2,4,8} sweep (the A-lane gather cost the ROADMAP carries),
    and one FRR all-roots batch.  With the default ``reps`` the tuner
    stays inside its deterministic explore phase, so a deterministic
    stage timer makes the whole run byte-identical."""
    from holo_tpu.frr.manager import FrrEngine
    from holo_tpu.spf.backend import TpuSpfBackend
    from holo_tpu.spf.synth import (
        fat_tree_topology,
        whatif_link_failure_masks,
    )

    topo = fat_tree_topology(k=k, seed=seed)
    masks = whatif_link_failure_masks(topo, batch, seed=seed + 1)
    backend = TpuSpfBackend()
    for _ in range(max(reps, 1)):
        backend.compute(topo)
    for _ in range(max(reps, 1)):
        backend.compute_whatif(topo, masks)
    for kk in (1, 2, 4, 8):
        for _ in range(2):
            backend.compute(topo, multipath_k=kk)
    FrrEngine("tpu").compute(topo)


def cmd_explain(args) -> int:
    """Dispatch-observatory report (ISSUE 12): run a seeded workload —
    the synthetic dispatch mix, or a full convergence storm with
    ``--storm`` — with the observatory, deep profiling, and the engine
    tuner armed, then render top-k cost centers with sketch-derived
    p50/p99, per-(engine, shape-bucket) roofline attribution (achieved
    FLOP/s, bytes/s, arithmetic intensity, memory-/compute-bound
    verdict), the tuner's win/loss ledger, and the sentinel state.

    Deterministic by default: the stage timer is a counter clock, so
    two same-seed runs print byte-identical reports (walls become
    timer-read counts — the classification and attribution signal is
    real; pass ``--wall-clock`` for honest walls at the price of
    run-to-run jitter)."""
    from holo_tpu.pipeline import tuner as tuner_mod
    from holo_tpu.telemetry import critpath, observatory, profiling

    if not args.wall_clock:
        profiling.set_stage_timer(observatory.DeterministicTimer())
    profiling.set_device_profiling(True)
    obs = observatory.configure(
        check_every=16,
        ledger_path=args.ledger,
    )
    # Critical-path ledger (ISSUE 17): stamps read the same stage
    # timer as the observatory, so the waterfall section inherits the
    # byte-identical contract under the deterministic counter clock.
    cp = critpath.configure(check_every=16) if args.critical_path else None
    # SLO plane (ISSUE 20): the engine clock is profiling.clock, so the
    # burn/budget arithmetic inherits the byte-identical contract under
    # the deterministic counter clock exactly like the ledgers above.
    sl = None
    prober = None
    if args.slo:
        from holo_tpu.telemetry import relay, slo

        sl = slo.configure(check_every=16)
        st = relay.status()
        if st["status"] != "unknown":
            # The relay availability objective grades real watch
            # verdicts only — a process that never probed the relay
            # reports the row as budget-unknown rather than faking one.
            sl.note_relay(st["status"] == "up")
    tuner = tuner_mod.configure_engine_tuner()
    try:
        if args.storm:
            from holo_tpu.spf.synth_storm import run_convergence_storm

            hook = None
            if sl is not None:
                from holo_tpu.telemetry import canary

                state: dict = {}

                def hook(net, i, now):
                    if "prober" not in state:
                        # Arm on the first hook tick: the storm loop
                        # only exists once the net is built.  Virtual
                        # heartbeats fire during every advance from
                        # here on — deterministic probe schedule.
                        state["prober"] = canary.CanaryProber(
                            net.loop, period=2.0, warmup=10.0
                        )
                        state["prober"].start()

                run_convergence_storm(
                    n_routers=args.storm, events=args.events,
                    seed=args.seed, event_hook=hook,
                )
                prober = state.get("prober")
                if prober is not None:
                    prober.stop()
            else:
                run_convergence_storm(
                    n_routers=args.storm, events=args.events,
                    seed=args.seed,
                )
        else:
            _explain_workload(args.k, args.batch, args.reps, args.seed)
        # Close the run's sentinel window: seed/compare every key now
        # (not just those that crossed a check_every boundary) and
        # persist the --ledger baseline for the next invocation.
        obs.checkpoint()
        doc = obs.report(top=args.top)
        doc["tuner"] = tuner.ledger()
        if cp is not None:
            cp.checkpoint()
            doc["critical_path"] = cp.report(top=args.top)
        if sl is not None:
            sl.checkpoint()
            doc["slo"] = sl.report()
            if prober is not None:
                doc["slo"]["canary"] = prober.stats()
        if args.json:
            print(json.dumps(doc, sort_keys=True, indent=2))
            return 0
        peaks = doc["peaks"]
        print(
            f"dispatch observatory — timing: {doc['timing']}, peaks: "
            f"{peaks['source']} "
            f"(ridge {peaks['ridge_flops_per_byte']:g} flop/B)"
        )
        print(f"top {args.top} cost centers:")
        _print_table(
            ("site/stage", "engine", "kind", "bucket", "n",
             "total_s", "p50_ms", "p99_ms"),
            [
                (
                    f"{r['site']}/{r['stage']}", r["engine"], r["kind"],
                    json.dumps(r["bucket"], separators=(",", ":")),
                    r["count"], f"{r['total_s']:g}",
                    f"{r['p50_s'] * 1e3:.3f}", f"{r['p99_s'] * 1e3:.3f}",
                )
                for r in doc["cost_centers"]
            ],
        )
        print("roofline (per engine × shape-bucket):")
        _print_table(
            ("site", "engine", "kind", "bucket", "AI", "verdict",
             "flop/s", "B/s", "roofline", "p50_ms", "p99_ms"),
            [
                (
                    r["site"], r["engine"], r["kind"],
                    json.dumps(r["bucket"], separators=(",", ":")),
                    (
                        f"{r['ai_flops_per_byte']:g}"
                        if r["ai_flops_per_byte"] is not None
                        else "-"
                    ),
                    r["verdict"],
                    (
                        f"{r['achieved_flops_per_sec']:.3e}"
                        if r.get("achieved_flops_per_sec")
                        else "-"
                    ),
                    (
                        f"{r['achieved_bytes_per_sec']:.3e}"
                        if r.get("achieved_bytes_per_sec")
                        else "-"
                    ),
                    (
                        f"{r['roofline_fraction']:.2%}"
                        if r.get("roofline_fraction") is not None
                        else "-"
                    ),
                    (
                        f"{r['device_p50_s'] * 1e3:.3f}"
                        if r.get("device_p50_s") is not None
                        else "-"
                    ),
                    (
                        f"{r['device_p99_s'] * 1e3:.3f}"
                        if r.get("device_p99_s") is not None
                        else "-"
                    ),
                )
                for r in doc["roofline"]
            ],
        )
        print("engine tuner win/loss ledger:")
        _print_table(
            ("kind", "bucket", "winner", "dispatches", "measured", "basis"),
            [
                (
                    t["kind"],
                    json.dumps(t["bucket"], separators=(",", ":")),
                    t["winner"], t["dispatches"],
                    ",".join(
                        f"{e}={v['median_ms']}ms"
                        for e, v in t["engines"].items()
                    ),
                    t["basis"],
                )
                for t in doc["tuner"]
            ],
        )
        s = doc["sentinel"]
        print(
            f"sentinel: {s['ledger-entries']} ledger entries, "
            f"{s['seeded']} seeded, {s['ratcheted']} ratcheted, "
            f"{s['flags']} flags"
            + (f", regressed: {', '.join(s['regressed'])}"
               if s["regressed"] else "")
        )
        if cp is not None:
            cpd = doc["critical_path"]
            v = cpd["verdicts"]
            hf = cpd["host-fraction-p99"]
            uf = cpd["unattributed-frac-p50"]
            print(
                f"critical path — {cpd['completed']} events "
                f"({cpd['dropped']} dropped), verdicts: "
                f"host={v['host']} queue={v['queue']} "
                f"device={v['device']}, host-fraction-p99: "
                + (f"{hf:.2%}" if hf is not None else "-")
                + ", unattributed-frac-p50: "
                + (f"{uf:.2%}" if uf is not None else "-")
            )
            print("phase ledger (cut order):")
            _print_table(
                ("phase", "p50_ms", "p99_ms", "mean_ms", "share_p99"),
                [
                    (
                        r["phase"], f"{r['p50'] * 1e3:.3f}",
                        f"{r['p99'] * 1e3:.3f}",
                        f"{r['mean'] * 1e3:.3f}",
                        f"{r['share_p99']:.2%}",
                    )
                    for r in cpd["phases"]
                ],
            )
            print(f"last {len(cpd['events'])} waterfalls:")
            _print_table(
                ("n", "trigger", "verdict", "wall_ms", "top phases",
                 "stalls"),
                [
                    (
                        w["n"], w["trigger"], w["verdict"],
                        f"{w['wall'] * 1e3:.3f}",
                        " ".join(
                            f"{p}={w['phases'][p] * 1e3:.3f}ms"
                            for p, _ in sorted(
                                w["phases"].items(),
                                key=lambda kv: (-kv[1], kv[0]),
                            )[:3]
                            if w["phases"][p] > 0.0
                        ) or "-",
                        w["stalls"],
                    )
                    for w in cpd["events"]
                ],
            )
        if sl is not None:
            sld = doc["slo"]
            w = sld["windows"]
            print(
                f"slo — windows: fast {w['fast_s']:g}s / slow "
                f"{w['slow_s']:g}s, burn limits "
                f"{w['fast_burn_limit']:g}/{w['slow_burn_limit']:g}"
            )
            _print_table(
                ("objective", "kind", "events", "good", "bad",
                 "burn_fast", "burn_slow", "budget", "fires",
                 "measured_p99_ms"),
                [
                    (
                        r["objective"], r["kind"], r["events"],
                        r["good_fast"], r["bad_fast"],
                        (
                            f"{r['burn_fast']:g}"
                            if r["burn_fast"] is not None else "-"
                        ),
                        (
                            f"{r['burn_slow']:g}"
                            if r["burn_slow"] is not None else "-"
                        ),
                        (
                            f"{r['budget_remaining']:g}"
                            if r["budget_remaining"] is not None else "-"
                        ),
                        r["sentinel_fires_fast"] + r["sentinel_fires_slow"],
                        (
                            f"{r['measured_ms']['p99']:g}"
                            if r.get("measured_ms") else "-"
                        ),
                    )
                    for r in sld["objectives"]
                ],
            )
            if sld["sheds"]:
                print(
                    "sheds: " + ", ".join(
                        f"{k}={v}" for k, v in sld["sheds"].items()
                    )
                )
            if "canary" in sld:
                c = sld["canary"]
                print(
                    f"canary: {c['probes']} probes, "
                    f"{c['attributed']} attributed, "
                    f"{c['unattributed']} unattributed, "
                    f"{c['failed']} failed ({c['sheds']} shed, "
                    f"{c['overdue']} overdue)"
                )
        return 0
    finally:
        observatory.configure(enabled=False)
        if cp is not None:
            critpath.configure(0)
        if sl is not None:
            from holo_tpu.telemetry import slo

            slo.configure(False)
        profiling.set_device_profiling(False)
        profiling.set_stage_timer(None)
        tuner_mod.reset_engine_tuner()


def cmd_import_yang(args) -> int:
    """Parse YANG text file(s) and dump the resulting schema subtrees —
    the libyang-load analog for externally authored modules.  Multiple
    files form one module set with cross-module grouping/typedef
    resolution (pass every import together, like a libyang context)."""
    from pathlib import Path

    from holo_tpu.yang.parser import load_modules
    from holo_tpu.yang.schema import Container, Leaf, LeafList, List, SchemaError

    try:
        mods = load_modules(
            [Path(f).read_text() for f in args.files]
        )
    except (OSError, UnicodeDecodeError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    nodes = [n for ns in mods.values() for n in ns]
    if not nodes:
        print("(no config data nodes — augment/identity-only modules)")

    def dump(node, depth=0):
        pad = "  " * depth
        if isinstance(node, Leaf):
            extra = f" = {node.default!r}" if node.default is not None else ""
            enum = f" {{{','.join(node.enum)}}}" if node.enum else ""
            ro = "" if node.config else " (state)"
            print(f"{pad}{node.name} [{node.type}{enum}]{extra}{ro}")
        elif isinstance(node, LeafList):
            print(f"{pad}{node.name}* [{node.type}]")
        elif isinstance(node, List):
            print(f"{pad}{node.name}[{node.key}]/")
            for c in node.children.values():
                dump(c, depth + 1)
        elif isinstance(node, Container):
            p = " (presence)" if node.presence else ""
            print(f"{pad}{node.name}/{p}")
            for c in node.children.values():
                dump(c, depth + 1)

    for node in nodes:
        dump(node)
    return 0


def cmd_deviations(args) -> int:
    """Generate a "not-supported" deviations skeleton for a YANG module
    (reference holo-tools/src/yang_deviations.rs): one commented-out
    ``deviate not-supported`` per schema node, fully prefixed, ready for
    an implementer to uncomment for the nodes they do NOT support.
    Extra files are the module's imports (one context, like libyang)."""
    from pathlib import Path

    from holo_tpu.yang.parser import load_modules, parse_text
    from holo_tpu.yang.schema import SchemaError

    try:
        texts = [Path(f).read_text() for f in args.files]
        target = parse_text(texts[0])
    except (OSError, UnicodeDecodeError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if target.keyword != "module":
        print("error: first file must be a YANG module", file=sys.stderr)
        return 2
    name = target.arg
    pfx_stmt = target.sub("prefix")
    prefix = pfx_stmt.arg if pfx_stmt is not None else name
    try:
        mods = load_modules(texts)
    except SchemaError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"module holo-{name}-deviations {{")
    print("  yang-version 1.1;")
    print(
        f'  namespace "http://holo-routing.org/yang/holo-{name}-deviations";'
    )
    print(f"  prefix holo-{name}-deviations;")
    print(f"\n  import {name} {{\n    prefix {prefix};\n  }}")
    print('\n  organization\n    "Holo Routing Stack";')
    print(
        f'\n  description\n    "This module defines deviation statements '
        f'for the {name}\n     module.";'
    )

    def emit(node, path):
        path = f"{path}/{prefix}:{node.name}"
        print(
            f"\n  /*\n  deviation \"{path}\" {{\n"
            f"    deviate not-supported;\n  }}\n  */"
        )
        for child in getattr(node, "children", {}).values():
            emit(child, path)

    for node in mods.get(name, []):
        emit(node, "")
    print("}")
    return 0


def cmd_postmortem(args) -> int:
    """Pretty-print a flight-recorder postmortem bundle (written by the
    daemon on breaker-open / crash-loop / SIGTERM when ``[telemetry]
    flight-buffer-entries`` + ``postmortem-dir`` are set).  ``--json``
    re-emits the canonical sorted JSON (diff two seeded runs with it)."""
    try:
        with open(args.bundle) as fh:
            bundle = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if bundle.get("schema") != "holo-postmortem/1":
        print(
            f"error: {args.bundle} is not a holo-postmortem/1 bundle",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(bundle, sort_keys=True, indent=2))
        return 0
    ring = bundle.get("ring", [])
    print(f"postmortem #{bundle.get('dump')}: {bundle.get('reason')}")
    kinds = {}
    for e in ring:
        kinds[e[0]] = kinds.get(e[0], 0) + 1
    print(
        f"ring: {len(ring)} entries ("
        + ", ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
        + ")"
    )
    for e in ring:
        if e[0] == "event":
            _, kind, fields, t = e
            kv = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
            print(f"  [{t:10.3f}] {kind:18s} {kv}")
    spans = [e for e in ring if e[0] == "span"]
    if spans:
        if args.top:
            # Cost-center view (shared with trace/explain): the
            # heaviest spans in the whole ring, duration-descending.
            picked = sorted(spans, key=lambda e: -e[5])[: args.top]
            print(f"top {len(picked)} spans by duration (of {len(spans)}):")
        else:
            picked = spans[-args.spans:]
            print(f"last spans ({len(picked)} of {len(spans)}):")
        rows = [
            (
                f"#{sid}",
                name,
                f"{dur / 1e3:.3f}ms",
                parent if parent is not None else "-",
                " ".join(f"{k}={v}" for k, v in sorted(attrs.items())),
            )
            for _, name, sid, parent, start, dur, attrs in picked
        ]
        _print_table(("span", "name", "wall", "parent", "attrs"), rows)
    health = bundle.get("health", {})
    for name, br in sorted(health.get("breakers", {}).items()):
        print(
            f"breaker {name}: {br['state']} "
            f"(failures={br['consecutive-failures']}"
            f"/{br['failure-threshold']}, last={br['last-error'] or '-'})"
        )
    sup = health.get("supervision")
    if sup:
        print(
            f"supervision: degraded={sup['degraded-actors'] or '-'} "
            f"restarts={sup['restarts']}"
        )
    metrics = bundle.get("metrics", {})
    if metrics:
        print(f"metric deltas since arm ({len(metrics)} series):")
        for name in sorted(metrics):
            print(f"  {name} += {metrics[name]}")
    tail = bundle.get("journal-tail", [])
    if tail:
        print(
            f"journal tail: seq {tail[0][0]}..{tail[-1][0]} "
            f"({len(tail)} markers)"
        )
    return 0


def cmd_lint(args) -> int:
    """holo-lint: repo-native static analysis (JAX hot-path hazards +
    daemon lock discipline), gated against a ratchet baseline.  Exit 0
    when the tree matches the baseline, 1 on new findings, 2 on usage
    or parse errors."""
    from pathlib import Path

    from holo_tpu.analysis import (
        audit_suppressions,
        compare_to_baseline,
        default_baseline_path,
        load_baseline,
        run_paths,
        run_paths_cached,
        self_check,
        write_baseline,
    )

    pkg_root = Path(__file__).resolve().parent.parent  # holo_tpu/
    repo_root = pkg_root.parent
    paths = [Path(p) for p in args.paths] if args.paths else [pkg_root]
    for p in paths:
        if not p.exists():
            print(f"error: no such path {p}", file=sys.stderr)
            return 2

    if args.list_rules:
        from holo_tpu.analysis import all_rules

        for rule in all_rules():
            print(
                f"{rule.id}  [{rule.family:6s}]  [{rule.severity:5s}]  "
                f"{rule.title}"
            )
        return 0

    # The incremental cache covers the default full-package scan only:
    # an ad-hoc `lint some/path` has a different file set and must not
    # overwrite the gate's cache (all-or-nothing validation would then
    # force the next gate run cold).
    use_cache = not args.no_cache and not args.paths
    if args.self_check:
        if not use_cache:
            # self_check exercises the default cache file; running it
            # over an ad-hoc path set would store that partial file
            # set and force the next gate run cold.
            print(
                "error: --self-check validates the full-package cache "
                "and cannot combine with --no-cache or explicit paths",
                file=sys.stderr,
            )
            return 2
        mismatches = self_check(
            paths, root=repo_root, audit=not args.no_audit
        )
        if mismatches:
            for m in mismatches:
                print(f"cache self-check: {m}", file=sys.stderr)
            print(
                "holo-lint: cache self-check FAILED — cached replay "
                "diverged from a cold scan (delete "
                ".holo_lint_cache.json and report this)",
                file=sys.stderr,
            )
            return 2
    if use_cache:
        result = run_paths_cached(paths, root=repo_root)
    else:
        result = run_paths(paths, root=repo_root)
    if result.parse_errors:
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
        return 2

    # The HL3xx jaxpr kernel audit joins the gate on the default
    # full-package lint only: an ad-hoc `lint some/path` checks files,
    # not compiled kernel contracts.  Audit findings merge into the
    # same baseline/suppression/severity machinery as the AST rules.
    audit = None
    if not args.paths and not args.no_audit:
        from holo_tpu.analysis import run_audit_cached

        audit = run_audit_cached(repo_root, no_cache=args.no_cache)
        result.findings.extend(audit.findings)
        result.suppressed.extend(audit.suppressed)

    stale_suppressions = (
        audit_suppressions(result) if args.check_suppressions else []
    )

    baseline_path = (
        Path(args.baseline) if args.baseline else default_baseline_path()
    )
    if args.write_baseline:
        write_baseline(baseline_path, result.findings)
        print(
            f"baseline: wrote {len(result.findings)} finding(s) to "
            f"{baseline_path}"
        )
        return 0

    baseline = load_baseline(baseline_path)
    new, unused = compare_to_baseline(result.findings, baseline)
    # Severity tiers: only error-tier findings gate (exit 1); warn-tier
    # findings render as warnings and ride the JSON report.
    from holo_tpu.analysis import gate_findings

    new_errors = gate_findings(new)
    new_warns = [f for f in new if f.severity != "error"]

    if args.json:
        doc = {
            # Bump schema_version whenever a field is added/renamed so
            # the sentinel ledger (BENCH observatory) can gate its
            # parser instead of silently misreading lint telemetry.
            # v3: adds the "audit" block (HL3xx jaxpr kernel audit).
            "schema_version": 3,
            "files_checked": result.files_checked,
            "files_cached": result.files_cached,
            # Wall seconds per rule id (whole run) — the ledger tracks
            # lint cost per rule as the module set grows.
            "rule_seconds": {
                k: round(v, 6)
                for k, v in sorted(result.rule_seconds.items())
            },
            # HL3xx jaxpr kernel audit telemetry: per-kernel lowering
            # wall seconds (0.0 for cache-replayed kernels) so the
            # ledger can track audit cost as the registry grows.  None
            # when the audit did not run (--no-audit or explicit paths).
            "audit": None if audit is None else {
                "kernels_checked": audit.kernels_checked,
                "kernels_cached": audit.kernels_cached,
                "skipped": sorted(audit.skipped),
                "device_count": audit.device_count,
                "kernel_seconds": {
                    k: round(v, 6)
                    for k, v in sorted(audit.kernel_seconds.items())
                },
            },
            "stale_suppressions": stale_suppressions,
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "context": f.context,
                    "message": f.message,
                    "severity": f.severity,
                    "baselined": f not in new,
                }
                for f in result.findings
            ],
            "new": len(new),
            "new_errors": len(new_errors),
            "new_warnings": len(new_warns),
            "suppressed": len(result.suppressed),
            "unused_baseline_keys": sorted(unused),
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in new_errors:
            print(f.render())
        for f in new_warns:
            print(f"warning: {f.render()}")
        for s in stale_suppressions:
            print(s)
        n_base = len(result.findings) - len(new)
        cached = (
            f" ({result.files_cached} cached)"
            if result.files_cached
            else ""
        )
        print(
            f"holo-lint: {result.files_checked} files{cached}, "
            f"{len(new_errors)} new error(s), "
            f"{len(new_warns)} new warning(s), {n_base} baselined, "
            f"{len(result.suppressed)} suppressed"
        )
        if audit is not None:
            a_cached = (
                f" ({audit.kernels_cached} cached)"
                if audit.kernels_cached
                else ""
            )
            a_skip = (
                f", {len(audit.skipped)} skipped (no mesh)"
                if audit.skipped
                else ""
            )
            print(
                f"holo-lint: audit {audit.kernels_checked} "
                f"kernel(s){a_cached} on {audit.device_count} "
                f"device(s){a_skip}"
            )
        if stale_suppressions:
            print(
                f"holo-lint: {len(stale_suppressions)} stale "
                "suppression(s) — delete the dead disable comment(s) "
                "or fix the rule id they name"
            )
        if unused:
            print(
                f"holo-lint: {sum(unused.values())} baseline entr"
                f"{'y is' if sum(unused.values()) == 1 else 'ies are'} "
                "stale (fixed) — ratchet by removing them:"
            )
            for key in sorted(unused):
                print(f"  {key}")
    return 1 if (new_errors or stale_suppressions) else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="holo-tpu-tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("schema", help="dump the management schema tree")
    s.add_argument("module", nargs="?")
    s.set_defaults(fn=cmd_schema)
    s = sub.add_parser("coverage", help="schema node counts per module")
    s.set_defaults(fn=cmd_coverage)
    s = sub.add_parser("validate", help="validate a JSON config")
    s.add_argument("file")
    s.set_defaults(fn=cmd_validate)
    s = sub.add_parser("replay", help="replay recorded events into OSPFv2")
    s.add_argument("events")
    s.add_argument("--setup", required=True,
                   help="JSON: router-id + interfaces layout")
    s.set_defaults(fn=cmd_replay)
    s = sub.add_parser(
        "conformance",
        help="run the reference conformance corpus (RIB bit-identity)",
    )
    s.add_argument("topo_dir", nargs="?",
                   help="one topology dir (default: all)")
    s.add_argument("--protocol", choices=("ospf", "isis"), default="ospf")
    s.set_defaults(fn=cmd_conformance)
    s = sub.add_parser(
        "trace",
        help="trace a synthetic SPF/FRR workload to Chrome trace JSON",
    )
    s.add_argument("-o", "--output", default="holo_tpu_trace.json")
    s.add_argument("--rows", type=int, default=6, help="grid topology side")
    s.add_argument("--repeat", type=int, default=3, help="single-SPF runs")
    s.add_argument(
        "--top", type=int, default=12,
        help="cost centers to print (metric rows, total-descending)",
    )
    s.set_defaults(fn=cmd_trace)
    s = sub.add_parser(
        "explain",
        help="dispatch-observatory report: top-k cost centers, roofline "
             "attribution, tuner win/loss ledger over a seeded workload",
    )
    s.add_argument("--top", type=int, default=10, help="cost centers to show")
    s.add_argument("--seed", type=int, default=7)
    s.add_argument("--k", type=int, default=12, help="fat-tree arity")
    s.add_argument("--batch", type=int, default=16, help="what-if batch size")
    s.add_argument(
        "--reps", type=int, default=8,
        help="single-SPF / what-if repetitions (the default exactly "
             "covers the tuner's deterministic explore phase)",
    )
    s.add_argument(
        "--storm", type=int, default=0, metavar="ROUTERS",
        help="run a seeded convergence storm of this many routers "
             "instead of the synthetic dispatch mix",
    )
    s.add_argument("--events", type=int, default=60, help="storm events")
    s.add_argument(
        "--ledger",
        help="sentinel baseline JSON (seed/flag/ratchet across runs)",
    )
    s.add_argument(
        "--wall-clock", action="store_true",
        help="measure real walls instead of the deterministic "
             "byte-identical counter clock",
    )
    s.add_argument(
        "--critical-path", action="store_true",
        help="arm the critical-path ledger and append the per-phase "
             "trigger→FIB waterfall section (meaningful with --storm)",
    )
    s.add_argument(
        "--slo", action="store_true",
        help="arm the SLO plane (error budgets + burn-rate sentinels) "
             "and append the objective table; with --storm a synthetic "
             "canary rides the storm loop as its own objective",
    )
    s.add_argument("--json", action="store_true", help="JSON report")
    s.set_defaults(fn=cmd_explain)
    s = sub.add_parser(
        "import-yang",
        help="parse YANG text module(s) and dump their schema subtrees",
    )
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_import_yang)
    s = sub.add_parser(
        "deviations",
        help="generate a not-supported deviations skeleton for a module",
    )
    s.add_argument("files", nargs="+", help="module file, then its imports")
    s.set_defaults(fn=cmd_deviations)
    s = sub.add_parser(
        "postmortem",
        help="pretty-print a flight-recorder postmortem bundle",
    )
    s.add_argument("bundle", help="postmortem-*.json bundle file")
    s.add_argument(
        "--json", action="store_true",
        help="re-emit the canonical sorted JSON instead of a summary",
    )
    s.add_argument(
        "--spans", type=int, default=12,
        help="how many trailing spans to show (default 12)",
    )
    s.add_argument(
        "--top", type=int, default=0, metavar="N",
        help="show the N heaviest spans in the ring instead of the "
             "trailing window (cost-center sorting, shared with "
             "trace/explain)",
    )
    s.set_defaults(fn=cmd_postmortem)
    s = sub.add_parser(
        "lint",
        help="holo-lint: JAX hot-path + lock-discipline static analysis",
    )
    s.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the holo_tpu package)",
    )
    s.add_argument(
        "--baseline",
        help="ratchet baseline JSON "
             "(default: holo_tpu/analysis/baseline.json)",
    )
    s.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings as the new baseline and exit 0",
    )
    s.add_argument("--json", action="store_true", help="JSON report")
    s.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    s.add_argument(
        "--check-suppressions", action="store_true",
        help="flag stale `# holo-lint: disable=` comments whose rule "
             "no longer fires on that line (exit 1)",
    )
    s.add_argument(
        "--no-cache", action="store_true",
        help="force a full scan (skip the incremental lint cache)",
    )
    s.add_argument(
        "--self-check", action="store_true",
        help="run cached + cold scans and fail loudly (exit 2) if the "
             "cache replay diverges from the full scan",
    )
    s.add_argument(
        "--no-audit", action="store_true",
        help="skip the HL3xx jaxpr kernel audit (the abstract CPU "
             "lowering of every registered jit seam)",
    )
    s.set_defaults(fn=cmd_lint)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

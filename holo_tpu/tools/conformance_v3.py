"""OSPFv3 reference-conformance: replay recorded topologies, compare RIBs.

Consumes /root/reference/holo-ospf/tests/conformance/ospfv3/topologies
(7 topologies, 44 routers: single/multi-area, stub areas, p2p and LAN
circuits) the same way tools/conformance.py does for OSPFv2:

1. Decode every recorded LSA's raw wire bytes with OUR v3 codec and
   union them into the converged per-area LSDB (newest copy per key).
2. Rebuild each router's local view — interfaces in config order so our
   interface ids line up with the recorded ``iface_key`` ids, FULL
   neighbors synthesized from the recorded hellos (router-id, link-local
   source, and the neighbor's interface id from the hello body).
3. Run OUR v3 SPF + route derivation and compare (prefix, metric,
   next-hop set) against the reference's expected ``local-rib``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from ipaddress import (
    IPv4Address,
    IPv6Address,
    IPv6Network,
    ip_interface,
)
from pathlib import Path

from holo_tpu.protocols.ospf.instance_v3 import (
    OspfV3Instance,
    V3IfConfig,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
from holo_tpu.protocols.ospf.packet_v3 import Lsa
from holo_tpu.utils.bytesbuf import Reader
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

V3_DIR = Path(
    "/root/reference/holo-ospf/tests/conformance/ospfv3/topologies"
)


def _loads_lenient(text: str):
    return json.JSONDecoder().raw_decode(text)[0]


def _area_id(v) -> IPv4Address:
    if isinstance(v, dict):
        return IPv4Address(v.get("Id", 0))
    return IPv4Address(v)


@dataclass
class ExpectedRoute:
    prefix: IPv6Network
    metric: int
    route_type: str
    nexthops: frozenset  # {(ifname, IPv6Address|None)}


@dataclass
class RouterData:
    name: str
    router_id: IPv4Address = None
    # config order: [(area_id, ifname, iface cfg dict, stub)]
    ifaces: list = field(default_factory=list)
    area_ids: list = field(default_factory=list)  # all configured areas
    # ifname -> (link_local, [global prefixes])
    addrs: dict = field(default_factory=dict)
    # iface slot id (1-based, config order) -> [(router_id, src_ll,
    #                                            nbr_iface_id)]
    hellos: dict = field(default_factory=dict)
    # area id -> [Lsa]
    rx_lsas: dict = field(default_factory=dict)
    expected: list = field(default_factory=list)
    # ifname -> OS ifindex (the reference's interface id, from the
    # recorded InterfaceUpd events)
    ifindex: dict = field(default_factory=dict)
    # Configured virtual links [(transit area id, peer router id)].
    vlinks: list = field(default_factory=list)
    # The complete recorded ietf-ospf:ospf state tree (full-tree diff).
    full_state: dict = field(default_factory=dict)


def load_router(rt_dir: Path) -> RouterData:
    rd = RouterData(name=rt_dir.name)
    cfg = _loads_lenient((rt_dir / "config.json").read_text())
    proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]
    ospf = proto["ietf-ospf:ospf"]
    rd.router_id = IPv4Address(ospf["explicit-router-id"])
    for area in ospf.get("areas", {}).get("area", []):
        aid = IPv4Address(area["area-id"])
        stub = "stub" in (area.get("area-type") or "")
        rd.area_ids.append(aid)
        summary = area.get("summary", True)
        for vl in (area.get("virtual-links") or {}).get(
            "virtual-link", []
        ):
            rd.vlinks.append(
                (IPv4Address(vl["transit-area-id"]),
                 IPv4Address(vl["router-id"]))
            )
        for iface in area.get("interfaces", {}).get("interface", []):
            rd.ifaces.append((aid, iface["name"], iface, (stub, summary)))

    ll, globs = {}, {}
    for line in (rt_dir / "events.jsonl").read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        ev = _loads_lenient(line)
        ibus = ev.get("Ibus")
        if ibus and "InterfaceUpd" in ibus:
            u = ibus["InterfaceUpd"]
            rd.ifindex[u["ifname"]] = u.get("ifindex", 0)
        if ibus and "InterfaceAddressAdd" in ibus:
            upd = ibus["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                continue
            if addr.version != 6:
                continue
            if addr.ip.is_link_local:
                ll.setdefault(upd["ifname"], addr.ip)
            else:
                globs.setdefault(upd["ifname"], []).append(addr.network)
        pkt_ev = (ev.get("Protocol") or {}).get("NetRxPacket")
        if pkt_ev:
            packet = (pkt_ev.get("packet") or {}).get("Ok") or {}
            iface_id = (pkt_ev.get("iface_key") or {}).get("Id")
            hello = packet.get("Hello")
            if hello is not None and iface_id is not None:
                rd.hellos.setdefault(iface_id, []).append(
                    (
                        IPv4Address(hello["hdr"]["router_id"]),
                        IPv6Address(pkt_ev["src"]),
                        hello.get("iface_id", 0),
                        hello.get("dr"),
                        hello.get("bdr"),
                    )
                )
            upd = packet.get("LsUpdate")
            if upd is not None:
                aid = IPv4Address(upd["hdr"]["area_id"])
                for lsa_obj in upd.get("lsas", []):
                    raw = bytes(lsa_obj["raw"])
                    try:
                        lsa = Lsa.decode(Reader(raw))
                    except Exception:  # noqa: BLE001 — foreign types
                        continue
                    rd.rx_lsas.setdefault(aid, []).append(lsa)
    for ifname in set(ll) | set(globs):
        rd.addrs[ifname] = (
            ll.get(ifname),
            globs.get(ifname, []),
        )

    state = _loads_lenient(
        (rt_dir / "output" / "northbound-state.json").read_text()
    )
    ospf_state = state["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]["ietf-ospf:ospf"]
    rd.full_state = ospf_state
    for route in ospf_state.get("local-rib", {}).get("route", []):
        nhs = set()
        for nh in route.get("next-hops", {}).get("next-hop", []):
            addr = nh.get("next-hop")
            nhs.add(
                (
                    nh.get("outgoing-interface"),
                    IPv6Address(addr) if addr else None,
                )
            )
        rd.expected.append(
            ExpectedRoute(
                prefix=IPv6Network(route["prefix"]),
                metric=route.get("metric", 0),
                route_type=route.get("route-type", ""),
                nexthops=frozenset(nhs),
            )
        )
    return rd


def load_topology(topo_dir: Path) -> dict[str, RouterData]:
    return {
        rt.name: load_router(rt)
        for rt in sorted(topo_dir.iterdir())
        if rt.is_dir() and (rt / "events.jsonl").exists()
    }


def link_lsa_map(routers: dict[str, RouterData]) -> dict:
    """(adv_rtr, originator's iface id) -> link-local address, from every
    Link-LSA recorded anywhere in the topology (RFC 5340 §4.4.3.8: the
    link-state id of a Link-LSA is the originating interface's id)."""
    from holo_tpu.protocols.ospf.packet_v3 import LsaLink

    out = {}
    for rd in routers.values():
        for lsas in rd.rx_lsas.values():
            for lsa in lsas:
                if isinstance(lsa.body, LsaLink):
                    out[(lsa.adv_rtr, int(lsa.lsid))] = (
                        lsa.body.link_local
                    )
    return out


def converged_lsdb(routers: dict[str, RouterData]) -> dict:
    out: dict = {}
    for rd in routers.values():
        for aid, lsas in rd.rx_lsas.items():
            area = out.setdefault(aid, {})
            for lsa in lsas:
                cur = area.get(lsa.key)
                if cur is None or lsa.compare(cur) > 0:
                    area[lsa.key] = lsa
    return out


class _NullIo(NetIo):
    def send(self, *a):
        pass


def compute_routes(rd: RouterData, lsdb_by_area: dict, ll_map: dict):
    loop = EventLoop(clock=VirtualClock())
    inst = OspfV3Instance(
        name=f"conf3-{rd.name}", router_id=rd.router_id, netio=_NullIo()
    )
    inst.vlink_config = list(rd.vlinks)
    loop.register(inst)

    # Bind every recorded hello to the right local interface by chaining
    # through the LSDB (the recorded iface_key ids are arena keys in a
    # different id space than the protocol's interface ids):
    #   hello src link-local --(Link-LSAs)--> (nbr router-id, nbr ifid)
    #   --(our router-LSA p2p/transit entry)--> our protocol iface id
    #   --(our Link-LSA)--> our link-local --> our interface name.
    ll_to_ref = {ll: key for key, ll in ll_map.items()}
    our_ll_by_refid = {
        ref_id: ll
        for (adv, ref_id), ll in ll_map.items()
        if adv == rd.router_id
    }
    ifname_by_ll = {
        ll: ifname
        for ifname, (ll, _g) in rd.addrs.items()
        if ll is not None
    }
    our_links = []
    for lsas in lsdb_by_area.values():
        for lsa in lsas.values():
            if (
                lsa.adv_rtr == rd.router_id
                and type(lsa.body).__name__ == "LsaRouterV3"
            ):
                our_links.extend(lsa.body.links)
    nbrs_by_ifname: dict = {}
    for key_hellos in rd.hellos.values():
        for router_id, src, nbr_iface_id, _dr, _bdr in key_hellos:
            ref = ll_to_ref.get(src)
            our_ifid = None
            if ref is not None:
                nbr_rid, nbr_ifid = ref
                for link in our_links:
                    if (
                        link.nbr_router_id == nbr_rid
                        and link.nbr_iface_id == nbr_ifid
                    ):
                        our_ifid = link.iface_id
                        break
                else:
                    # LAN: our transit entry names the DR, not each
                    # neighbor — find the network LSA whose attached
                    # list contains this neighbor, then the transit
                    # link referencing that (DR, DR-ifid) pair.
                    lan_keys = set()
                    for lsas in lsdb_by_area.values():
                        for lsa in lsas.values():
                            if (
                                type(lsa.body).__name__
                                == "LsaNetworkV3"
                                and nbr_rid in lsa.body.attached
                            ):
                                lan_keys.add(
                                    (lsa.adv_rtr, int(lsa.lsid))
                                )
                    for link in our_links:
                        if int(link.link_type) == 2 and (
                            link.nbr_router_id,
                            link.nbr_iface_id,
                        ) in lan_keys:
                            our_ifid = link.iface_id
                            break
            ifname = None
            if our_ifid is not None:
                ll = our_ll_by_refid.get(our_ifid)
                ifname = ifname_by_ll.get(ll)
            if ifname is not None:
                nbrs_by_ifname.setdefault(ifname, []).append(
                    (router_id, src, nbr_iface_id, _dr, _bdr)
                )

    for aid, ifname, icfg, (stub, summary) in rd.ifaces:
        link_local, prefixes = rd.addrs.get(ifname, (None, []))
        if link_local is None:
            link_local = IPv6Address("fe80::1")
        if_type = (
            IfType.POINT_TO_POINT
            if icfg.get("interface-type") == "point-to-point"
            else IfType.BROADCAST
        )
        iface = inst.add_interface(
            ifname,
            V3IfConfig(area_id=aid, if_type=if_type,
                       loopback=ifname == "lo" or ifname.startswith("lo:")),
            link_local,
            prefixes,
            stub=stub,
            summary=summary,
        )
        iface.up = True
        # Use the reference's interface id — the OS ifindex (recorded
        # InterfaceUpd), which is also what its Link-LSA lsids carry —
        # so self-originated network-vertex keys line up with the LSDB.
        ref = ll_to_ref.get(link_local)
        if ref is not None and ref[0] == rd.router_id:
            iface.iface_id = ref[1]
        elif iface.config.loopback and ifname in rd.ifindex:
            # Loopbacks have no Link-LSA to chain through; their id is
            # the OS ifindex and keys nothing in the protocol.
            iface.iface_id = rd.ifindex[ifname]
        for router_id, src, nbr_iface_id, h_dr, h_bdr in nbrs_by_ifname.get(
            ifname, []
        ):
            nbr = iface.neighbors.get(router_id)
            if nbr is None:
                nbr = Neighbor(
                    router_id=router_id, src=src, state=NsmState.FULL
                )
                iface.neighbors[router_id] = nbr
            nbr.iface_id = nbr_iface_id
            # Converged DR/BDR from the last recorded hello claims (the
            # reference ran the real election during recording).
            if h_dr is not None and int(IPv4Address(h_dr)):
                iface.dr = IPv4Address(h_dr)
            if h_bdr is not None and int(IPv4Address(h_bdr)):
                iface.bdr = IPv4Address(h_bdr)
        # LAN DR from the converged network LSAs: the LSA whose
        # (originator, iface id) matches one of this LAN's neighbors —
        # or our own interface — names the DR.
        if if_type == IfType.BROADCAST:
            for lsas in lsdb_by_area.values():
                for lsa in lsas.values():
                    if type(lsa.body).__name__ != "LsaNetworkV3":
                        continue
                    adv, lsid = lsa.adv_rtr, int(lsa.lsid)
                    if adv == rd.router_id and lsid == iface.iface_id:
                        iface.dr = adv
                    else:
                        nbr = iface.neighbors.get(adv)
                        if nbr is not None and nbr.iface_id == lsid:
                            iface.dr = adv

    # Configured areas without interfaces (a virtual-link-attached
    # backbone, reference topo3) still hold an LSDB and join route calc.
    from holo_tpu.protocols.ospf.instance_v3 import V3Area

    for aid in rd.area_ids:
        if aid not in inst.areas:
            inst.areas[aid] = V3Area(aid)
    # Link-scope LSAs (type 8) live in the owning circuit's LSDB; map
    # each one through its originator's link-local to our interface.
    ifname_of_ll = {
        ll: ifname
        for ifname, (ll, _g) in rd.addrs.items()
        if ll is not None
    }
    # Seed the inter-area lsid allocator from the recorded SELF LSAs so
    # our re-origination lands on the recorded link-state ids instead of
    # duplicating them under fresh ones.
    for aid, lsas in lsdb_by_area.items():
        for lsa in lsas.values():
            if lsa.adv_rtr != rd.router_id:
                continue
            if int(lsa.type) == 0x2003:
                inst._inter_ids[(aid, lsa.body.prefix)] = lsa.lsid
            elif int(lsa.type) == 0x2004:
                inst._inter_ids[
                    (aid, ("asbr", lsa.body.dest_router_id))
                ] = lsa.lsid
    for aid, lsas in lsdb_by_area.items():
        if aid not in inst.areas:
            continue
        for lsa in lsas.values():
            if int(lsa.type) == 8:
                ll = ll_map.get((lsa.adv_rtr, int(lsa.lsid)))
                target = None
                if ll is not None:
                    name = ifname_of_ll.get(ll)
                    if name is not None:
                        target = inst.interfaces.get(name)
                    else:
                        for iface in inst.interfaces.values():
                            if any(
                                n.src == ll
                                for n in iface.neighbors.values()
                            ):
                                target = iface
                                break
                if target is not None:
                    target.link_lsdb.install(lsa, 0.0)
                continue  # never into the area database
            inst.areas[aid].lsdb.install(lsa, 0.0)
    inst.run_spf()
    return inst


def compare_router(rd: RouterData, routes: dict) -> list[str]:
    problems = []
    expected_by_prefix = {e.prefix: e for e in rd.expected}
    for prefix, exp in expected_by_prefix.items():
        got = routes.get(prefix)
        if got is None:
            problems.append(f"missing route {prefix}")
            continue
        if got.dist != exp.metric:
            problems.append(
                f"{prefix}: metric {got.dist} != expected {exp.metric}"
            )
        ours = frozenset(
            (nh[0], nh[1]) for nh in got.nexthops
        )
        want = exp.nexthops
        # Local (metric-0) routes have no next hops on either side.
        if want == frozenset() and not got.nexthops:
            continue
        if ours != want:
            problems.append(
                f"{prefix}: nexthops {sorted(map(str, ours))} != "
                f"expected {sorted(map(str, want))}"
            )
    for prefix in routes.keys() - expected_by_prefix.keys():
        problems.append(f"unexpected extra route {prefix}")
    return problems


def compare_state(rd: RouterData, inst) -> list[str]:
    """Full recorded ietf-ospf tree vs our YANG-modeled render — the
    same both-sided contract the v2/IS-IS stepwise harnesses enforce."""
    from holo_tpu.protocols.ospf.nb_state_v3 import instance_state
    from holo_tpu.tools.treediff import tree_diff

    return tree_diff(rd.full_state, instance_state(inst), "ospf")


def router_lsdb(rd: RouterData, union: dict) -> dict:
    """This router's LSDB view: foreign LSAs newest-per-key from ITS OWN
    recorded stream (lsid reuse across re-originations means another
    router's stream can hold a different final incarnation), self LSAs
    from the topology union (a router never receives its own floods —
    other routers' streams carry what we last originated)."""
    out: dict = {}
    for aid, lsas in rd.rx_lsas.items():
        area = out.setdefault(aid, {})
        for lsa in lsas:
            cur = area.get(lsa.key)
            if cur is None or lsa.compare(cur) > 0:
                area[lsa.key] = lsa
    for aid, lsas in union.items():
        area = out.setdefault(aid, {})
        for key, lsa in lsas.items():
            if lsa.adv_rtr != rd.router_id:
                continue
            cur = area.get(key)
            # Prefer the union only on a strictly higher seqno: lsid
            # reuse can produce same-seqno different-content collisions
            # across streams, and our own echo is authoritative then.
            if cur is None or lsa.seq_no > cur.seq_no:
                area[key] = lsa
    # A winning MaxAge incarnation is a completed flush: the reference
    # removed it from the database once acked (§14).
    for area in out.values():
        for key in [k for k, l in area.items() if l.is_maxage]:
            del area[key]
    return out


def run_topology(topo_dir: Path) -> dict[str, list[str]]:
    routers = load_topology(topo_dir)
    union = converged_lsdb(routers)
    ll_map = link_lsa_map(routers)
    results = {}
    for name, rd in sorted(routers.items()):
        try:
            inst = compute_routes(rd, router_lsdb(rd, union), ll_map)
            results[name] = compare_router(rd, inst.routes)
            results[name] += compare_state(rd, inst)
        except Exception as e:  # noqa: BLE001 — sweep must not die
            results[name] = [f"exception: {type(e).__name__}: {e}"]
    return results


def run_all() -> dict[str, list[str]]:
    results = {}
    for topo_dir in sorted(V3_DIR.iterdir()):
        if not topo_dir.is_dir():
            continue
        for rt, problems in run_topology(topo_dir).items():
            results[f"{topo_dir.name}/{rt}"] = problems
    return results


if __name__ == "__main__":
    import sys

    res = run_all()
    ok = [k for k, v in res.items() if not v]
    bad = {k: v for k, v in res.items() if v}
    for k, v in sorted(bad.items()):
        if "-v" in sys.argv:
            print(f"FAIL {k}: {'; '.join(v[:4])[:400]}")
    print(f"pass {len(ok)} fail {len(bad)} / {len(res)}")

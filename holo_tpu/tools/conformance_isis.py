"""IS-IS reference-conformance harness: replay recorded topologies.

Consumes the reference's IS-IS conformance corpus
(/root/reference/holo-isis/tests/conformance/topologies — SURVEY.md §4):
per-router recorded events whose NetRxPdu entries carry raw PDU wire
bytes, plus expected operational state.  For each topology:

1. Decode every recorded PDU with OUR codecs (LSPs in both narrow
   TLV 2/128 and wide TLV 22/135 form, plus RFC 5308 IPv6 TLVs).
2. Rebuild each router's per-level LSDB: the union of the LSPs it
   received and its self-originated LSPs as seen in its neighbors'
   streams (newest copy wins) — which scopes L1 databases to the
   router's own area exactly as real flooding does.
3. Synthesize adjacencies from the recorded hellos (p2p three-way and
   LAN DIS lan-ids, with IPv4 and link-local IPv6 next-hop addresses),
   run OUR SPF + route derivation per level, merge L1-over-L2, and
   compare (prefix, metric, level, next-hop set) against the
   reference's expected ``local-rib`` for BOTH address families.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from ipaddress import ip_address, ip_interface, ip_network
from pathlib import Path

from holo_tpu.protocols.isis.instance import (
    Adjacency,
    AdjacencyState,
    IsisIfConfig,
    IsisInstance,
    LspEntry,
)
from holo_tpu.protocols.isis.packet import HelloLan, HelloP2p, Lsp, PduType, decode_pdu
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

REFERENCE_CONFORMANCE_ISIS = Path(
    "/root/reference/holo-isis/tests/conformance/topologies"
)


@dataclass
class ExpectedRoute:
    prefix: object  # IPv4Network | IPv6Network
    metric: int
    level: int
    nexthops: frozenset  # {(ifname, addr|None)}


@dataclass
class IsisRouterData:
    name: str
    sysid: bytes = b""
    levels: tuple = (2,)
    iface_types: dict = field(default_factory=dict)  # ifname -> "p2p"|"broadcast"
    addrs: dict = field(default_factory=dict)  # ifname -> first v4 ip_interface
    ifindexes: dict = field(default_factory=dict)  # ifindex -> ifname
    # (ifname, level) -> {sysid: last hello pdu seen}
    hellos: dict = field(default_factory=dict)
    rx_lsps: dict = field(default_factory=dict)  # level -> [Lsp]
    expected: list = field(default_factory=list)
    afs: set = field(default_factory=lambda: {"ipv4"})
    mt_enabled: bool = False
    # The complete recorded ietf-isis:isis state tree (full-tree diff).
    full_state: dict = field(default_factory=dict)
    # configured interface names in config order (for state rendering)
    if_order: list = field(default_factory=list)


def _parse_sysid(s: str) -> bytes:
    return bytes.fromhex(s.replace(".", ""))


def load_router(rt_dir: Path) -> IsisRouterData:
    rd = IsisRouterData(name=rt_dir.name)
    cfg = json.loads((rt_dir / "config.json").read_text())
    proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]["ietf-isis:isis"]
    rd.sysid = _parse_sysid(proto["system-id"])
    afl = (proto.get("address-families") or {}).get(
        "address-family-list", []
    )
    # Absent config = the instance default (both families enabled).
    rd.afs = (
        {
            af["address-family"]
            for af in afl
            if af.get("enabled", True)
        }
        if afl
        else {"ipv4", "ipv6"}
    )
    topos = (proto.get("topologies") or {}).get("topology", [])
    rd.mt_enabled = any(
        t.get("name") == "ipv6-unicast" for t in topos
    )
    lt = proto.get("level-type", "level-all")
    rd.levels = {"level-1": (1,), "level-2": (2,)}.get(lt, (1, 2))
    for iface in proto.get("interfaces", {}).get("interface", []):
        rd.if_order.append(iface["name"])
        rd.iface_types[iface["name"]] = (
            "p2p"
            if iface.get("interface-type") == "point-to-point"
            else "broadcast"
        )

    for line in (rt_dir / "events.jsonl").read_text().splitlines():
        if not line.strip():
            continue
        ev = json.loads(line)
        ibus = ev.get("Ibus")
        if ibus and "InterfaceUpd" in ibus:
            upd = ibus["InterfaceUpd"]
            rd.ifindexes[upd["ifindex"]] = upd["ifname"]
        if ibus and "InterfaceAddressAdd" in ibus:
            upd = ibus["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                continue
            if addr.version == 4 and upd["ifname"] not in rd.addrs:
                rd.addrs[upd["ifname"]] = addr
        pdu_ev = (ev.get("Protocol") or {}).get("NetRxPdu")
        if pdu_ev:
            try:
                pdu_type, pdu = decode_pdu(bytes(pdu_ev["bytes"]))
            except Exception:
                continue  # deliberately-malformed PDUs in error corpora
            if isinstance(pdu, Lsp):
                rd.rx_lsps.setdefault(pdu.level, []).append(pdu)
                continue
            if not isinstance(pdu, (HelloP2p, HelloLan)):
                continue
            # The recorded iface_key is the reference's internal arena id,
            # not the ifindex — attribute the hello to the interface whose
            # subnet contains the sender's advertised address instead
            # (each link is its own subnet, so this is unambiguous, and
            # it also pins parallel p2p links to the right interface).
            ifname = None
            for a in pdu.tlvs.get("ip_addresses") or []:
                for name, our in rd.addrs.items():
                    if a != our.ip and a in our.network:
                        ifname = name
                        break
                if ifname:
                    break
            if ifname is None:
                continue
            if isinstance(pdu, HelloP2p):
                for level in (1, 2):
                    if pdu.circuit_type & level:
                        rd.hellos.setdefault((ifname, level), {})[
                            pdu.sysid
                        ] = pdu
            else:
                rd.hellos.setdefault((ifname, pdu.level), {})[pdu.sysid] = pdu

    state = json.loads(
        (rt_dir / "output" / "northbound-state.json").read_text()
    )
    isis_state = state["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]["ietf-isis:isis"]
    rd.full_state = isis_state
    for route in isis_state.get("local-rib", {}).get("route", []):
        nhs = set()
        for nh in route.get("next-hops", {}).get("next-hop", []):
            addr = nh.get("next-hop")
            nhs.add(
                (nh.get("outgoing-interface"),
                 ip_address(addr) if addr else None)
            )
        rd.expected.append(
            ExpectedRoute(
                prefix=ip_network(route["prefix"]),
                metric=route.get("metric", 0),
                level=route.get("level", 2),
                nexthops=frozenset(nhs),
            )
        )
    return rd


def load_topology(topo_dir: Path) -> dict[str, IsisRouterData]:
    return {
        rt.name: load_router(rt)
        for rt in sorted(topo_dir.iterdir())
        if rt.is_dir() and (rt / "events.jsonl").exists()
    }


def router_lsdb(rd: IsisRouterData, routers: dict, level: int) -> dict:
    """This router's converged LSDB at ``level``: its own received LSPs
    plus its self-originated ones recovered from every neighbor's stream
    (ISO 10589 newest-wins).  L1 area scoping falls out naturally: a
    router only ever received LSPs flooded within its own area."""
    out: dict = {}

    def add(lsp: Lsp):
        cur = out.get(lsp.lsp_id)
        if cur is None or lsp.compare(cur.lifetime, cur.seqno, cur.cksum) > 0:
            out[lsp.lsp_id] = lsp

    for lsp in rd.rx_lsps.get(level, []):
        add(lsp)
    for other in routers.values():
        for lsp in other.rx_lsps.get(level, []):
            if lsp.lsp_id.sysid == rd.sysid:
                add(lsp)
    return out


class _NullIo(NetIo):
    def send(self, *a):
        pass


def compute_level_routes(rd: IsisRouterData, routers: dict, level: int,
                         backend=None) -> dict:
    """Run OUR pipeline for one router at one level; {prefix: (m, nhs)}."""
    loop = EventLoop(clock=VirtualClock())
    inst = IsisInstance(
        name=f"conf-{rd.name}-l{level}",
        sysid=rd.sysid,
        level=level,
        netio=_NullIo(),
        spf_backend=backend,
        mt_enabled=rd.mt_enabled,
    )
    inst.afs = set(rd.afs)
    inst.protocols = ([0xCC] if "ipv4" in rd.afs else []) + (
        [0x8E] if "ipv6" in rd.afs else []
    )
    loop.register(inst)

    for (ifname, hlevel), by_sysid in rd.hellos.items():
        if hlevel != level or not by_sysid:
            continue
        # The recorded hello type is authoritative for the circuit type
        # (config may omit interface-type; LAN is the YANG default).
        is_lan = any(isinstance(h, HelloLan) for h in by_sysid.values())
        addr = rd.addrs.get(ifname) or ip_interface("0.0.0.0/32")
        if ifname not in inst.interfaces:
            inst.add_interface(
                ifname,
                IsisIfConfig(
                    circuit_type="broadcast" if is_lan else "p2p"
                ),
                addr.ip,
                addr.network,
            )
        iface = inst.interfaces[ifname]
        for sysid, hello in by_sysid.items():
            if isinstance(hello, HelloLan) != is_lan:
                continue  # stray mismatched-type hello
            adj = Adjacency(sysid=sysid, state=AdjacencyState.UP)
            for a in hello.tlvs.get("ip_addresses") or []:
                adj.addr = a
                break
            for a6 in hello.tlvs.get("ipv6_addresses") or []:
                if a6.is_link_local:
                    adj.addr6 = a6
                    break
            # State-plane attributes carried by the recorded hello.
            adj.usage_ctype = getattr(hello, "circuit_type", level)
            adj.priority = getattr(hello, "priority", 64)
            adj.area_addresses = tuple(
                hello.tlvs.get("area_addresses") or ()
            )
            adj.protocols = tuple(
                hello.tlvs.get("protocols_supported") or ()
            )
            adj.addrs4 = tuple(hello.tlvs.get("ip_addresses") or ())
            adj.addrs6 = tuple(hello.tlvs.get("ipv6_addresses") or ())
            mt = tuple(
                m[0] if isinstance(m, (tuple, list)) else m
                for m in (hello.tlvs.get("mt_ids") or ())
            )
            if mt:
                adj.topologies = mt
            if iface.is_lan:
                adj.lan_id = hello.lan_id
                iface.adjs[sysid] = adj
                # Converged consensus: every member advertises the DIS.
                iface.dis_lan_id = hello.lan_id
            else:
                iface.adj = adj

    # Configured interfaces without adjacencies (loopbacks) still join
    # the instance so they render and advertise their prefixes.
    for ifname in rd.if_order:
        if ifname in inst.interfaces:
            continue
        addr = rd.addrs.get(ifname)
        if addr is None:
            continue
        inst.add_interface(
            ifname,
            IsisIfConfig(
                circuit_type=(
                    "p2p"
                    if rd.iface_types.get(ifname) == "p2p"
                    else "broadcast"
                ),
                passive=ifname == "lo" or ifname.startswith("lo:"),
            ),
            addr.ip,
            addr.network,
        )
    now = loop.clock.now()
    for lsp in router_lsdb(rd, routers, level).values():
        if lsp.lifetime == 0:
            continue
        inst.lsdb[lsp.lsp_id] = LspEntry(lsp, now)
        # RFC 5301: learn dynamic hostnames from the seeded LSPs (the
        # live rx path does this during flooding).
        name = lsp.tlvs.get("hostname")
        if name and lsp.lsp_id.pseudonode == 0:
            inst.hostnames[lsp.lsp_id.sysid] = name
    inst.run_spf()
    return inst


def compute_routes(rd: IsisRouterData, routers: dict, backend_factory=None):
    """Merged multi-level routes: {prefix: (metric, nhs, level)} with the
    IS-IS preference of L1 over L2 for the same prefix.  Returns
    (merged routes, per-level instances)."""
    merged: dict = {}
    insts = []
    for level in sorted(rd.levels, reverse=True):  # L2 first, L1 overrides
        backend = backend_factory() if backend_factory else None
        inst = compute_level_routes(rd, routers, level, backend)
        insts.append(inst)
        for prefix, (metric, nhs) in inst.routes.items():
            merged[prefix] = (metric, nhs, level)
    insts.sort(key=lambda i: i.level)
    return merged, insts


def compare_router(rd: IsisRouterData, routes: dict) -> list[str]:
    problems = []
    expected_by_prefix = {e.prefix: e for e in rd.expected}
    for prefix, exp in expected_by_prefix.items():
        got = routes.get(prefix)
        if got is None:
            problems.append(f"missing route {prefix}")
            continue
        metric, nhs, level = got
        if metric != exp.metric:
            problems.append(
                f"{prefix}: metric {metric} != expected {exp.metric}"
            )
        if level != exp.level:
            problems.append(
                f"{prefix}: level {level} != expected {exp.level}"
            )
        if nhs != exp.nexthops:
            problems.append(
                f"{prefix}: nexthops {sorted(map(str, nhs))} != "
                f"expected {sorted(map(str, exp.nexthops))}"
            )
    for prefix in routes.keys() - expected_by_prefix.keys():
        problems.append(f"unexpected extra route {prefix}")
    return problems


def run_topology(topo_dir: Path, backend_factory=None) -> dict[str, list[str]]:
    """backend_factory: () -> SpfBackend (None = scalar default); passing
    TpuSpfBackend proves the TENSOR engine reproduces the reference RIBs."""
    routers = load_topology(topo_dir)
    results = {}
    for name, rd in sorted(routers.items()):
        routes, insts = compute_routes(rd, routers, backend_factory)
        results[name] = compare_router(rd, routes)
        results[name] += compare_state(rd, routes, insts)
    return results


def compare_state(rd: IsisRouterData, routes, insts) -> list[str]:
    """Full recorded ietf-isis tree vs our YANG-modeled render — the
    same complete-tree contract the stepwise harness enforces."""
    from types import SimpleNamespace

    from holo_tpu.protocols.isis.nb_state import instance_state
    from holo_tpu.tools.treediff import tree_diff

    # Multi-level routers render the MERGED route table (the node's
    # view); a namespace with .routes is all the renderer needs.
    node = None
    if len(insts) > 1:
        node = SimpleNamespace(
            routes={p: (m, nhs) for p, (m, nhs, _l) in routes.items()}
        )
    return tree_diff(
        rd.full_state,
        instance_state(insts, node=node, ifnames=rd.if_order or None),
        "isis",
    )

"""Deep YANG-JSON tree comparison shared by the stepwise harnesses.

Mirrors the reference's full-plane state assertion
(holo-protocol/src/test/stub/northbound.rs): every leaf in the expected
tree must match, and every leaf we emit must be expected — both-sided.
Lists are paired by their YANG keys when known (falling back to a
whole-entry canonical sort), so a single mismatched entry produces one
focused diff instead of a cascade.
"""

from __future__ import annotations

import json
import re

# YANG list-entry keys by list name (union across the protocols' trees;
# name collisions resolve to compatible keys).
LIST_KEYS = {
    # ietf-ospf
    "area": ("area-id",),
    "interface": ("name",),
    "neighbor": (
        "neighbor-router-id", "address", "remote-address",
        "neighbor-id", "mt-id",
    ),
    "route": ("prefix",),
    "area-scope-lsa-type": ("lsa-type",),
    "link-scope-lsa-type": ("lsa-type",),
    "as-scope-lsa-type": ("lsa-type",),
    "area-scope-lsa": ("lsa-id", "adv-router"),
    "link-scope-lsa": ("lsa-id", "adv-router"),
    "as-scope-lsa": ("lsa-id", "adv-router"),
    "hostname": ("router-id", "system-id"),
    "extended-prefix-tlv": ("prefix",),
    # ietf-mpls-ldp
    "address": ("address", "advertisement-type", "peer"),
    "fec-label": ("fec",),
    "peer": ("lsr-id",),
    "hello-adjacency": ("adjacent-address",),
    "target": ("adjacent-address",),
    # ietf-isis
    "levels": ("level",),
    "level": ("level",),
    "holo-isis:level": ("level",),
    "lsp": ("lsp-id",),
    "adjacency": ("neighbor-sysid",),
    "instance": ("id",),
    "topology": ("mt-id",),
    "prefixes": ("ip-prefix", "prefix-len", "mt-id"),
    "node-msds": ("msd-type",),
    "global-block": ("label-value",),
    "local-block": ("label-value",),
}


def tree_diff(exp, got, path: str, list_keys: dict | None = None) -> list[str]:
    keys_map = LIST_KEYS if list_keys is None else list_keys
    problems: list[str] = []
    if isinstance(exp, dict) and isinstance(got, dict):
        for k in exp:
            if k not in got:
                problems.append(f"{path}/{k}: missing")
            else:
                problems += tree_diff(exp[k], got[k], f"{path}/{k}", keys_map)
        for k in got:
            if k not in exp:
                problems.append(f"{path}/{k}: unexpected")
        return problems
    if isinstance(exp, list) and isinstance(got, list):
        name = path.rsplit("/", 1)[-1].split("[", 1)[0]
        keys = keys_map.get(name)

        def keyfn(entry):
            if keys and isinstance(entry, dict):
                return json.dumps(
                    [entry.get(k) for k in keys], sort_keys=True
                )
            return json.dumps(entry, sort_keys=True)

        exp_s = sorted(exp, key=keyfn)
        got_s = sorted(got, key=keyfn)
        if len(exp_s) != len(got_s):
            problems.append(f"{path}: list length {len(got_s)} != {len(exp_s)}")
        for i, (e, g) in enumerate(zip(exp_s, got_s)):
            problems += tree_diff(e, g, f"{path}[{i}]", keys_map)
        return problems
    if exp != got and not _identity_eq(exp, got):
        problems.append(f"{path}: {got!r} != {exp!r}")
    return problems


_IDENTITY_PREFIX = re.compile(r"^[a-z][a-z0-9.-]*:(?=[a-z])")


def _identity_eq(a, b) -> bool:
    """YANG identityref leaves may or may not carry the module prefix
    depending on the recording's libyang vintage ('ietf-ospf:v2-e-bit'
    vs 'v2-e-bit'): equal when stripping the prefix from the ONE side
    that has it yields the other.  Requiring the other side to be
    colon-free keeps IPv6 literals (both sides have colons) and
    cross-module identities (both sides prefixed) unequal."""
    if not (isinstance(a, str) and isinstance(b, str)):
        return False
    if ":" not in a and _IDENTITY_PREFIX.match(b):
        return _IDENTITY_PREFIX.sub("", b) == a
    if ":" not in b and _IDENTITY_PREFIX.match(a):
        return _IDENTITY_PREFIX.sub("", a) == b
    return False

"""RIP stepwise conformance: replay the reference's recorded cases.

Covers BOTH corpora — holo-rip/tests/conformance/{ripv2,ripng} (38 case
dirs each plus 4 topology snapshots per family).  Each case brings one
recorded router up by replaying its events.jsonl through our live
RipInstance (real codec/route-table/update machinery), then applies the
numbered step inputs and asserts:

- the protocol plane (UdpTxPdu messages, unordered subset match);
- the ibus plane (RouteIpAdd/RouteIpDel from route-table diffs);
- the northbound-state plane (interfaces, neighbors, per-route state:
  metric/next-hop/interface/route-type/deleted/changed flags).

Timers are recorded events (InitialUpdate, UpdateInterval, TriggeredUpd,
TriggeredUpdTimeout, RouteTimeout, RouteGcTimeout, NbrTimeout), so the
replay is fully deterministic under the virtual clock.
"""

from __future__ import annotations

import json
import re
from ipaddress import ip_address, ip_interface, ip_network
from pathlib import Path

from holo_tpu.protocols.rip import (
    INFINITY_METRIC,
    RipCommand,
    RipIfConfig,
    RipInstance,
    RipngVersion,
    RipVersion,
)
from holo_tpu.tools.refjson import Unsupported, subset_match
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import EventLoop, VirtualClock

RIP_DIR = Path("/root/reference/holo-rip/tests/conformance")


def case_map(family: str) -> dict[str, tuple[str, str]]:
    out = {}
    text = (RIP_DIR / family / "mod.rs").read_text()
    for m in re.finditer(
        r'run_test(?:_topology)?::<[^(]*\(\s*"([^"]+)",\s*"([^"]+)",\s*"([^"]+)"',
        text,
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


class _TxCapture(NetIo):
    def __init__(self):
        self.log = []

    def send(self, ifname, src, dst, data):
        self.log.append((ifname, dst, data))


def _pdu_to_json(version, data: bytes) -> dict:
    """Our wire bytes -> the reference's serde shape."""
    command, entries, _seqno = version.decode(data)
    rtes = []
    for prefix, tag, metric, nh in entries:
        if prefix is None:
            if version is RipVersion:
                rtes.append({"Zero": {"metric": metric}})
            else:
                rtes.append(
                    {"Ipv6": {"prefix": "::/0", "tag": 0, "metric": metric}}
                )
        elif version is RipVersion:
            rtes.append(
                {
                    "Ipv4": {
                        "tag": tag,
                        "prefix": str(prefix),
                        "nexthop": str(nh) if nh is not None else None,
                        "metric": metric,
                    }
                }
            )
        else:
            rtes.append(
                {
                    "Ipv6": {
                        "tag": tag,
                        "prefix": str(prefix),
                        "metric": metric,
                    }
                }
            )
    return {
        "command": "Request" if command == RipCommand.REQUEST else "Response",
        "version": 2 if version is RipVersion else 1,
        "rtes": rtes,
    }


def _pdu_from_json(version, j: dict) -> bytes:
    """Reference serde JSON -> our wire bytes."""
    from holo_tpu.protocols.rip import RipngPacket, RipPacket, Rte

    command = (
        RipCommand.REQUEST if j["command"] == "Request" else RipCommand.RESPONSE
    )
    if version is RipVersion:
        from ipaddress import IPv4Address

        rtes = []
        for e in j.get("rtes", []):
            if "Zero" in e:
                rtes.append(
                    Rte(None, IPv4Address(0), e["Zero"].get("metric", 16))
                )
            elif "Ipv4" in e:
                v = e["Ipv4"]
                rtes.append(
                    Rte(
                        ip_network(v["prefix"]),
                        IPv4Address(v["nexthop"] or "0.0.0.0"),
                        v.get("metric", 1),
                        v.get("tag", 0),
                    )
                )
            else:
                raise Unsupported(f"rte {next(iter(e))}")
        return RipPacket(command, rtes).encode()
    rtes = []
    for e in j.get("rtes", []):
        if "Ipv6" in e:
            v = e["Ipv6"]
            rtes.append(
                (ip_network(v["prefix"]), v.get("tag", 0), v.get("metric", 1))
            )
        elif "Zero" in e:
            rtes.append((ip_network("::/0"), 0, e["Zero"].get("metric", 16)))
        elif "Nexthop" in e:
            # RFC 2080 §2.1.1 next-hop RTE (metric 0xFF).
            nh = e["Nexthop"].get("addr") or "::"
            rtes.append((ip_network(f"{nh}/128"), 0, 0xFF))
        else:
            raise Unsupported(f"rte {next(iter(e))}")
    return RipngPacket(command, rtes).encode()


class CaseRun:
    def __init__(self, family: str, topo_dir: Path, rt: str):
        self.family = family
        self.version = RipVersion if family == "ripv2" else RipngVersion
        self.loop = EventLoop(clock=VirtualClock())
        self.tx = _TxCapture()
        self.rt_dir = topo_dir / rt
        cfg = json.loads((self.rt_dir / "config.json").read_text())
        proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-rip:rip"]
        self.if_conf: dict[str, dict] = {}
        for iface in (proto.get("interfaces") or {}).get("interface", []):
            self.if_conf[iface["interface"]] = iface
        self.inst = RipInstance(
            "test", self.tx, version=self.version, route_cb=self._routes_changed
        )
        self.loop.register(self.inst)
        # Replay determinism: the instance's own timers never fire (the
        # recorded events drive updates), so cancel the auto-started ones.
        self.inst._update_timer.cancel()
        self.inst._age_timer.cancel()
        self.prev_routes: dict = {}
        self.ibus_log: list = []
        self.live = False  # True once bring-up finished (step phase)
        self.ifindex: dict[str, int] = {}
        self.addrs: dict[str, list] = {}
        self.oper_up: set = set()

    # -- ibus plane

    def _routes_changed(self, routes: dict) -> None:
        # Connected routes stay out of the RIB feed: the kernel already
        # owns them as DIRECT, and the reference only ever installs
        # learned routes (recorded ibus planes carry distance-120 adds
        # with real nexthops, never the interface's own prefix).
        routes = {
            p: r for p, r in routes.items()
            if r.route_type != "connected"
        }
        for prefix, route in routes.items():
            cur = (route.metric, route.nexthop, route.ifname)
            if self.prev_routes.get(prefix) != cur:
                self.ibus_log.append(("add", prefix, route))
        for prefix in self.prev_routes.keys() - routes.keys():
            self.ibus_log.append(("del", prefix, None))
        self.prev_routes = {
            p: (r.metric, r.nexthop, r.ifname) for p, r in routes.items()
        }

    # -- interface lifecycle

    def _want_af(self, addr) -> bool:
        return (addr.version == 4) == (self.family == "ripv2")

    def _ensure_iface(self, ifname: str) -> None:
        if ifname not in self.if_conf or ifname not in self.oper_up:
            return
        if ifname in self.inst.interfaces:
            return
        addrs = [
            a for a in self.addrs.get(ifname, []) if self._want_af(a.ip)
        ]
        if not addrs and not ifname.startswith("lo"):
            return
        use = None
        if self.family == "ripng":
            # RIPng runs over link-local sources; the advertised prefix
            # is the global one.
            g = [a for a in addrs if not a.ip.is_link_local]
            ll = [a for a in addrs if a.ip.is_link_local]
            if g:
                use = (ll[0].ip if ll else g[0].ip, g[0].network)
            elif ll:
                use = (ll[0].ip, None)
        elif addrs:
            use = (addrs[0].ip, addrs[0].network)
        if use is None:
            return
        icfg = self.if_conf[ifname]
        self.inst.add_interface(
            ifname,
            RipIfConfig(
                cost=(icfg.get("metric") or {}).get("value", 1),
                split_horizon=icfg.get("split-horizon", "simple"),
                passive=icfg.get("passive", False)
                or ifname.startswith("lo"),
            ),
            use[0],
            use[1],
        )
        for a in addrs:
            if self.family == "ripng" and a.ip.is_link_local:
                continue
            if use[1] is not None and a.network == use[1]:
                continue  # primary already installed by add_interface
            self.inst.add_connected(ifname, a.network)
        self.loop.run_until_idle()

    def apply_ibus(self, ev: dict) -> None:
        if "InterfaceUpd" in ev:
            upd = ev["InterfaceUpd"]
            ifname = upd["ifname"]
            if upd.get("ifindex"):
                self.ifindex[ifname] = upd["ifindex"]
            flags_s = upd.get("flags")
            operative = (
                "OPERATIVE" in flags_s if flags_s is not None else True
            )
            if operative:
                self.oper_up.add(ifname)
                self._ensure_iface(ifname)
            else:
                self.oper_up.discard(ifname)
                self.inst.remove_interface(ifname)
                self.loop.run_until_idle()
        elif "InterfaceAddressAdd" in ev:
            upd = ev["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.setdefault(upd["ifname"], [])
            if addr not in lst:
                lst.append(addr)
            self._ensure_iface(upd["ifname"])
            ifname = upd["ifname"]
            if (
                ifname in self.inst.interfaces
                and self._want_af(addr.ip)
                and not (
                    self.family == "ripng" and addr.ip.is_link_local
                )
            ):
                self.inst.add_connected(ifname, addr.network)
                self.loop.run_until_idle()
        elif "InterfaceAddressDel" in ev:
            upd = ev["InterfaceAddressDel"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.get(upd["ifname"]) or []
            if addr in lst:
                lst.remove(addr)
            if not self._want_af(addr.ip):
                return
            ifname = upd["ifname"]
            entry = self.inst.interfaces.get(ifname)
            if entry is None:
                return
            self.inst.del_connected(addr.network)
            usable = [a for a in lst if self._want_af(a.ip)]
            if self.family == "ripng":
                # RIPng needs a link-local source; loopbacks (which
                # never transmit) stay eligible with any address.
                eligible = any(a.ip.is_link_local for a in usable) or (
                    ifname.startswith("lo") and bool(usable)
                )
            else:
                eligible = bool(usable)
            if not eligible:
                # No usable source address left: the circuit leaves RIP.
                self.inst.remove_interface(ifname)
            self.loop.run_until_idle()
        elif "RouteRedistributeAdd" in ev:
            upd = ev["RouteRedistributeAdd"]
            prefix = ip_network(upd["prefix"])
            if upd.get("protocol") in ("ripv2", "ripng"):
                return  # our own routes echoed back by the RIB
            if self._want_af(prefix.network_address):
                self.inst.redistribute(
                    prefix, metric=max(1, upd.get("metric", 0)),
                    tag=upd.get("tag") or 0,
                )
                self.loop.run_until_idle()
        elif "RouteRedistributeDel" in ev:
            upd = ev["RouteRedistributeDel"]
            prefix = ip_network(upd["prefix"])
            if self._want_af(prefix.network_address):
                self.inst.redistribute_del(prefix)
                self.loop.run_until_idle()
        elif "RouteIpAdd" in ev or "RouteIpDel" in ev:
            pass  # our own installed routes echoed by the RIB manager
        else:
            raise Unsupported(f"ibus {next(iter(ev))}")

    def apply_protocol(self, ev: dict) -> None:
        inst = self.inst
        if "UdpRxPdu" in ev:
            rx = ev["UdpRxPdu"]
            pj = rx.get("pdu", {})
            port = int(rx["src"].rsplit(":", 1)[1])
            src_str = rx["src"].rsplit(":", 1)[0].strip("[]")
            # RIPng sources embed a zone (the kernel ifindex).
            zone = None
            if "%" in src_str:
                src_str, zone = src_str.split("%", 1)
            src = ip_address(src_str)
            ifname = None
            if zone is not None:
                ifname = next(
                    (
                        n for n, idx in self.ifindex.items()
                        if str(idx) == zone
                    ),
                    None,
                )
            if ifname is None:
                ifname = self._iface_for(src)
            if ifname is None:
                return
            self.inst.neighbors[src] = self.loop.clock.now()
            if "Err" in pj:
                return  # recorded decode error: only the peer stats move
            pdu_json = pj.get("Ok", pj)
            well_known = 520 if self.family == "ripv2" else 521
            if pdu_json.get("command") == "Response" and port != well_known:
                return  # responses must come from the RIP port
            data = _pdu_from_json(self.version, pdu_json)
            inst.handle(NetRxPacket(ifname, src, None, data))
            self.loop.run_until_idle()
        elif "InitialUpdate" in ev:
            inst.initial_update()
        elif "UpdateInterval" in ev:
            inst._send_updates(changed_only=False)
        elif "TriggeredUpd" in ev:
            inst.drain_triggered()
        elif "TriggeredUpdTimeout" in ev:
            inst.holdoff_expired()
        elif "RouteTimeout" in ev:
            inst.route_timeout(ip_network(ev["RouteTimeout"]["prefix"]))
        elif "RouteGcTimeout" in ev:
            inst.route_gc(ip_network(ev["RouteGcTimeout"]["prefix"]))
        elif "NbrTimeout" in ev:
            inst.nbr_timeout(ip_address(ev["NbrTimeout"]["addr"]))
        else:
            raise Unsupported(f"protocol {next(iter(ev))}")
        self.loop.run_until_idle()

    def _iface_for(self, src):
        for ifname, (_cfg, _a, prefix) in self.inst.interfaces.items():
            if prefix is not None and src in prefix:
                return ifname
        return None

    def bring_up(self) -> None:
        for line in (self.rt_dir / "events.jsonl").read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])
        self.live = True
        self.inst._holdoff = False

    # -- output planes

    def drain_tx(self):
        out = self.tx.log[:]
        self.tx.log.clear()
        return out

    def drain_ibus(self):
        out = self.ibus_log[:]
        self.ibus_log.clear()
        return out

    def compare_protocol_output(self, expected_lines: list[dict]) -> list[str]:
        ours = []
        for ifname, dst, data in self.drain_tx():
            ours.append(
                {"ifname": ifname, "pdu": _pdu_to_json(self.version, data)}
            )
        problems = []
        want = []
        for exp in expected_lines:
            tx = exp.get("UdpTxPdu")
            if tx is None:
                problems.append(f"unsupported output {next(iter(exp))}")
                continue
            want.append({"ifname": tx.get("ifname"), "pdu": tx["pdu"]})

        def matches(w, g):
            if w["ifname"] is not None and w["ifname"] != g["ifname"]:
                return False
            return subset_match(w["pdu"], g["pdu"])

        cand = [[i for i, g in enumerate(ours) if matches(w, g)] for w in want]
        assign: dict[int, int] = {}

        def try_assign(w: int, seen: set) -> bool:
            for i in cand[w]:
                if i in seen:
                    continue
                seen.add(i)
                if i not in assign or try_assign(assign[i], seen):
                    assign[i] = w
                    return True
            return False

        for w, item in enumerate(want):
            if not try_assign(w, set()):
                problems.append(
                    "expected tx not sent: " + json.dumps(item["pdu"])[:160]
                )
        # Two-sided (stub/mod.rs:320-429): extra transmissions fail too.
        for i, got in enumerate(ours):
            if i not in assign:
                problems.append(
                    "unexpected tx: " + json.dumps(got["pdu"])[:160]
                )
        return problems

    def compare_ibus(self, expected_lines: list[dict]) -> list[str]:
        proto = "ripv2" if self.family == "ripv2" else "ripng"
        ours = []
        for kind, prefix, route in self.drain_ibus():
            if kind == "add":
                ours.append(
                    {
                        "RouteIpAdd": {
                            "protocol": proto,
                            "prefix": str(prefix),
                            "metric": route.metric,
                            "nexthops": sorted(
                                [
                                    (
                                        self.ifindex.get(route.ifname, 0),
                                        str(route.nexthop)
                                        if route.nexthop
                                        else None,
                                    )
                                ]
                            ),
                        }
                    }
                )
            else:
                ours.append(
                    {"RouteIpDel": {"protocol": proto, "prefix": str(prefix)}}
                )
        problems = []
        unmatched = list(ours)
        for exp in expected_lines:
            if "RouteIpAdd" in exp:
                e = exp["RouteIpAdd"]
                canon = {
                    "RouteIpAdd": {
                        "protocol": e.get("protocol"),
                        "prefix": e.get("prefix"),
                        "metric": e.get("metric"),
                        "nexthops": sorted(
                            (
                                nh.get("Address", {}).get("ifindex", 0),
                                nh.get("Address", {}).get("addr"),
                            )
                            for nh in e.get("nexthops", [])
                        ),
                    }
                }
            elif "RouteIpDel" in exp:
                canon = {
                    "RouteIpDel": {
                        "protocol": exp["RouteIpDel"].get("protocol"),
                        "prefix": exp["RouteIpDel"].get("prefix"),
                    }
                }
            else:
                continue
            hit = next(
                (
                    i
                    for i, got in enumerate(unmatched)
                    if subset_match(canon, got)
                ),
                None,
            )
            if hit is None:
                problems.append(
                    "expected ibus msg not sent: " + json.dumps(canon)[:140]
                )
            else:
                unmatched.pop(hit)
        for got in unmatched:  # two-sided: extra ibus emissions fail
            problems.append(
                "unexpected ibus msg: " + json.dumps(got)[:140]
            )
        return problems

    def compare_state(self, state: dict) -> list[str]:
        rip = state["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-rip:rip"]
        problems = []
        af = rip.get("ipv4") if self.family == "ripv2" else rip.get("ipv6")
        if af is None:
            return problems
        nbrs = (af.get("neighbors") or {}).get("neighbor")
        if nbrs is not None:
            key = (
                "ipv4-address" if self.family == "ripv2" else "ipv6-address"
            )
            exp = {n[key] for n in nbrs}
            got = {str(a) for a in self.inst.neighbors}
            if exp != got:
                problems.append(f"neighbors {sorted(got)} != {sorted(exp)}")
        routes = (af.get("routes") or {}).get("route")
        if routes is not None:
            key = "ipv4-prefix" if self.family == "ripv2" else "ipv6-prefix"
            exp_by_prefix = {ip_network(r[key]): r for r in routes}
            ours = self.inst.routes
            for prefix, r in exp_by_prefix.items():
                got = ours.get(prefix)
                if got is None:
                    problems.append(f"missing route {prefix}")
                    continue
                if r.get("metric") is not None and got.metric != r["metric"]:
                    problems.append(
                        f"{prefix}: metric {got.metric} != {r['metric']}"
                    )
                if "next-hop" in r and str(got.nexthop) != r["next-hop"]:
                    problems.append(
                        f"{prefix}: nexthop {got.nexthop} != {r['next-hop']}"
                    )
                if "interface" in r and got.ifname != r["interface"]:
                    problems.append(
                        f"{prefix}: iface {got.ifname} != {r['interface']}"
                    )
                want_type = r.get("route-type")
                have_type = (
                    "connected" if got.route_type == "connected" else
                    "redistributed" if got.route_type == "redistributed"
                    else "rip"
                )
                if want_type is not None and have_type != want_type:
                    problems.append(
                        f"{prefix}: type {have_type} != {want_type}"
                    )
                if r.get("deleted"):
                    problems.append(f"{prefix}: expected deleted route")
                if r.get("inactive") is not None:
                    inactive = got.garbage_at is not None
                    if inactive != r["inactive"]:
                        problems.append(
                            f"{prefix}: inactive {inactive} != {r['inactive']}"
                        )
                if r.get("need-triggered-update") is not None:
                    if got.changed != r["need-triggered-update"]:
                        problems.append(
                            f"{prefix}: changed {got.changed} != "
                            f"{r['need-triggered-update']}"
                        )
            for prefix in set(ours) - set(exp_by_prefix):
                problems.append(f"extra route {prefix}")
        return problems

    # -- config / rpc

    def apply_rpc(self, rpc: dict) -> None:
        if "ietf-rip:clear-rip-route" in rpc:
            self.inst.clear_routes()
        else:
            raise Unsupported(f"rpc {next(iter(rpc))}")
        self.loop.run_until_idle()

    def apply_config_change(self, tree: dict) -> None:
        proto = tree["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]
        rip = proto.get("ietf-rip:rip", {})
        inst = self.inst
        unhandled: list[str] = []

        def op_of(node: dict, leaf: str | None = None):
            ann = node.get("@" + leaf if leaf else "@") or {}
            return ann.get("yang:operation")

        dist = rip.get("distance")
        if isinstance(dist, dict) and op_of(dist, "default") in (
            "replace", "create"
        ):
            inst.distance = dist["default"]
        elif isinstance(dist, int) and op_of(rip, "distance") in (
            "replace", "create"
        ):
            inst.distance = dist
            for prefix, route in inst.routes.items():
                if route.route_type == "rip" and route.metric < INFINITY_METRIC:
                    self.ibus_log.append(("add", prefix, route))
        for if_node in (rip.get("interfaces") or {}).get("interface", []):
            ifname = if_node["interface"]
            if op_of(if_node) == "delete":
                self.if_conf.pop(ifname, None)
                inst.remove_interface(ifname)
                self.addrs.pop(ifname, None)
                self.oper_up.discard(ifname)
                continue
            if op_of(if_node) == "create":
                self.if_conf[ifname] = {
                    k: v for k, v in if_node.items()
                    if not k.startswith("@")
                }
                self._ensure_iface(ifname)
            entry = inst.interfaces.get(ifname)
            cfg = entry[0] if entry else None
            if op_of(if_node, "cost") in ("replace", "create"):
                self.if_conf.setdefault(ifname, {})["cost"] = if_node["cost"]
                if cfg is not None:
                    inst.iface_cost_update(ifname, if_node["cost"])
            if op_of(if_node, "split-horizon") in ("replace", "create"):
                self.if_conf.setdefault(ifname, {})["split-horizon"] = (
                    if_node["split-horizon"]
                )
                if cfg is not None:
                    cfg.split_horizon = if_node["split-horizon"]
            if op_of(if_node, "passive") in ("replace", "create"):
                self.if_conf.setdefault(ifname, {})["passive"] = if_node[
                    "passive"
                ]
                if cfg is not None:
                    cfg.passive = bool(if_node["passive"])
            nbrs = (if_node.get("neighbors") or {}).get("neighbor", [])
            for nbr in nbrs:
                addr = ip_address(nbr["address"])
                if op_of(nbr) == "delete":
                    inst.static_neighbors.discard((ifname, addr))
                else:
                    inst.static_neighbors.add((ifname, addr))
        for nbr in (rip.get("static-neighbors") or {}).get("neighbor", []):
            addr = ip_address(nbr["ipv4-address" if self.family == "ripv2" else "ipv6-address"])
            ifname = inst._iface_of(addr)
            if op_of(nbr) == "delete":
                inst.static_neighbors = {
                    (i, a) for i, a in inst.static_neighbors if a != addr
                }
            elif ifname is not None and (
                (ifname, addr) not in inst.static_neighbors
            ):
                inst.static_neighbors.add((ifname, addr))
                entry = inst.interfaces[ifname]
                inst.netio.send(
                    ifname, entry[1], addr,
                    self.version.encode_request_all(),
                )
        self.loop.run_until_idle()


def run_case(family: str, case_dir: Path, topo: str, rt: str):
    run = CaseRun(family, RIP_DIR / family / "topologies" / topo, rt)
    try:
        run.bring_up()
    except Unsupported as e:
        return "skip", f"bring-up: {e}"
    run.drain_tx()
    run.drain_ibus()

    steps = sorted(
        {f.name.split("-")[0] for f in case_dir.iterdir() if f.name[0].isdigit()}
    )
    problems = []
    for step in steps:
        run.drain_ibus()
        try:
            for kind in ("ibus", "protocol"):
                f = case_dir / f"{step}-input-{kind}.jsonl"
                if f.exists():
                    for line in f.read_text().splitlines():
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if kind == "ibus":
                            run.apply_ibus(ev)
                        else:
                            run.apply_protocol(ev)
            f = case_dir / f"{step}-input-northbound-config-change.json"
            if f.exists():
                run.apply_config_change(json.loads(f.read_text()))
            f = case_dir / f"{step}-input-northbound-rpc.json"
            if f.exists():
                run.apply_rpc(json.loads(f.read_text()))
        except Unsupported as e:
            return "skip", f"step {step}: {e}"
        # The stub's sync point: queued self-posted triggers drain once
        # all of the step's inputs have been applied.
        run.inst.drain_triggered()
        out_proto = case_dir / f"{step}-output-protocol.jsonl"
        if out_proto.exists():
            expected = [
                json.loads(l)
                for l in out_proto.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}"
                for p in run.compare_protocol_output(expected)
            ]
        else:
            run.drain_tx()
        out_ibus = case_dir / f"{step}-output-ibus.jsonl"
        if out_ibus.exists():
            expected = [
                json.loads(l)
                for l in out_ibus.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}" for p in run.compare_ibus(expected)
            ]
        out_state = case_dir / f"{step}-output-northbound-state.json"
        if out_state.exists():
            state = json.loads(out_state.read_text())
            problems += [
                f"step {step}: {p}" for p in run.compare_state(state)
            ]
    return ("pass", "") if not problems else ("fail", "; ".join(problems[:6]))


def run_all(families=("ripv2", "ripng")):
    results = {}
    for family in families:
        for case, (topo, rt) in sorted(case_map(family).items()):
            case_dir = RIP_DIR / family / case
            if not case_dir.is_dir():
                continue
            try:
                results[f"{family}/{case}"] = run_case(
                    family, case_dir, topo, rt
                )
            except Exception as e:  # noqa: BLE001 — survey must not die
                results[f"{family}/{case}"] = (
                    "fail", f"exception: {type(e).__name__}: {e}"
                )
    return results


if __name__ == "__main__":
    res = run_all()
    by = {"pass": [], "fail": [], "skip": []}
    for case, (status, detail) in sorted(res.items()):
        by[status].append(case)
        if status != "pass":
            print(f"{status:5} {case}: {detail[:170]}")
    print(
        f"\npass {len(by['pass'])} fail {len(by['fail'])} "
        f"skip {len(by['skip'])} / {len(res)}"
    )

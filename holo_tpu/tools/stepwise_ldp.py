"""LDP stepwise conformance: replay the reference's recorded corpus.

Drives holo-ldp/tests/conformance (70 step cases + 2 topology snapshots)
through the live LdpEngine (holo_tpu/protocols/ldp/engine.py) and the real
wire codec: every recorded message is rebuilt as a Message object, encoded
to RFC 5036 wire bytes, decoded back, and only then handed to the engine —
so each replay exercises the codec round-trip as well as the protocol
logic.

Asserted planes per step (mirrors holo-protocol/src/test/stub/mod.rs):
- protocol: NbrTxPdu messages (nbr_id + message content + flush; message
  ids are counter positions, compared where the recording is aligned);
- ibus: RouteMplsAdd / RouteMplsDel label-FIB programming;
- northbound-notif: hello-adjacency / peer / fec YANG notifications;
- northbound-state: full ietf-mpls-ldp operational tree (deep compare).
"""

from __future__ import annotations

import json
import re
from ipaddress import IPv4Address, ip_address, ip_interface, ip_network
from pathlib import Path

from holo_tpu.protocols.ldp.engine import (
    InterfaceCfg,
    Interface,
    LdpEngine,
    TargetedNbr,
    TargetedNbrCfg,
)
from holo_tpu.protocols.ldp.packet import (
    AddressMsg,
    DecodeError,
    FecPrefix,
    FecWildcard,
    HelloMsg,
    InitMsg,
    KeepaliveMsg,
    LabelMsg,
    MsgType,
    NotifMsg,
    Pdu,
    AF_IPV4,
    AF_IPV6,
    HELLO_GTSM,
    HELLO_REQ_TARGETED,
    HELLO_TARGETED,
)

LDP_DIR = Path("/root/reference/holo-ldp/tests/conformance")


class Unsupported(Exception):
    pass


def case_map() -> dict[str, tuple[str, str]]:
    out = {}
    text = (LDP_DIR / "mod.rs").read_text()
    for m in re.finditer(
        r'run_test(?:_topology)?::<[^(]*\(\s*"([^"]+)",\s*"([^"]+)",'
        r'\s*"([^"]+)"',
        text,
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


def _loads_lenient(text: str):
    """Some recorded files carry trailing bytes after the JSON value."""
    return json.JSONDecoder().raw_decode(text)[0]


# ===== reference serde JSON <-> Message objects =====

_HELLO_FLAGS = [
    ("TARGETED", HELLO_TARGETED),
    ("REQ_TARGETED", HELLO_REQ_TARGETED),
    ("GTSM", HELLO_GTSM),
]


def _flags_from_str(s: str, table) -> int:
    out = 0
    for name in filter(None, (p.strip() for p in s.split("|"))):
        for fname, bit in table:
            if fname == name:
                out |= bit
                break
        else:
            raise Unsupported(f"flag {name}")
    return out


def _flags_to_str(v: int, table) -> str:
    return " | ".join(name for name, bit in table if v & bit)


def _fec_from_json(e):
    if "Prefix" in e:
        return FecPrefix(ip_network(e["Prefix"]))
    wc = e["Wildcard"]
    if wc == "All":
        return FecWildcard()
    af = wc["Typed"]["Prefix"]
    return FecWildcard(typed_af=AF_IPV4 if af == "Ipv4" else AF_IPV6)


def _fec_to_json(elem):
    if isinstance(elem, FecPrefix):
        return {"Prefix": str(elem.prefix)}
    if elem.typed_af is None:
        return {"Wildcard": "All"}
    return {
        "Wildcard": {
            "Typed": {
                "Prefix": "Ipv4" if elem.typed_af == AF_IPV4 else "Ipv6"
            }
        }
    }


def msg_from_json(j: dict):
    kind, body = next(iter(j.items()))
    if kind == "Hello":
        params = body["params"]
        return HelloMsg(
            msg_id=body.get("msg_id", 0),
            holdtime=params["holdtime"],
            flags=_flags_from_str(params.get("flags", ""), _HELLO_FLAGS),
            ipv4_addr=(
                IPv4Address(body["ipv4_addr"])
                if body.get("ipv4_addr")
                else None
            ),
            cfg_seqno=body.get("cfg_seqno"),
        )
    if kind == "Initialization":
        params = body["params"]
        flags = 0
        if params.get("flags"):
            raise Unsupported(f"init flags {params['flags']}")
        return InitMsg(
            msg_id=body.get("msg_id", 0),
            keepalive_time=params["keepalive_time"],
            flags=flags,
            pvlim=params.get("pvlim", 0),
            max_pdu_len=params.get("max_pdu_len", 0),
            lsr_id=IPv4Address(params["lsr_id"]),
            lspace_id=params.get("lspace_id", 0),
            cap_dynamic="cap_dynamic" in body
            and body["cap_dynamic"] is not None,
            cap_twcard_fec=body.get("cap_twcard_fec"),
            cap_unrec_notif=body.get("cap_unrec_notif"),
        )
    if kind == "Keepalive":
        return KeepaliveMsg(msg_id=body.get("msg_id", 0))
    if kind == "Address":
        af, addrs = next(iter(body["addr_list"].items()))
        return AddressMsg(
            msg_id=body.get("msg_id", 0),
            withdraw=body["msg_type"] == "AddressWithdraw",
            addr_list=[ip_address(a) for a in addrs],
        )
    if kind == "Label":
        return LabelMsg(
            msg_id=body.get("msg_id", 0),
            msg_type=MsgType[_camel_to_const(body["msg_type"])],
            fec=[_fec_from_json(e) for e in body.get("fec", [])],
            label=body.get("label"),
            request_id=body.get("request_id"),
        )
    if kind == "Notification":
        st = body["status"]
        return NotifMsg(
            msg_id=body.get("msg_id", 0),
            status_code=st["status_code"],
            status_msg_id=st.get("msg_id", 0),
            status_msg_type=st.get("msg_type", 0),
            fec=(
                [_fec_from_json(e) for e in body["fec"]]
                if body.get("fec")
                else None
            ),
        )
    raise Unsupported(f"message {kind}")


_CAMEL = {
    "LabelMapping": "LABEL_MAPPING",
    "LabelRequest": "LABEL_REQUEST",
    "LabelWithdraw": "LABEL_WITHDRAW",
    "LabelRelease": "LABEL_RELEASE",
    "LabelAbortReq": "LABEL_ABORT_REQ",
}


def _camel_to_const(s: str) -> str:
    return _CAMEL[s]


_CONST_TO_CAMEL = {v: k for k, v in _CAMEL.items()}


def msg_to_json(msg) -> dict:
    if isinstance(msg, HelloMsg):
        body = {
            "msg_id": msg.msg_id,
            "params": {
                "holdtime": msg.holdtime,
                "flags": _flags_to_str(msg.flags, _HELLO_FLAGS),
            },
        }
        if msg.ipv4_addr is not None:
            body["ipv4_addr"] = str(msg.ipv4_addr)
        if msg.cfg_seqno is not None:
            body["cfg_seqno"] = msg.cfg_seqno
        return {"Hello": body}
    if isinstance(msg, InitMsg):
        return {
            "Initialization": {
                "msg_id": msg.msg_id,
                "params": {
                    "version": 1,
                    "keepalive_time": msg.keepalive_time,
                    "flags": "",
                    "pvlim": msg.pvlim,
                    "max_pdu_len": msg.max_pdu_len,
                    "lsr_id": str(msg.lsr_id),
                    "lspace_id": msg.lspace_id,
                },
                **({"cap_dynamic": []} if msg.cap_dynamic else {}),
                **(
                    {"cap_twcard_fec": msg.cap_twcard_fec}
                    if msg.cap_twcard_fec is not None
                    else {}
                ),
                **(
                    {"cap_unrec_notif": msg.cap_unrec_notif}
                    if msg.cap_unrec_notif is not None
                    else {}
                ),
            }
        }
    if isinstance(msg, KeepaliveMsg):
        return {"Keepalive": {"msg_id": msg.msg_id}}
    if isinstance(msg, AddressMsg):
        return {
            "Address": {
                "msg_id": msg.msg_id,
                "msg_type": (
                    "AddressWithdraw" if msg.withdraw else "Address"
                ),
                "addr_list": {
                    "Ipv4": [str(a) for a in msg.addr_list]
                },
            }
        }
    if isinstance(msg, LabelMsg):
        body = {
            "msg_id": msg.msg_id,
            "msg_type": _CONST_TO_CAMEL[msg.msg_type.name],
            "fec": [_fec_to_json(e) for e in msg.fec],
        }
        if msg.label is not None:
            body["label"] = msg.label
        if msg.request_id is not None:
            body["request_id"] = msg.request_id
        return {"Label": body}
    if isinstance(msg, NotifMsg):
        body = {
            "msg_id": msg.msg_id,
            "status": {
                "status_code": msg.status_code,
                "msg_id": msg.status_msg_id,
                "msg_type": msg.status_msg_type,
            },
        }
        if msg.fec is not None:
            body["fec"] = [_fec_to_json(e) for e in msg.fec]
        return {"Notification": body}
    raise Unsupported(f"msg_to_json {type(msg).__name__}")


def _decode_err_from_json(err) -> DecodeError:
    if isinstance(err, str):
        return DecodeError(err)
    kind, args = next(iter(err.items()))
    if not isinstance(args, list):
        args = [args]
    return DecodeError(kind, *args)


# ===== the case runner =====


class CaseRun:
    def __init__(self, topo_dir: Path, rt: str):
        self.rt_dir = topo_dir / rt
        self.tx_log: list = []  # (nbr_id, msg_json, flush)
        self.ibus_log: list = []  # {kind: payload}
        self.notif_log: list = []  # {name: data}
        self.engine = LdpEngine(
            "test",
            send_cb=self._capture_tx,
            ibus_cb=lambda kind, payload: self.ibus_log.append(
                {kind: payload}
            ),
            notif_cb=lambda name, data: self.notif_log.append(
                {name: data}
            ),
        )
        cfg = _loads_lenient((self.rt_dir / "config.json").read_text())
        self._apply_initial_config(cfg)

    def _capture_tx(self, nbr_id, msg, flush):
        # Round-trip through the wire codec: what goes on the log is what
        # a peer would decode off the TCP stream.
        wire = Pdu(
            self.engine.router_id or IPv4Address("0.0.0.0"), 0, [msg]
        ).encode()
        decoded = Pdu.decode(wire)
        assert len(decoded.messages) == 1
        self.tx_log.append((nbr_id, msg_to_json(decoded.messages[0]), flush))

    # ---- configuration

    def _apply_initial_config(self, cfg: dict) -> None:
        proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-mpls-ldp:mpls-ldp"]
        eng = self.engine
        g = proto.get("global", {})
        if "lsr-id" in g:
            eng.config.router_id = IPv4Address(g["lsr-id"])
        af = (g.get("address-families") or {}).get("ipv4")
        if af is not None:
            eng.config.ipv4_enabled = af.get("enabled", True)
        disc = proto.get("discovery", {})
        for i in (disc.get("interfaces") or {}).get("interface", []):
            iface = Interface(name=i["name"], config=InterfaceCfg())
            iaf = (i.get("address-families") or {}).get("ipv4")
            if iaf is not None:
                iface.config.ipv4_enabled = iaf.get("enabled", True)
            if "hello-holdtime" in i:
                iface.config.hello_holdtime = i["hello-holdtime"]
            eng.interfaces[i["name"]] = iface
        targeted = disc.get("targeted") or {}
        if "hello-accept" in targeted:
            eng.config.targeted_hello_accept = targeted[
                "hello-accept"
            ].get("enabled", False)
        taf = (targeted.get("address-families") or {}).get("ipv4") or {}
        for t in (taf.get("target") or []):
            addr = IPv4Address(t["adjacent-address"])
            tnbr = TargetedNbr(
                addr=addr,
                configured=True,
                config=TargetedNbrCfg(enabled=t.get("enabled", True)),
            )
            self.engine.tneighbors[addr] = tnbr
        eng.update()

    def apply_config_change(self, tree: dict) -> None:
        """nb-config-* cases: YANG data tree with yang:operation
        annotations -> engine config mutations + update events
        (northbound/configuration.rs callbacks)."""
        routing = tree.get("ietf-routing:routing", {})
        protos = (routing.get("control-plane-protocols") or {}).get(
            "control-plane-protocol", []
        )
        eng = self.engine
        for proto in protos:
            node = proto.get("ietf-mpls-ldp:mpls-ldp")
            if node is None:
                continue
            self._config_global(node.get("global") or {})
            self._config_discovery(node.get("discovery") or {})

    @staticmethod
    def _op(node: dict, leaf: str | None = None):
        ann = node.get("@" + leaf if leaf else "@") or {}
        return ann.get("yang:operation")

    def _config_global(self, g: dict) -> None:
        eng = self.engine
        changed = False
        if "lsr-id" in g and self._op(g, "lsr-id") in (
            "create",
            "replace",
        ):
            eng.config.router_id = IPv4Address(g["lsr-id"])
            changed = True
        af = (g.get("address-families") or {}).get("ipv4")
        if af is not None:
            afop = self._op(g.get("address-families") or {}, None)
            if self._op(af) == "delete":
                eng.config.ipv4_enabled = None
                changed = True
            elif "enabled" in af:
                op = self._op(af, "enabled") or self._op(af)
                if op in ("create", "replace"):
                    eng.config.ipv4_enabled = af["enabled"]
                    changed = True
                elif op == "delete":
                    eng.config.ipv4_enabled = None
                    changed = True
            elif self._op(af) == "create":
                eng.config.ipv4_enabled = af.get("enabled", True)
                changed = True
        if changed:
            eng.update()

    def _config_discovery(self, disc: dict) -> None:
        eng = self.engine
        for i in (disc.get("interfaces") or {}).get("interface", []):
            name = i["name"]
            op = self._op(i)
            if op == "delete":
                iface = eng.interfaces.pop(name, None)
                if iface is not None and iface.active:
                    eng.iface_stop(iface)
                continue
            iface = eng.interfaces.get(name)
            if iface is None:
                iface = Interface(name=name, config=InterfaceCfg())
                eng.interfaces[name] = iface
            iaf = (i.get("address-families") or {}).get("ipv4")
            if iaf is not None:
                if self._op(iaf) == "delete":
                    iface.config.ipv4_enabled = None
                elif "enabled" in iaf:
                    iface.config.ipv4_enabled = iaf["enabled"]
                elif self._op(iaf) == "create":
                    iface.config.ipv4_enabled = iaf.get("enabled", True)
            if "hello-holdtime" in i:
                iface.config.hello_holdtime = i["hello-holdtime"]
            eng.iface_check(iface)
        targeted = disc.get("targeted") or {}
        if "hello-accept" in targeted:
            ha = targeted["hello-accept"]
            if self._op(ha) == "delete" or self._op(ha, "enabled") == (
                "delete"
            ):
                eng.config.targeted_hello_accept = False
            elif "enabled" in ha:
                eng.config.targeted_hello_accept = ha["enabled"]
            # Dropping hello-accept deactivates dynamic targeted nbrs
            # (configuration.rs Event::TargetedNbrRemoveDynamic).
            if not eng.config.targeted_hello_accept:
                for tnbr in list(eng.tneighbors.values()):
                    tnbr.dynamic = False
                    eng.tnbr_update(tnbr)
        taf = (targeted.get("address-families") or {}).get("ipv4") or {}
        for t in taf.get("target") or []:
            addr = IPv4Address(t["adjacent-address"])
            op = self._op(t)
            if op == "delete":
                tnbr = eng.tneighbors.get(addr)
                if tnbr is not None:
                    tnbr.configured = False
                    eng.tnbr_update(tnbr)
                continue
            tnbr = eng.tneighbors.get(addr)
            if tnbr is None:
                tnbr = TargetedNbr(addr=addr, configured=True)
                eng.tneighbors[addr] = tnbr
            tnbr.configured = True
            if "enabled" in t:
                tnbr.config.enabled = t["enabled"]
            eng.tnbr_update(tnbr)

    # ---- events

    def apply_ibus(self, ev: dict) -> None:
        kind, body = next(iter(ev.items()))
        eng = self.engine
        if kind == "RouterIdUpdate":
            eng.router_id_update(
                IPv4Address(body) if body is not None else None
            )
        elif kind == "InterfaceUpd":
            eng.iface_update(
                body["ifname"],
                body.get("ifindex"),
                "OPERATIVE" in (body.get("flags") or ""),
            )
        elif kind == "InterfaceAddressAdd":
            eng.addr_add(
                body["ifname"],
                ip_interface(body["addr"]),
                unnumbered="UNNUMBERED" in (body.get("flags") or ""),
            )
        elif kind == "InterfaceAddressDel":
            eng.addr_del(
                body["ifname"],
                ip_interface(body["addr"]),
                unnumbered="UNNUMBERED" in (body.get("flags") or ""),
            )
        elif kind == "RouteRedistributeAdd":
            nexthops = []
            for nh in body.get("nexthops", []):
                if "Address" in nh:
                    a = nh["Address"]
                    nexthops.append(
                        (a.get("ifindex"), ip_address(a["addr"]))
                    )
            eng.route_add(
                ip_network(body["prefix"]), body["protocol"], nexthops
            )
        elif kind == "RouteRedistributeDel":
            eng.route_del(ip_network(body["prefix"]))
        elif kind in ("RouteIpAdd", "RouteIpDel", "RouteMplsAdd",
                      "RouteMplsDel"):
            pass  # our own routes echoed back; LDP ignores them
        else:
            raise Unsupported(f"ibus {kind}")

    def apply_protocol(self, ev: dict) -> None:
        kind, body = next(iter(ev.items()))
        eng = self.engine
        if kind == "UdpRxPdu":
            src = ip_address(body["src_addr"])
            multicast = body["multicast"]
            pdu_j = body["pdu"]
            if "Err" in pdu_j:
                pdu = _decode_err_from_json(pdu_j["Err"])
            else:
                pdu = self._pdu_from_json(pdu_j["Ok"], multicast)
            eng.udp_rx_pdu(src, multicast, pdu)
        elif kind == "AdjTimeout":
            eng.adj_timeout(body["adj_id"])
        elif kind == "TcpAccept":
            eng.tcp_accept(body["conn_info"])
        elif kind == "TcpConnect":
            eng.tcp_connect(body["nbr_id"], body["conn_info"])
        elif kind == "NbrRxPdu":
            pdu_j = body["pdu"]
            if "Err" in pdu_j:
                err = pdu_j["Err"]
                ekind = err if isinstance(err, str) else next(iter(err))
                if ekind == "TcpConnClosed":
                    eng.nbr_rx_pdu(body["nbr_id"], "conn-closed")
                elif ekind == "NbrPduDecodeError":
                    args = err[ekind]
                    derr = _decode_err_from_json(args[1])
                    eng.nbr_rx_pdu(
                        body["nbr_id"], ("decode-error", derr)
                    )
                else:
                    raise Unsupported(f"nbr pdu err {ekind}")
            else:
                pdu = self._pdu_from_json(pdu_j["Ok"], None)
                if isinstance(pdu, DecodeError):
                    eng.nbr_rx_pdu(
                        body["nbr_id"], ("decode-error", pdu)
                    )
                else:
                    eng.nbr_rx_pdu(body["nbr_id"], pdu)
        elif kind == "NbrKaTimeout":
            eng.nbr_ka_timeout(body["nbr_id"])
        elif kind == "NbrBackoffTimeout":
            eng.nbr_backoff_timeout(IPv4Address(body["lsr_id"]))
        else:
            raise Unsupported(f"protocol {kind}")

    def _pdu_from_json(self, j: dict, multicast):
        """JSON -> Pdu through the real wire codec (encode then decode)."""
        pdu = Pdu(
            IPv4Address(j["lsr_id"]),
            j.get("lspace_id", 0),
            [msg_from_json(m) for m in j.get("messages", [])],
        )
        wire = pdu.encode()
        try:
            return Pdu.decode(wire, multicast=multicast)
        except DecodeError as e:
            return e

    # ---- plane drains & comparisons

    def drain(self):
        tx, ib, nf = self.tx_log, self.ibus_log, self.notif_log
        self.tx_log, self.ibus_log, self.notif_log = [], [], []
        return tx, ib, nf

    def compare_protocol(self, expected_lines: list[dict], got) -> list[str]:
        problems = []
        want = []
        for exp in expected_lines:
            if "NbrTxPdu" not in exp:
                problems.append(
                    f"unsupported expected output {next(iter(exp))}"
                )
                continue
            e = exp["NbrTxPdu"]
            want.append(
                (e["nbr_id"], _strip_msg_id(e["msg"]), e.get("flush"))
            )
        ours = [
            (nbr_id, _strip_msg_id(mj), flush)
            for nbr_id, mj, flush in got
        ]
        for item in want:
            if item in ours:
                ours.remove(item)
            else:
                problems.append(
                    "expected tx missing: " + json.dumps(item[1])[:180]
                )
        for item in ours:
            problems.append(
                "unexpected tx: " + json.dumps(item[1])[:180]
            )
        return problems

    def compare_ibus(self, expected_lines: list[dict], got) -> list[str]:
        problems = []
        want = [
            e
            for e in expected_lines
            if next(iter(e)) in ("RouteMplsAdd", "RouteMplsDel")
        ]
        ours = [_canon_ibus(g) for g in got]
        want = [_canon_ibus(wn) for wn in want]
        for item in want:
            if item in ours:
                ours.remove(item)
            else:
                problems.append(
                    "expected ibus missing: " + json.dumps(item)[:180]
                )
        for item in ours:
            problems.append("unexpected ibus: " + json.dumps(item)[:180])
        return problems

    def compare_notifs(self, expected_lines: list[dict], got) -> list[str]:
        problems = []
        ours = list(got)
        for exp in expected_lines:
            if exp in ours:
                ours.remove(exp)
            else:
                problems.append(
                    "expected notif missing: " + json.dumps(exp)[:180]
                )
        for item in ours:
            problems.append(
                "unexpected notif: " + json.dumps(item)[:180]
            )
        return problems

    def compare_state(self, expected: dict) -> list[str]:
        exp_node = expected["ietf-routing:routing"][
            "control-plane-protocols"
        ]["control-plane-protocol"][0]["ietf-mpls-ldp:mpls-ldp"]
        got = self.engine.northbound_state()
        return _tree_diff(exp_node, got, "mpls-ldp")

    # ---- bring-up

    def bring_up(self) -> None:
        for line in (
            (self.rt_dir / "events.jsonl").read_text().splitlines()
        ):
            line = line.strip()
            if not line:
                continue
            ev = _loads_lenient(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])


def _strip_msg_id(mj: dict):
    kind, body = next(iter(mj.items()))
    body = dict(body)
    body.pop("msg_id", None)
    return json.dumps({kind: body}, sort_keys=True)


def _canon_ibus(e: dict) -> dict:
    kind, body = next(iter(e.items()))
    body = dict(body)
    nhs = []
    for nh in body.get("nexthops", []):
        if "Address" in nh:
            a = dict(nh["Address"])
            nhs.append(
                {
                    "Address": {
                        "ifindex": a.get("ifindex"),
                        "addr": a.get("addr"),
                        "labels": a.get("labels"),
                    }
                }
            )
    body["nexthops"] = sorted(nhs, key=json.dumps)
    if "route" in body and body["route"] is not None:
        body["route"] = list(body["route"])
    body.pop("replace", None)
    return {kind: body}


_LIST_KEYS = {
    "address": ("address", "advertisement-type", "peer"),
    "fec-label": ("fec",),
    "peer": ("lsr-id",),
    "interface": ("name",),
    "hello-adjacency": ("adjacent-address",),
    "target": ("adjacent-address",),
}


def _tree_diff(exp, got, path: str) -> list[str]:
    problems: list[str] = []
    if isinstance(exp, dict) and isinstance(got, dict):
        for k in exp:
            if k not in got:
                problems.append(f"{path}/{k}: missing")
            else:
                problems += _tree_diff(exp[k], got[k], f"{path}/{k}")
        for k in got:
            if k not in exp:
                problems.append(f"{path}/{k}: unexpected")
        return problems
    if isinstance(exp, list) and isinstance(got, list):
        name = path.rsplit("/", 1)[-1]
        keys = _LIST_KEYS.get(name)

        def keyfn(entry):
            if keys and isinstance(entry, dict):
                return json.dumps(
                    [entry.get(k) for k in keys], sort_keys=True
                )
            return json.dumps(entry, sort_keys=True)

        exp_s = sorted(exp, key=keyfn)
        got_s = sorted(got, key=keyfn)
        if len(exp_s) != len(got_s):
            problems.append(
                f"{path}: list length {len(got_s)} != {len(exp_s)}"
            )
        for i, (e, g) in enumerate(zip(exp_s, got_s)):
            problems += _tree_diff(e, g, f"{path}[{i}]")
        return problems
    if exp != got:
        problems.append(f"{path}: {got!r} != {exp!r}")
    return problems


def run_case(case_dir: Path, topo: str, rt: str):
    run = CaseRun(LDP_DIR / "topologies" / topo, rt)
    try:
        run.bring_up()
    except Unsupported as e:
        return "skip", f"bring-up: {e}"
    run.drain()

    steps = sorted(
        {
            f.name.split("-")[0]
            for f in case_dir.iterdir()
            if f.name[0].isdigit()
        }
    )
    problems = []
    for step in steps:
        try:
            for kind in ("ibus", "protocol"):
                f = case_dir / f"{step}-input-{kind}.jsonl"
                if f.exists():
                    for line in f.read_text().splitlines():
                        if not line.strip():
                            continue
                        ev = _loads_lenient(line)
                        if kind == "ibus":
                            run.apply_ibus(ev)
                        else:
                            run.apply_protocol(ev)
            f = case_dir / f"{step}-input-northbound-config-change.json"
            if f.exists():
                run.apply_config_change(
                    _loads_lenient(f.read_text())
                )
            f = case_dir / f"{step}-input-northbound-rpc.json"
            if f.exists():
                _apply_rpc(run, _loads_lenient(f.read_text()))
        except Unsupported as e:
            return "skip", f"step {step}: {e}"
        tx, ib, nf = run.drain()
        for plane, fname, cmp in (
            ("protocol", f"{step}-output-protocol.jsonl",
             lambda lines: run.compare_protocol(lines, tx)),
            ("ibus", f"{step}-output-ibus.jsonl",
             lambda lines: run.compare_ibus(lines, ib)),
            ("notif", f"{step}-output-northbound-notif.jsonl",
             lambda lines: run.compare_notifs(lines, nf)),
        ):
            f = case_dir / fname
            expected = (
                [
                    _loads_lenient(line)
                    for line in f.read_text().splitlines()
                    if line.strip()
                ]
                if f.exists()
                else []
            )
            problems += [f"step {step} {plane}: {p}" for p in cmp(expected)]
        f = case_dir / f"{step}-output-northbound-state.json"
        if f.exists():
            problems += [
                f"step {step} state: {p}"
                for p in run.compare_state(_loads_lenient(f.read_text()))
            ]
    return ("pass", "") if not problems else (
        "fail", "; ".join(problems[:8])
    )


def _apply_rpc(run: CaseRun, rpc: dict) -> None:
    if "ietf-mpls-ldp:mpls-ldp-clear-peer" in rpc:
        body = rpc["ietf-mpls-ldp:mpls-ldp-clear-peer"] or {}
        lsr_id = body.get("lsr-id")
        run.engine.clear_peer(
            IPv4Address(lsr_id) if lsr_id else None
        )
    elif "ietf-mpls-ldp:mpls-ldp-clear-hello-adjacency" in rpc:
        body = rpc["ietf-mpls-ldp:mpls-ldp-clear-hello-adjacency"] or {}
        ha = body.get("hello-adjacency") or {}
        targeted = None
        target_address = nh_iface = nh_addr = None
        if "targeted" in ha:
            targeted = True
            target_address = (ha["targeted"] or {}).get("target-address")
            if target_address:
                target_address = IPv4Address(target_address)
        if "link" in ha:
            targeted = False
            nh_iface = (ha["link"] or {}).get("next-hop-interface")
            nh_addr = (ha["link"] or {}).get("next-hop-address")
            if nh_addr:
                nh_addr = IPv4Address(nh_addr)
        run.engine.clear_hello_adjacency(
            targeted=targeted,
            target_address=target_address,
            next_hop_interface=nh_iface,
            next_hop_address=nh_addr,
        )
    elif "ietf-mpls-ldp:mpls-ldp-clear-peer-statistics" in rpc:
        body = (
            rpc["ietf-mpls-ldp:mpls-ldp-clear-peer-statistics"] or {}
        )
        lsr_id = body.get("lsr-id")
        run.engine.clear_peer_statistics(
            IPv4Address(lsr_id) if lsr_id else None
        )
    else:
        raise Unsupported(f"rpc {next(iter(rpc))}")


def run_topology(topo: str) -> dict[str, tuple[str, str]]:
    """Bring each router up and diff the converged output planes."""
    results = {}
    topo_dir = LDP_DIR / "topologies" / topo
    for rt_dir in sorted(topo_dir.iterdir()):
        if not rt_dir.is_dir():
            continue
        rt = rt_dir.name
        try:
            run = CaseRun(topo_dir, rt)
            run.bring_up()
            problems = []
            out = rt_dir / "output"
            f = out / "northbound-state.json"
            if f.exists():
                problems += run.compare_state(
                    _loads_lenient(f.read_text())
                )
            results[f"{topo}/{rt}"] = (
                ("pass", "")
                if not problems
                else ("fail", "; ".join(problems[:8]))
            )
        except Exception as e:  # noqa: BLE001
            results[f"{topo}/{rt}"] = (
                "fail",
                f"exception: {type(e).__name__}: {e}",
            )
    return results


def run_all():
    results = {}
    for case, (topo, rt) in sorted(case_map().items()):
        case_dir = LDP_DIR / case
        if not case_dir.is_dir():
            continue
        try:
            results[case] = run_case(case_dir, topo, rt)
        except Exception as e:  # noqa: BLE001
            results[case] = (
                "fail",
                f"exception: {type(e).__name__}: {e}",
            )
    return results


if __name__ == "__main__":
    import sys

    res = run_all()
    for topo in ("topo1-1", "topo2-1"):
        res.update(run_topology(topo))
    by = {"pass": [], "fail": [], "skip": []}
    for case, (status, detail) in sorted(res.items()):
        by[status].append(case)
        if status != "pass" and "-v" in sys.argv:
            print(f"{status:5} {case}: {detail[:260]}")
    print(
        f"pass {len(by['pass'])} fail {len(by['fail'])} "
        f"skip {len(by['skip'])} / {len(res)}"
    )
    if "-f" in sys.argv:
        for c in by["fail"]:
            print("FAIL", c)

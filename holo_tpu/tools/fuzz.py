"""Coverage-guided decoder fuzzing (the reference's fuzz/ equivalent).

The reference ships 31 libFuzzer targets over its wire decoders
(``fuzz/fuzz_targets/**``, driven by ``fuzz/fuzz-all.sh``).  atheris —
the Python libFuzzer binding — is not available in this image, so this
module implements the same loop natively on :mod:`sys.monitoring`
(PEP 669, CPython 3.12): per-target corpora evolve by keeping any
mutated input that lights up a previously-unseen line in the decoder
modules.

Contract under test (same as the reference's): a decoder fed arbitrary
bytes either succeeds or raises ``DecodeError`` — any other exception
is a crash, reported with the reproducing input.

Run standalone (`python -m holo_tpu.tools.fuzz [seconds-per-target]`)
or through ``tests/test_fuzz_coverage.py`` (time-capped).
"""

from __future__ import annotations

import random
import sys
import time
from dataclasses import dataclass, field
from ipaddress import IPv4Address as A
from ipaddress import IPv4Network as N

from holo_tpu.utils.bytesbuf import DecodeError, Reader

_TOOL_ID = 4  # sys.monitoring tool slot (0-5 free for applications)


@dataclass
class FuzzResult:
    name: str
    executions: int = 0
    corpus_size: int = 0
    coverage: int = 0
    crashes: list = field(default_factory=list)  # (exc, repr, hex)


#: sys.monitoring (PEP 669) landed in CPython 3.12.  Without it the loop
#: still runs — blind (no corpus growth), which is strictly better than
#: not fuzzing at all on older interpreters.
COVERAGE_AVAILABLE = hasattr(sys, "monitoring")


class _Coverage:
    """Line coverage over holo_tpu.protocols + holo_tpu.frr via
    sys.monitoring; degrades to coverage-less execution when the
    interpreter predates PEP 669."""

    def __init__(self):
        self.seen: set = set()
        self._new = False

    def _on_line(self, code, line):
        f = code.co_filename
        if "holo_tpu/protocols" not in f and "holo_tpu/frr" not in f:
            return sys.monitoring.DISABLE
        key = (id(code), line)
        if key not in self.seen:
            self.seen.add(key)
            self._new = True
        # Keep receiving events for this location only until seen once.
        return sys.monitoring.DISABLE

    def start(self):
        if not COVERAGE_AVAILABLE:
            return
        mon = sys.monitoring
        mon.use_tool_id(_TOOL_ID, "holo-fuzz")
        mon.register_callback(_TOOL_ID, mon.events.LINE, self._on_line)
        mon.set_events(_TOOL_ID, mon.events.LINE)

    def stop(self):
        if not COVERAGE_AVAILABLE:
            return
        mon = sys.monitoring
        mon.set_events(_TOOL_ID, 0)
        mon.free_tool_id(_TOOL_ID)

    def run(self, fn, data) -> tuple[bool, BaseException | None]:
        """Execute fn(data); returns (new_coverage, crash_exc)."""
        self._new = False
        if COVERAGE_AVAILABLE:
            sys.monitoring.restart_events()
        try:
            fn(data)
        except DecodeError:
            pass
        except Exception as e:  # noqa: BLE001 — the point of the fuzzer
            return self._new, e
        return self._new, None


def _mutate(rng: random.Random, seed: bytes) -> bytes:
    data = bytearray(seed)
    mode = rng.randrange(5)
    if mode == 0 or not data:
        return rng.randbytes(rng.randrange(0, 256))
    if mode == 1:  # byte flips
        for _ in range(rng.randrange(1, 8)):
            data[rng.randrange(len(data))] = rng.randrange(256)
    elif mode == 2:  # truncate / extend
        if rng.random() < 0.5:
            del data[rng.randrange(len(data)) :]
        else:
            data += rng.randbytes(rng.randrange(1, 32))
    elif mode == 3:  # interesting integers at random offsets
        v = rng.choice((0, 1, 0x7F, 0x80, 0xFF, 0xFFFF, 0x7FFFFFFF))
        w = rng.choice((1, 2, 4))
        off = rng.randrange(len(data))
        chunk = (v & ((1 << (8 * w)) - 1)).to_bytes(w, "big")
        data[off : off + w] = chunk
    else:  # splice two seeds
        other = bytearray(seed)
        cut = rng.randrange(len(data))
        data = data[:cut] + other[rng.randrange(len(other) or 1) :]
    return bytes(data)


def fuzz_target(
    name: str,
    fn,
    seeds: list[bytes],
    budget_s: float = 0.5,
    rng: random.Random | None = None,
) -> FuzzResult:
    """Evolve a corpus for one decoder until the time budget lapses."""
    rng = rng or random.Random(hash(name) & 0xFFFFFFFF)
    res = FuzzResult(name=name)
    cov = _Coverage()
    cov.start()
    try:
        corpus = [s for s in seeds if s]
        # Seed pass: baseline coverage from the valid inputs.
        for s in corpus:
            _, crash = cov.run(fn, s)
            if crash is not None:
                res.crashes.append((type(crash).__name__, str(crash)[:120], s.hex()))
            res.executions += 1
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            seed = rng.choice(corpus) if corpus else b""
            data = _mutate(rng, seed)
            new_cov, crash = cov.run(fn, data)
            res.executions += 1
            if crash is not None:
                res.crashes.append(
                    (type(crash).__name__, str(crash)[:120], data.hex())
                )
                if len(res.crashes) >= 5:
                    break
            elif new_cov:
                corpus.append(data)  # coverage-guided corpus growth
        res.corpus_size = len(corpus)
        res.coverage = len(cov.seen)
    finally:
        cov.stop()
    return res


def frr_padding_invariants(data: bytes) -> None:
    """Padded-input invariants of the FRR pipeline (not a wire decoder):
    pad rows carry ``valid == False`` and MUST be result-neutral.  The
    input bytes pick a small synth topology and a grown pad bucket; the
    structural invariants of :func:`holo_tpu.frr.inputs.marshal_frr` are
    checked and the scalar oracle's backup tables must be bit-identical
    across pad sizes (the device kernel is pinned bit-for-bit to the
    oracle — including one grown-pad case — in tests/test_frr_parity.py,
    so oracle invariance transfers).  Any violation raises
    AssertionError, which the harness reports as a crash.
    """
    if len(data) < 4:
        raise DecodeError("frr spec: need 4 bytes (kind, size, seed, pad)")
    import numpy as np  # noqa: PLC0415

    from holo_tpu.frr.inputs import marshal_frr  # noqa: PLC0415
    from holo_tpu.frr.scalar import frr_reference  # noqa: PLC0415
    from holo_tpu.spf import synth  # noqa: PLC0415

    kind, size, seed, pad = data[0] % 3, 3 + data[1] % 4, data[2], data[3]
    if kind == 0:
        topo = synth.ring_topology(size, seed=seed)
    elif kind == 1:
        topo = synth.grid_topology(2, size, seed=seed)
    else:
        topo = synth.random_ospf_topology(
            n_routers=size + 2, n_networks=2, extra_p2p=2, seed=seed
        )
    small = marshal_frr(topo, pad_multiple=1)
    grown = marshal_frr(topo, pad_multiple=8 * (1 + pad % 4))  # 8..32
    # Structural: pad rows are inert by construction.
    for fin in (small, grown):
        nl, na = fin.n_links, fin.n_adj
        assert not fin.link_valid[nl:].any(), "pad link marked valid"
        assert not fin.adj_valid[na:].any(), "pad adjacency marked valid"
        assert (fin.link_edge[nl:] == -1).all(), "pad link edge not -1"
        assert (fin.adj_link[na:] == -1).all(), "pad adj link not -1"
        assert fin.edge_masks[nl:].all(), "pad scenario must keep edges up"
    nl, na = small.n_links, small.n_adj
    assert (grown.n_links, grown.n_adj) == (nl, na), "pad changed counts"
    assert grown.atom_link == small.atom_link, "pad changed atom→link map"
    for f in ("link_edge", "link_far", "link_cost"):
        assert (getattr(small, f)[:nl] == getattr(grown, f)[:nl]).all(), f
    assert (small.edge_masks[:nl] == grown.edge_masks[:nl]).all()
    for f in ("adj_edge", "adj_nbr", "adj_cost", "adj_link", "adj_atom"):
        assert (getattr(small, f)[:na] == getattr(grown, f)[:na]).all(), f
    # Semantic: growing the pad never changes a table entry.
    a = frr_reference(topo, inputs=small)
    b = frr_reference(topo, inputs=grown)
    for f in (
        "lfa_adj",
        "lfa_nodeprot",
        "rlfa_pq",
        "tilfa_p",
        "tilfa_q",
        "post_dist",
        "post_nh",
    ):
        if not np.array_equal(getattr(a, f), getattr(b, f)):
            raise AssertionError(f"pad-variant table: {f}")


def delta_apply_invariants(data: bytes) -> None:
    """DeltaPath invariants (ISSUE 7; not a wire decoder): an arbitrary
    chain of topology deltas applied through ``DeviceGraphCache`` —
    weight changes, edge add/remove, transit strikes, depth caps and
    forced full rebuilds — must leave the device-resident graph
    representing EXACTLY the final topology: for every vertex, the
    multiset of valid (src, cost, atom) in-slots equals the topology's
    in-edges, and the one-hot atom words match the slot atoms.  Since
    every SPF engine consumes only those planes (plus ``in_edge_id``,
    which delta chains invalidate for mask consumers by contract), slot
    equality implies bit-identical SPF results; the devicewide parity
    property is pinned in tests/test_delta_spf.py.  Violations raise
    AssertionError, which the harness reports as a crash.
    """
    if len(data) < 6:
        raise DecodeError("delta spec: need 6+ bytes (kind,size,seed,depth,ops)")
    import numpy as np  # noqa: PLC0415

    from holo_tpu.ops.graph import diff_topologies  # noqa: PLC0415
    from holo_tpu.ops.spf_engine import DeviceGraphCache  # noqa: PLC0415
    from holo_tpu.spf import synth  # noqa: PLC0415
    from holo_tpu.spf.synth import clone_topology as clone  # noqa: PLC0415

    kind, size, seed = data[0] % 3, 4 + data[1] % 5, data[2]
    if kind == 0:
        topo = synth.ring_topology(size, seed=seed)
    elif kind == 1:
        topo = synth.grid_topology(2, size, seed=seed)
    else:
        topo = synth.random_ospf_topology(
            n_routers=size + 2, n_networks=2, extra_p2p=2, seed=seed
        )
    cache = DeviceGraphCache(capacity=4, max_delta_depth=1 + data[3] % 5)
    n_atoms = 64

    def check(g, t):
        """Device graph == final topology, row by row (multisets)."""
        in_src = np.asarray(g.in_src)
        in_cost = np.asarray(g.in_cost)
        in_valid = np.asarray(g.in_valid)
        words = np.asarray(g.direct_nh_words)
        for v in range(t.n_vertices):
            want = sorted(
                (int(s), int(c), int(a))
                for s, d, c, a in zip(
                    t.edge_src, t.edge_dst, t.edge_cost, t.edge_direct_atom
                )
                if d == v
            )
            got = []
            for k in np.nonzero(in_valid[v])[0]:
                bits = [
                    wi * 32 + b
                    for wi in range(words.shape[2])
                    for b in range(32)
                    if words[v, k, wi] >> np.uint32(b) & np.uint32(1)
                ]
                assert len(bits) <= 1, f"slot carries {len(bits)} atoms"
                got.append(
                    (int(in_src[v, k]), int(in_cost[v, k]),
                     bits[0] if bits else -1)
                )
            assert sorted(got) == want, f"row {v}: {sorted(got)} != {want}"

    g, _ = cache.get(topo, n_atoms)
    check(g, topo)
    cur = topo
    for b in data[4:24]:
        n, ne = cur.n_vertices, cur.n_edges
        op = b >> 6
        if op == 0 and ne:  # metric change
            nxt = clone(cur, cost={b % ne: 1 + b % 61})
        elif op == 1 and ne:  # drop one directed edge
            keep = np.ones(ne, bool)
            keep[b % ne] = False
            nxt = clone(cur, keep=keep)
        elif op == 2:  # add a directed edge (atom -1 or small)
            nxt = clone(
                cur, extra=[[b % n, (b // 7) % n, 1 + b % 31, b % 5 - 1]]
            )
        else:  # transit strike (overload bit): no diff form — direct delta
            v = b % n
            keep = cur.edge_src != v
            nxt = clone(cur, keep=keep)
            from holo_tpu.ops.graph import TopologyDelta  # noqa: PLC0415

            nxt.link_delta(
                TopologyDelta(
                    base_key=cur.cache_key,
                    overload=np.asarray([v], np.int32),
                    ids_stable=False,
                )
            )
            g, _ = cache.get(nxt, n_atoms)
            check(g, nxt)
            cur = nxt
            continue
        delta = diff_topologies(cur, nxt)
        if delta is not None:
            nxt.link_delta(delta)
        # Alternate mask-consumer lookups: stale-id entries must rebuild.
        g, _ = cache.get(nxt, n_atoms, need_edge_ids=bool(b & 0x20))
        check(g, nxt)
        cur = nxt


def multipath_invariants(data: bytes) -> None:
    """Multipath invariants (ISSUE 10; not a wire decoder): the scalar
    multipath oracle over arbitrary small topologies must produce
    next-hop sets and parent planes that are LOOP-FREE and
    WEIGHT-CONSISTENT:

    - parent sets are sorted by (path cost, parent id), carry no
      duplicates, and every parent satisfies the loop-free criterion
      (``dist[u] < dist[v]`` strictly, or it is an equal-cost DAG
      member with ``pdist == dist[v]``); path costs never undercut the
      shortest distance;
    - the ECMP members (``pdist == dist``) are exactly the DAG parent
      sources (truncated to the set width);
    - ``npaths`` satisfies the saturated DAG recursion and atoms with
      positive UCMP weight are a subset of the ECMP next-hop bitmask.

    The device kernel is pinned bit-identical to this oracle in
    tests/test_multipath.py, so oracle invariants are kernel
    invariants.  Violations raise AssertionError (reported as a crash).
    """
    if len(data) < 4:
        raise DecodeError("multipath spec: need 4+ bytes (kind,size,seed,k)")
    import numpy as np  # noqa: PLC0415

    from holo_tpu.ops.graph import INF, MP_SAT  # noqa: PLC0415
    from holo_tpu.spf import synth  # noqa: PLC0415
    from holo_tpu.spf.scalar import (  # noqa: PLC0415
        spf_multipath_reference,
    )

    kind, size, seed, kp = (
        data[0] % 3, 4 + data[1] % 6, data[2], 1 << (data[3] % 4)
    )
    if kind == 0:
        topo = synth.ring_topology(size, max_cost=3, seed=seed)
    elif kind == 1:
        topo = synth.grid_topology(2, size, max_cost=3, seed=seed)
    else:
        topo = synth.random_ospf_topology(
            n_routers=size + 2, n_networks=2, extra_p2p=size, max_cost=3,
            seed=seed,
        )
    base, mp = spf_multipath_reference(topo, kp)
    dist = base.dist
    n = topo.n_vertices
    inf, sat = int(INF), int(MP_SAT)

    dag_srcs: list[set] = [set() for _ in range(n)]
    np_sum = np.zeros(n, np.int64)
    for e in range(topo.n_edges):
        u, v = int(topo.edge_src[e]), int(topo.edge_dst[e])
        if (
            v != topo.root
            and int(dist[u]) < inf
            and int(dist[u]) + int(topo.edge_cost[e]) == int(dist[v])
        ):
            dag_srcs[v].add(u)
            np_sum[v] += int(mp.npaths[u])

    for v in range(n):
        if int(dist[v]) >= inf:
            assert int(mp.npaths[v]) == 0, f"npaths on unreachable {v}"
            continue
        # npaths: saturated DAG recursion over already-clamped values.
        want = 1 if v == topo.root else min(int(np_sum[v]), sat)
        assert int(mp.npaths[v]) == want, f"npaths[{v}]"
        row = [
            (int(mp.parents[v, j]), int(mp.pdist[v, j]))
            for j in range(kp)
            if int(mp.parents[v, j]) < n
        ]
        keys = [(c, u) for u, c in row]
        assert keys == sorted(keys), f"parent order {v}"
        assert len({u for u, _ in row}) == len(row), f"dup parent {v}"
        ecmp = {u for u, c in row if c == int(dist[v])}
        for u, c in row:
            assert c >= int(dist[v]), f"pathcost undercuts dist at {v}"
            assert u != v, f"self-parent {v}"
            assert (
                int(dist[u]) < int(dist[v]) or c == int(dist[v])
            ), f"loop-unsafe parent {u}->{v}"
        # ECMP members == DAG parent sources (modulo width truncation).
        if len(row) < kp:
            assert ecmp == dag_srcs[v], f"ecmp set {v}"
        else:
            assert ecmp <= dag_srcs[v], f"ecmp overreach {v}"
        # Weighted atoms are a subset of the ECMP next-hop bitmask.
        for a in range(mp.nh_weights.shape[1]):
            w = int(mp.nh_weights[v, a])
            assert 0 <= w <= sat, f"weight range {v},{a}"
            if w > 0:
                word = int(base.nexthop_words(64)[v, a // 32])
                assert word >> (a % 32) & 1, f"weighted atom {a} not in set"


def tropical_tile_invariants(data: bytes) -> None:
    """Tropical tile-plane invariants (ISSUE 13; not a wire decoder):
    the blocked min-plus marshal over arbitrary small topologies must
    produce planes that are (a) structurally sound — per row block,
    slot cb ascending with an all-INF sentinel tail, the pos grid a
    faithful inverse, every padded vertex row/column all-INF — (b)
    value-faithful — every valid ELL
    edge's tile entry equals the MIN cost over its parallel group,
    every entry with no edge INF — and (c) semantically exact — a
    host-side (numpy) min-plus fixpoint over the tiles reproduces the
    scalar oracle's distances bit-for-bit.  The device kernel consumes
    only these planes for its dist phase (parity pinned in
    tests/test_tropical.py), so marshal invariance is kernel
    invariance.  Violations raise AssertionError (a crash)."""
    if len(data) < 4:
        raise DecodeError("tropical spec: need 4+ bytes (kind,size,seed,b)")
    import numpy as np  # noqa: PLC0415

    from holo_tpu.ops.graph import INF, build_ell  # noqa: PLC0415
    from holo_tpu.ops.tropical import (  # noqa: PLC0415
        _BLOCKS,
        build_tiles_host,
    )
    from holo_tpu.spf import synth  # noqa: PLC0415
    from holo_tpu.spf.scalar import spf_reference  # noqa: PLC0415

    kind, size, seed = data[0] % 3, 4 + data[1] % 6, data[2]
    block = _BLOCKS[data[3] % len(_BLOCKS)] if data[3] % 2 else None
    if kind == 0:
        topo = synth.ring_topology(size, max_cost=4, seed=seed)
    elif kind == 1:
        topo = synth.grid_topology(2, size, max_cost=4, seed=seed)
    else:
        topo = synth.random_ospf_topology(
            n_routers=size + 2, n_networks=2, extra_p2p=size, max_cost=4,
            seed=seed,
        )
    ell = build_ell(topo)
    if block is not None and block < topo.n_vertices:
        block = None  # explicit blocks must cover the pow2 cap rule
    tt, meta = build_tiles_host(
        ell.in_src, ell.in_cost, ell.in_valid, block=block
    )
    nb, tm, b, _ = tt.tiles.shape
    n = topo.n_vertices
    assert nb * b >= n, "tile vertex space must cover the graph"
    assert meta["tm"] == tm and meta["block"] == b and meta["nb"] == nb
    # (a) structural: per row block, slot cb ascending with sentinel
    # tail; pos grid is the inverse map; sentinel slots all-INF.
    rows_, cols_ = np.nonzero(ell.in_valid)
    for r in range(nb):
        cbs = [int(c) for c in tt.cb[r]]
        real = [c for c in cbs if c < nb]
        assert real == sorted(real), "slot order"
        assert cbs[len(real):] == [nb] * (tm - len(real)), "sentinel tail"
        for s_, c in enumerate(real):
            assert int(tt.pos[r, c]) == s_, "pos inverse"
            assert int(meta["pos"][r, c]) == s_, "meta pos inverse"
        for s_ in range(len(real), tm):
            assert (tt.tiles[r, s_] == INF).all(), "sentinel slot not INF"
    # (b) value-faithful: dense expected matrix vs tile entries — in
    # the marshal's PERMUTED vertex space (ISSUE 15 RCM relabeling;
    # perm/inv must round-trip).
    perm, inv = meta["perm"], meta["inv"]
    assert np.array_equal(np.sort(perm), np.arange(n)), "perm bijection"
    assert np.array_equal(perm[inv], np.arange(n)), "inv inverse"
    want = np.full((nb * b, nb * b), INF, np.int64)
    srcs = ell.in_src[rows_, cols_]
    costs = ell.in_cost[rows_, cols_]
    np.minimum.at(want, (inv[rows_], inv[srcs]), costs)
    got = np.full((nb * b, nb * b), INF, np.int64)
    for r in range(nb):
        for s_ in range(tm):
            c = int(tt.cb[r, s_])
            if c < nb:
                got[r * b : (r + 1) * b, c * b : (c + 1) * b] = tt.tiles[
                    r, s_
                ]
    assert np.array_equal(got[:n, :n], want[:n, :n]), "tile values"
    # Padded vertex rows/cols (and uncovered block pairs) stay INF.
    assert (got[n:] == INF).all() and (got[:, n:] == INF).all(), (
        "pad sentinel rows/cols must be INF"
    )
    # (c) semantic: host min-plus fixpoint == scalar oracle distances
    # (fixpoint in permuted space; compared back through perm).
    dist = np.full(nb * b, INF, np.int64)
    dist[inv[topo.root]] = 0
    for _ in range(nb * b):
        cand = np.where(
            (got < INF) & (dist[None, :] < INF), got + dist[None, :], INF
        ).min(axis=1)
        new = np.minimum(dist, cand)
        if np.array_equal(new, dist):
            break
        dist = new
    ref = spf_reference(topo)
    assert np.array_equal(dist[inv], ref.dist.astype(np.int64)), (
        "tile fixpoint distances != scalar oracle"
    )


def partition_invariants(data: bytes) -> None:
    """Partitioned-SPF plan invariants (ISSUE 15; not a wire decoder):
    over arbitrary small topologies (ring/grid/random, optionally
    carrying a seeded native ``partition_hint``) the partition plan
    must be (a) an exact cover — dense non-empty partition ids, every
    vertex exactly one own row, local ids bijective — (b) boundary-
    closed — both endpoints of every cut edge (plus the root) are
    skeleton vertices, each partition's halo is exactly the external
    cut-edge sources into it — and (c) stitch-exact — a host
    intra-partition Dijkstra per boundary vertex builds the contracted
    skeleton's edge weights, and :func:`skeleton_solve` over that
    skeleton reproduces the scalar oracle's global distances at every
    skeleton vertex bit-for-bit (the contraction-exactness argument the
    device path inherits).  Violations raise AssertionError (a crash)."""
    if len(data) < 4:
        raise DecodeError("partition spec: need 4+ bytes (kind,size,seed,p)")
    import heapq  # noqa: PLC0415

    import numpy as np  # noqa: PLC0415

    from holo_tpu.ops.graph import INF  # noqa: PLC0415
    from holo_tpu.ops.partition import (  # noqa: PLC0415
        build_plan,
        skeleton_solve,
    )
    from holo_tpu.spf import synth  # noqa: PLC0415
    from holo_tpu.spf.scalar import spf_reference  # noqa: PLC0415

    kind, size, seed = data[0] % 3, 4 + data[1] % 8, data[2]
    if kind == 0:
        topo = synth.ring_topology(size, max_cost=4, seed=seed)
    elif kind == 1:
        topo = synth.grid_topology(2, size, max_cost=4, seed=seed)
    else:
        topo = synth.random_ospf_topology(
            n_routers=size + 2, n_networks=2, extra_p2p=size, max_cost=4,
            seed=seed,
        )
    n = topo.n_vertices
    if data[3] % 4 == 0:
        # Native-hint arm: a seeded grouping stamped the way the
        # protocol seams do (apply_partition_hint semantics).
        rng = np.random.default_rng(seed)
        topo.partition_hint = rng.integers(
            0, 2 + data[3] % 3, n, dtype=np.int32
        )
        plan = build_plan(topo)
    else:
        plan = build_plan(topo, max_part=max(2, n // (2 + data[3] % 3)))

    # (a) exact cover.
    part = plan.part_of
    assert part.min() >= 0 and part.max() == plan.n_parts - 1, "dense ids"
    assert np.all(np.bincount(part, minlength=plan.n_parts) > 0), (
        "empty partition id"
    )
    allv = np.sort(np.concatenate(plan.verts))
    assert np.array_equal(allv, np.arange(n)), "verts not an exact cover"
    for p in range(plan.n_parts):
        assert np.array_equal(part[plan.verts[p]], np.full(
            plan.verts[p].shape[0], p
        )), "verts/part_of disagree"
        assert np.array_equal(
            plan.local_of[plan.verts[p]],
            np.arange(plan.verts[p].shape[0]),
        ), "local ids not bijective"

    # (b) boundary closure.
    cutm = part[topo.edge_src] != part[topo.edge_dst]
    assert np.array_equal(
        np.sort(plan.cut_eid), np.nonzero(cutm)[0]
    ), "cut edge set"
    skel_set = set(plan.skel.tolist())
    assert int(topo.root) in skel_set, "root not in skeleton"
    for e in plan.cut_eid:
        assert int(topo.edge_src[e]) in skel_set, "cut src outside skel"
        assert int(topo.edge_dst[e]) in skel_set, "cut dst outside skel"
    for p in range(plan.n_parts):
        want_halo = np.unique(
            topo.edge_src[plan.cut_eid][
                part[topo.edge_dst[plan.cut_eid]] == p
            ]
        )
        assert np.array_equal(plan.halo[p], want_halo), "halo set"
        assert np.array_equal(
            plan.bnd[p], plan.skel[part[plan.skel] == p]
        ), "bnd set"

    # (c) skeleton weights from host intra-partition Dijkstras, then
    # the stitch reproduces the oracle's global skeleton distances.
    btab = np.full(
        (plan.n_parts, plan.b_pad, plan.b_pad), int(INF), np.int64
    )
    for p in range(plan.n_parts):
        intra = np.nonzero(
            (part[topo.edge_src] == p) & (part[topo.edge_dst] == p)
        )[0]
        adj: dict[int, list] = {}
        for e in intra:
            adj.setdefault(int(topo.edge_src[e]), []).append(
                (int(topo.edge_dst[e]), int(topo.edge_cost[e]))
            )
        for i, s in enumerate(plan.bnd[p]):
            dist = {int(s): 0}
            heap = [(0, int(s))]
            while heap:
                d, v = heapq.heappop(heap)
                if d > dist.get(v, int(INF)):
                    continue
                for u, wgt in adj.get(v, ()):
                    nd = d + wgt
                    if nd < dist.get(u, int(INF)):
                        dist[u] = nd
                        heapq.heappush(heap, (nd, u))
            for j, t in enumerate(plan.bnd[p]):
                btab[p, i, j] = dist.get(int(t), int(INF))
    skel_dist = skeleton_solve(plan, btab)
    ref = spf_reference(topo)
    want = np.minimum(
        ref.dist[plan.skel].astype(np.int64), int(INF)
    )
    assert np.array_equal(np.minimum(skel_dist, int(INF)), want), (
        "skeleton stitch != scalar oracle at skeleton vertices"
    )


def bgp_table_invariants(data: bytes) -> None:
    """Device BGP table invariants (ISSUE 16; not a wire decoder): over
    arbitrary small Adj-RIB-In tables the device fold must satisfy
    (a) eligibility ⊆ occupancy, (b) the winning column is the scalar
    oracle's best path (which, whenever the conditional MED rung never
    fires, is exactly the min packed sort key among eligible columns),
    and (c) the device multipath selection is a ⊆ of the equal-key set,
    capped at max_paths — all checked against the verbatim scalar
    decision process on an identical table.  Violations raise
    AssertionError (a crash)."""
    if len(data) < 6:
        raise DecodeError("bgp table spec: need 6+ bytes")
    from holo_tpu.ops.bgp_table import TpuBgpTableBackend  # noqa: PLC0415
    from holo_tpu.protocols.bgp_engine import (  # noqa: PLC0415
        AdjRib,
        AsSegment,
        BaseAttrs,
        BgpEngine,
        Destination,
        NhtEntry,
        Route,
        RouteOrigin,
    )

    n_prefixes = 1 + data[0] % 4
    n_peers = 1 + data[1] % 3
    mp_byte = data[2]
    need = 3 + n_prefixes * n_peers
    if len(data) < need:
        raise DecodeError(f"bgp table spec: need {need} bytes")
    mp_cfg = None
    if mp_byte & 1:
        mp_cfg = {
            "enabled": True,
            "ebgp_max": 1 + (mp_byte >> 1) % 3,
            "ibgp_max": 1 + (mp_byte >> 3) % 3,
            "allow_multiple_as": bool(mp_byte & 0x20),
        }

    def build(backend):
        eng = BgpEngine("fuzz", table_backend=backend)
        eng.asn = 65000
        if mp_cfg:
            eng.multipath["ipv4-unicast"] = dict(mp_cfg)
        table = eng.tables["ipv4-unicast"]
        for addr, metric in (("9.9.9.1", 10), ("9.9.9.2", None)):
            table.nht[addr] = NhtEntry(metric=metric)
        k = 3
        for pi in range(n_prefixes):
            prefix = f"10.0.{pi}.0/24"
            for qi in range(n_peers):
                b = data[k]
                k += 1
                if not b & 1:
                    continue  # empty cell
                addr = f"1.1.1.{qi + 1}"
                path = (65000,) if b & 2 else (100 + (b >> 2) % 2,)
                attrs = BaseAttrs(
                    origin=("Igp", "Egp", "Incomplete")[(b >> 3) % 3],
                    as_path=(AsSegment("Sequence", path),),
                    nexthop="9.9.9.1" if b & 0x40 else "9.9.9.2",
                    med=None if b & 0x80 else (b >> 2) % 4,
                    local_pref=None if b & 0x10 else 100 + (b % 8),
                )
                dest = table.prefixes.setdefault(prefix, Destination())
                adj = dest.adj_rib.setdefault(addr, AdjRib())
                adj.in_post = Route(
                    origin=RouteOrigin(
                        identifier=f"0.0.0.{1 + (b >> 5) % 2}",
                        remote_addr=addr,
                    ),
                    attrs=attrs,
                    route_type="External" if b & 4 else "Internal",
                )
                table.queued.add(prefix)
                if backend is not None:
                    backend.note_route_change("ipv4-unicast", prefix)
        return eng

    scalar = build(None)
    backend = TpuBgpTableBackend()
    device = build(backend)
    scalar.run_decision_process()
    device.run_decision_process()

    st, dt = scalar.tables["ipv4-unicast"], device.tables["ipv4-unicast"]
    assert set(st.prefixes) == set(dt.prefixes), "pruned prefix sets differ"
    for prefix, sdest in st.prefixes.items():
        ddest = dt.prefixes[prefix]
        s_best = (
            None
            if sdest.local is None
            else (sdest.local.attrs, sdest.local.route_type)
        )
        d_best = (
            None
            if ddest.local is None
            else (ddest.local.attrs, ddest.local.route_type)
        )
        assert s_best == d_best, f"best path diverged at {prefix}"
        assert sdest.local_nexthops == ddest.local_nexthops, (
            f"multipath set diverged at {prefix}"
        )

    batch = backend._batch.get("ipv4-unicast")
    if batch:
        devtab = backend._tables["ipv4-unicast"]
        for prefix, (best_col, _reasons, elig, mp_sel) in batch.items():
            dest = dt.prefixes.get(prefix)
            occ = {0} if dest is not None and dest.redistribute else set()
            if dest is not None:
                occ |= {
                    devtab.cols[a]
                    for a, adj in dest.adj_rib.items()
                    if adj.in_post is not None
                }
            elig_cols = {int(c) for c in range(len(elig)) if elig[c]}
            assert elig_cols <= occ, "eligibility outside occupancy"
            assert (best_col >= 0) == bool(elig_cols), "winner vs eligibility"
            if best_col >= 0:
                assert best_col in elig_cols, "winner not eligible"
            sel = {int(c) for c in range(len(mp_sel)) if mp_sel[c]}
            assert sel <= elig_cols, "multipath outside eligible set"
            if mp_cfg:
                cap = max(mp_cfg["ebgp_max"], mp_cfg["ibgp_max"])
                assert len(sel) <= cap, "multipath wider than max_paths"


# ===== target registry (the reference's fuzz_targets/** inventory) =====


def _seed_corpus():
    """Valid wire messages per protocol — reuses the regression corpus."""
    from tests.test_fuzz_decoders import corpus  # noqa: PLC0415

    return corpus()


def targets() -> dict:
    """name -> (decode_fn, seed_filter) — ≥31 targets mirroring
    fuzz/fuzz_targets/** (bfd, bgp message+attribute, isis, ldp, ospf
    v2+v3, rip, vrrp) plus igmp (ours)."""
    from holo_tpu.protocols import bfd, bgp, igmp, ldp, rip, vrrp
    from holo_tpu.protocols.isis import packet as isis_pkt
    from holo_tpu.protocols.ldp import packet as ldp_full
    from holo_tpu.protocols.ospf import packet as ospf_pkt
    from holo_tpu.protocols.ospf import packet_v3 as v3

    def ldp_pdu(data):
        try:
            return ldp_full.Pdu.decode(data)
        except ldp_full.DecodeError as e:
            raise DecodeError(str(e)) from e

    def bgp_body(cls):
        def run(data):
            return cls.decode_body(Reader(data))

        return run

    out = {
        # ospf/ (reference: 6 targets over v2+v3 packet/LSA)
        "ospfv2_packet_decode": ospf_pkt.Packet.decode,
        "ospfv2_lsa_decode": lambda b: ospf_pkt.Lsa.decode(Reader(b)),
        "ospfv2_router_info_decode": ospf_pkt.decode_router_info,
        "ospfv2_ext_prefix_decode": ospf_pkt.decode_ext_prefix_entries,
        "ospfv2_grace_tlvs_decode": ospf_pkt.decode_grace_tlvs,
        "ospfv2_ext_link_decode": ospf_pkt.decode_ext_link,
        "ospfv3_packet_decode": v3.Packet.decode,
        "ospfv3_lsa_decode": lambda b: v3.Lsa.decode(Reader(b)),
        # isis/ (reference: isis_pdu_decode; split by PDU class for
        # per-corpus guidance)
        "isis_pdu_decode": isis_pkt.decode_pdu,
        "isis_hello_decode": isis_pkt.decode_pdu,
        "isis_lsp_decode": isis_pkt.decode_pdu,
        "isis_snp_decode": isis_pkt.decode_pdu,
        # ldp/
        "ldp_msg_decode": ldp.LdpMsg.decode,
        "ldp_pdu_decode": ldp_pdu,
        # rip/
        "ripv2_pdu_decode": rip.RipPacket.decode,
        "ripng_pdu_decode": rip.RipngPacket.decode,
        # bfd/
        "bfd_packet_decode": bfd.BfdPacket.decode,
        # vrrp/
        "vrrphdr_ipv4_decode": lambda b: vrrp.VrrpPacket.decode(b, af=4),
        "vrrphdr_ipv6_decode": lambda b: vrrp.VrrpPacket.decode(b, af=6),
        # bgp/message + bgp/attribute
        "bgp_message_decode": bgp.decode_msg,
        "bgp_open_decode": bgp_body(bgp.OpenMsg),
        "bgp_update_decode": bgp_body(bgp.UpdateMsg),
        "bgp_notification_decode": bgp_body(bgp.NotificationMsg),
        "bgp_keepalive_decode": bgp_body(bgp.KeepaliveMsg),
        "bgp_attrs_decode": lambda b: bgp.PathAttrs.decode(Reader(b)),
        "bgp_ipv4_prefix_decode": lambda b: bgp._decode_prefixes(Reader(b)),
        "bgp_ipv6_prefix_decode": lambda b: bgp._decode_prefixes(
            Reader(b), v6=True
        ),
        # per-attribute decoders (reference: bgp/attribute/*_decode.rs)
        "bgp_aggregator_decode": lambda b: bgp.decode_aggregator(Reader(b)),
        "bgp_comm_decode": lambda b: bgp.decode_comm(Reader(b)),
        "bgp_ext_comm_decode": lambda b: bgp.decode_ext_comm(Reader(b)),
        "bgp_extv6_comm_decode": lambda b: bgp.decode_extv6_comm(Reader(b)),
        "bgp_large_comm_decode": lambda b: bgp.decode_large_comm(Reader(b)),
        "bgp_cluster_list_decode": lambda b: bgp.decode_cluster_list(
            Reader(b)
        ),
        "bgp_routerefresh_decode": bgp_body(bgp.RouteRefreshMsg),
        # igmp (no reference counterpart — ours has a kernel-facing decoder)
        "igmp_packet_decode": igmp.IgmpPacket.decode,
        # frr/ (ISSUE 1): padded-input invariants of the LFA kernel model.
        "frr_padding_invariants": frr_padding_invariants,
        # DeltaPath (ISSUE 7): device-resident graph delta-chain
        # invariants of the shared marshal cache.
        "delta_apply_invariants": delta_apply_invariants,
        # Multipath (ISSUE 10): loop-free + weight-consistent parent
        # set / UCMP planes of the multipath oracle.
        "multipath_invariants": multipath_invariants,
        # Tropical tiles (ISSUE 13): blocked min-plus marshal structure
        # + value faithfulness + fixpoint-vs-oracle distances.
        "tropical_tile_invariants": tropical_tile_invariants,
        # Partitioned SPF (ISSUE 15): exact partition cover, cut-closed
        # boundary/halo sets, skeleton-stitch exactness vs the oracle.
        "partition_invariants": partition_invariants,
        # Device BGP table (ISSUE 16): eligibility ⊆ occupancy, device
        # winner == scalar oracle best path, multipath ⊆ equal-key set.
        "bgp_table_invariants": bgp_table_invariants,
    }

    # Authenticated decode paths (r5): the auth framing (trailer
    # lengths, key ids, digests, LLS CA TLVs) is attacker-controlled
    # parsing that the unauthenticated targets never reach.
    from holo_tpu.utils.keychain import Key, Keychain

    _kc = Keychain("fuzz", [Key(1, "md5", b"fuzz-key"),
                            Key(2, "hmac-sha-256", b"fuzz-key-2")])
    _ospf_auth = ospf_pkt.AuthCtx(
        ospf_pkt.AuthType.CRYPTOGRAPHIC, keychain=_kc, clock=lambda: 1.0
    )
    _v3_auth = v3.AuthCtxV3(key=b"", keychain=_kc, clock=lambda: 1.0)
    _isis_auth = isis_pkt.AuthCtxIsis(
        key=b"", keychain=_kc, clock=lambda: 1.0
    )

    def _rip_lookup(key_id):
        k = _kc.key_lookup_accept(key_id, 1.0, mask=0xFF)
        return k.string if k is not None else None

    def _isis_auth_verify(data):
        t, pdu = isis_pkt.decode_pdu(data)
        tlvs = getattr(pdu, "tlvs", None)
        if isinstance(tlvs, dict):
            isis_pkt.verify_pdu_auth(data, tlvs, _isis_auth)
        return pdu

    out |= {
        "ospfv2_packet_decode_auth": lambda b: ospf_pkt.Packet.decode(
            b, auth=_ospf_auth
        ),
        "ospfv3_at_verify": lambda b: _v3_auth.verify(b[:32], b[32:]),
        "isis_pdu_auth_verify": _isis_auth_verify,
        "ripv2_pdu_decode_auth": lambda b: rip.RipPacket.decode(
            b, auth_key_lookup=_rip_lookup
        ),
    }
    return out


def run_all(budget_s: float = 0.5) -> dict[str, FuzzResult]:
    seeds = _seed_corpus()
    results = {}
    for name, fn in sorted(targets().items()):
        results[name] = fuzz_target(name, fn, seeds, budget_s=budget_s)
    return results


if __name__ == "__main__":
    budget = float(sys.argv[1]) if len(sys.argv) > 1 else 2.0
    total_crashes = 0
    for name, res in run_all(budget).items():
        status = "CRASH" if res.crashes else "ok"
        print(
            f"{name:28} {status:5} execs={res.executions:6} "
            f"cov={res.coverage:5} corpus={res.corpus_size}"
        )
        for exc, msg, hexdata in res.crashes[:3]:
            print(f"    {exc}: {msg}  input={hexdata[:80]}")
        total_crashes += len(res.crashes)
    sys.exit(1 if total_crashes else 0)

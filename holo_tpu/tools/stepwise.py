"""Stepwise conformance: replay the reference's per-step golden cases.

The reference ships ~86 OSPFv2 case directories (plus the topology
snapshots the round-1 harness consumes).  Each case runs ONE router of a
recorded topology to convergence, then applies numbered step inputs and
asserts the output planes (holo-protocol/src/test/stub/mod.rs:171-226,
320-429).  This engine does the same against OUR live instance:

- bring-up: replay the router's recorded ``events.jsonl`` through the
  real packet/FSM/flooding machinery (virtual clock frozen; the recorded
  ``SpfDelayEvent {DelayTimer}`` markers drive SPF exactly when the
  reference ran it, and recorded ISM timer events drive DR election);
- steps: ``NN-input-protocol.jsonl`` / ``NN-input-ibus.jsonl`` feed the
  instance; ``NN-output-protocol.jsonl`` is subset-compared against our
  transmitted packets (via refjson), and ``NN-output-northbound-state``'s
  ``local-rib`` plane is compared against our computed routes.

Cases touching constructs we don't model yet raise ``Unsupported`` and
are reported as skipped, not failed.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, ip_interface
from pathlib import Path

from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
    SpfFsmState,
    WaitTimerMsg,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.tools import refjson
from holo_tpu.tools.refjson import Unsupported
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import EventLoop, VirtualClock

OSPFV2_DIR = Path("/root/reference/holo-ospf/tests/conformance/ospfv2")


def case_map(conf_dir: Path = OSPFV2_DIR) -> dict[str, tuple[str, str]]:
    """case name -> (topology, router), parsed from the reference's test
    module (the run_test call sites)."""
    out = {}
    text = (conf_dir / "mod.rs").read_text()
    for m in re.finditer(
        r'run_test(?:_topology)?::<[^(]*\(\s*"([^"]+)",\s*"([^"]+)",\s*"([^"]+)"',
        text,
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


class _TxCapture(NetIo):
    def __init__(self):
        self.log = []  # (ifname, dst, bytes)

    def send(self, ifname, src, dst, data):
        self.log.append((ifname, dst, data))


@dataclass
class StepResult:
    step: str
    problems: list = field(default_factory=list)


class CaseRun:
    def __init__(self, topo_dir: Path, rt: str):
        self.loop = EventLoop(clock=VirtualClock())
        self.tx = _TxCapture()
        self.rt_dir = topo_dir / rt
        cfg = json.loads((self.rt_dir / "config.json").read_text())
        ospf = cfg["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-ospf:ospf"]
        self.notif_log: list = []
        self.inst = OspfInstance(
            name=f"step-{rt}",
            config=InstanceConfig(
                router_id=IPv4Address(ospf["explicit-router-id"])
            ),
            netio=self.tx,
            # Late-bound: drain_notifs() swaps the list object out.
            notif_cb=lambda n: self.notif_log.append(n),
        )
        self.inst.config.deterministic_dd = True
        self.inst.config.external_orig_checks = True
        # Last recorded lsa_body per (class, area, lsa-id) — cadence
        # tracking for the LsaOrigCheck replay (see apply_protocol).
        self._check_hist: dict[tuple, str] = {}
        # True while replaying the recorded bring-up stream (interface
        # up-transitions gate on recorded ISM positions); False during
        # steps (the ISM reacts to inputs directly).
        self.in_bring_up = True
        # The replay clock is frozen (recordings carry no timestamps), so
        # the RFC §13(5a) MinLSArrival throttle would reject every newer
        # copy of an LSA; the recording is the reference's own accepted
        # sequence, so arrival pacing is moot here.
        self.inst.config.min_ls_arrival = 0.0
        self.inst.config.preference = ospf.get("preference", {}).get("all", 110)
        self.loop.register(self.inst)
        # Capture the instance's real ibus route messages (the reference's
        # output-ibus plane).
        from holo_tpu.utils.ibus import Ibus

        self.ibus_log: list = []
        log = self.ibus_log

        class _IbusCapture:
            name = "rib-capture"

            def attach(self, loop_):
                pass

            def handle(self, msg):
                log.append(getattr(msg, "payload", msg))

        self.loop.register(_IbusCapture())
        self.inst.attach_ibus(Ibus(self.loop), routing_actor="rib-capture")
        # interface configs from the YANG config tree
        self.if_conf: dict[str, dict] = {}
        self.if_area: dict[str, IPv4Address] = {}
        self.area_conf: dict[IPv4Address, dict] = {}
        self.area_order: list[IPv4Address] = []
        for area in ospf.get("areas", {}).get("area", []):
            aid = IPv4Address(area["area-id"])
            self.area_conf[aid] = area
            self.area_order.append(aid)
            for iface in area.get("interfaces", {}).get("interface", []):
                self.if_conf[iface["name"]] = iface
                self.if_area[iface["name"]] = aid
        self.addrs: dict[str, list] = {}  # ifname -> [IPv4Interface]
        self.ifindexes: dict[str, int] = {}  # ifname -> kernel ifindex
        self.up: set[str] = set()
        # Interfaces fully provisioned (created + addressed + operative)
        # awaiting their recorded InterfaceStateChange position to come up.
        self.ready: set[str] = set()
        self._saw_state_change_evt = False
        # Reference arena-id mapping (observed from the recordings):
        # areas are keyed {"Id": n} with n = 1-based rank of the area-id
        # in ascending order; interfaces are keyed per area, 1-based over
        # the area's interfaces sorted by NAME (the reference's config
        # trees iterate BTreeMap order — 'lo' naturally sorts last).
        self.area_by_id = {
            i + 1: aid for i, aid in enumerate(sorted(self.area_conf, key=int))
        }
        self.iface_by_id: dict[tuple, str] = {}
        for aid, area in self.area_conf.items():
            names = sorted(
                i["name"]
                for i in area.get("interfaces", {}).get("interface", [])
            )
            for n, name in enumerate(names, start=1):
                self.iface_by_id[(aid, n)] = name

    # -- input application

    def _maybe_step_up(self, ifname: str) -> None:
        """Bring a ready-but-down interface up during the STEP phase.

        Bring-up replay instead gates up-transitions on the recorded
        InterfaceStateChange positions (see _ensure_iface), so this is a
        no-op while in_bring_up."""
        if (
            not self.in_bring_up
            and ifname in self.ready
            and ifname not in self.up
        ):
            self.up.add(ifname)
            self.loop.send(self.inst.name, IfUpMsg(ifname))
            self.loop.run_until_idle()

    def _ensure_iface(self, ifname: str) -> None:
        if ifname in self.up or ifname not in self.if_conf:
            return
        if self._find_iface(ifname) is not None:
            # Already created, currently down: ready to come back up at
            # the next recorded InterfaceStateChange position.
            self.ready.add(ifname)
            return
        addrs = self.addrs.get(ifname) or []
        if not addrs:
            return
        icfg = self.if_conf[ifname]
        aid = self.if_area[ifname]
        area = self.area_conf[aid]
        atype = area.get("area-type", "")
        loopback = ifname.startswith("lo")
        if_type = (
            IfType.POINT_TO_POINT
            if icfg.get("interface-type") == "point-to-point"
            else IfType.BROADCAST
        )
        addr = addrs[0]
        new_area = aid not in self.inst.areas
        self.inst.add_interface(
            ifname,
            IfConfig(
                area_id=aid,
                if_type=if_type,
                cost=icfg.get("cost", 10),
                hello_interval=icfg.get("hello-interval", 10),
                dead_interval=icfg.get("dead-interval", 40),
                priority=icfg.get("priority", 1),
                passive=icfg.get("passive", False) or loopback,
                loopback=loopback,
            ),
            addr.network,
            addr.ip,
            stub="stub-area" in atype,
            stub_default_cost=area.get("default-cost", 10),
            nssa="nssa" in atype,
        )
        if new_area:
            # AreaStart fires the RI-LSA origination check immediately in
            # the reference (its areas exist from config apply, before any
            # recorded event) — reproduce that at our lazy area creation.
            self.inst.flush_orig_checks("ri")
        got = self._find_iface(ifname)
        if got is not None and ifname in self.ifindexes:
            got.ifindex = self.ifindexes[ifname]
        if new_area:
            # Initial config snapshot applies at area creation only —
            # later config-change mutations must not be clobbered.
            self.inst.areas[aid].summary = area.get("summary", True)
        # The reference's ISM runs INLINE during southbound processing —
        # the recorded InterfaceStateChange position is only the (later)
        # dequeue of the origination event it raised.  Bring the
        # interface up here so packets recorded between the real ISM
        # transition and that position aren't dropped; LSA instance
        # cadence is driven separately by the recorded LsaOrigCheck
        # stream, so early origination cannot desynchronize it.
        self.ready.add(ifname)
        self.up.add(ifname)
        self.loop.send(self.inst.name, IfUpMsg(ifname))
        self.loop.run_until_idle()

    def _iface_by_key(self, key, area_key=None) -> str | None:
        if isinstance(key, dict):
            if "Value" in key:
                return key["Value"]
            if "Id" in key:
                aid = None
                if isinstance(area_key, dict):
                    if "Value" in area_key:
                        aid = IPv4Address(area_key["Value"])
                    elif "Id" in area_key:
                        aid = self.area_by_id.get(area_key["Id"])
                if aid is None and len(self.area_conf) == 1:
                    aid = next(iter(self.area_conf))
                return self.iface_by_id.get((aid, key["Id"]))
        return None

    def apply_ibus(self, ev: dict) -> None:
        if "InterfaceUpd" in ev:
            upd = ev["InterfaceUpd"]
            ifname = upd["ifname"]
            operative = "OPERATIVE" in (
                upd["flags"] if upd.get("flags") is not None else "OPERATIVE"
            )
            if not operative:
                if ifname in self.up:
                    from holo_tpu.protocols.ospf.instance import IfDownMsg

                    self.loop.send(self.inst.name, IfDownMsg(ifname))
                    self.loop.run_until_idle()
                    self.up.discard(ifname)
                return
            if upd.get("ifindex"):
                self.ifindexes[ifname] = upd["ifindex"]
            self._ensure_iface(ifname)
            iface = self._find_iface(ifname)
            if iface is not None:
                iface.ifindex = upd.get("ifindex", iface.ifindex)
                iface.config.mtu = upd.get("mtu", iface.config.mtu)
            # Step phase: the interface just became operative — the
            # reference's ISM brings it up directly (bring-up replay
            # instead gates on recorded InterfaceStateChange positions).
            self._maybe_step_up(ifname)
        elif "InterfaceAddressAdd" in ev:
            upd = ev["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            if addr.version != 4:
                return
            self.addrs.setdefault(upd["ifname"], []).append(addr)
            if upd["ifname"] in self.up:
                self.inst.interface_address_add(upd["ifname"], addr.network)
                self.loop.run_until_idle()
            else:
                self._ensure_iface(upd["ifname"])
                # Step inputs have no recorded InterfaceStateChange
                # positions — the reference's ISM reacts to the address
                # appearing, so bring the interface up immediately.
                self._maybe_step_up(upd["ifname"])
        elif "InterfaceAddressDel" in ev:
            upd = ev["InterfaceAddressDel"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.get(upd["ifname"]) or []
            if addr in lst:
                lst.remove(addr)
            ifname = upd["ifname"]
            iface = self._find_iface(ifname)
            if ifname in self.up and iface is not None:
                if iface.addr_ip == addr.ip:
                    # Primary address gone: the interface can no longer
                    # run OSPF (the kernel path would withdraw it).
                    from holo_tpu.protocols.ospf.instance import IfDownMsg

                    self.loop.send(self.inst.name, IfDownMsg(ifname))
                    self.loop.run_until_idle()
                    self.up.discard(ifname)
                else:
                    self.inst.interface_address_del(ifname, addr.network)
                    self.loop.run_until_idle()
        elif "HostnameUpdate" in ev:
            self.inst.set_hostname(ev["HostnameUpdate"])
            self.loop.run_until_idle()
        elif any(
            k in ev
            for k in (
                # No conformance topology configures redistribution, so the
                # reference instance receives-and-ignores these (as do we).
                "RouteRedistributeAdd",
                "RouteRedistributeDel",
                "RouterIdUpdate",
                "RouteIpAdd",
                "RouteIpDel",
                "RouteMplsAdd",
                "SrCfgUpd",
                "SrCfgEvent",
            )
        ):
            pass  # not consumed by our OSPF instance
        else:
            raise Unsupported(f"ibus {next(iter(ev))}")

    def _find_iface(self, ifname: str):
        for area in self.inst.areas.values():
            if ifname in area.interfaces:
                return area.interfaces[ifname]
        return None

    def apply_protocol(self, ev: dict) -> None:
        if "NetRxPacket" in ev:
            rx = ev["NetRxPacket"]
            ifname = self._iface_by_key(
                rx.get("iface_key"), rx.get("area_key")
            ) or rx.get("ifname")
            if ifname is None:
                raise Unsupported("unmapped iface key")
            src = IPv4Address(rx["src"]) if rx.get("src") else IPv4Address(0)
            dst = IPv4Address(rx["dst"]) if rx.get("dst") else IPv4Address(0)
            pkt_json = rx.get("packet", {})
            if "Err" in pkt_json or not pkt_json.get("Ok", pkt_json):
                # Decode-error cases: feed undecodable bytes so the rx
                # path raises + notifies exactly like the real wire would.
                data = b"\x02\x99\x00\x04"
            else:
                pkt = refjson.packet_from_json(pkt_json.get("Ok", pkt_json))
                data = pkt.encode()
            self.loop.send(
                self.inst.name, NetRxPacket(ifname, src, dst, data)
            )
            self.loop.run_until_idle()
        elif "SpfDelayEvent" in ev:
            from holo_tpu.protocols.ospf.instance import SpfFsmState

            sev = ev["SpfDelayEvent"].get("event")
            if sev == "DelayTimer":
                self.inst.run_spf()
                self.loop.run_until_idle()
            elif sev == "LearnTimer":
                # RFC 8405 transition 3 (spf.rs:372-377).
                if self.inst.spf_state == SpfFsmState.SHORT_WAIT:
                    self.inst.spf_state = SpfFsmState.LONG_WAIT
            elif sev == "HoldDownTimer":
                # Transitions 5/6: back to QUIET (spf.rs:402-418).
                self.inst._spf_holddown_fired()
            # "Igp" entries are the reference's own trigger messages; our
            # instance generates its own IGP events inline.
        elif "NsmEvent" in ev and ev["NsmEvent"].get("event") == "InactivityTimer":
            sub = ev["NsmEvent"]
            ifname = self._iface_by_key(sub.get("iface_key"), sub.get("area_key"))
            nbr_key = sub.get("nbr_key") or {}
            if not ifname or "Value" not in nbr_key:
                raise Unsupported("unmapped InactivityTimer keys")
            from holo_tpu.protocols.ospf.instance import InactivityTimerMsg

            self.loop.send(
                self.inst.name,
                InactivityTimerMsg(ifname, IPv4Address(nbr_key["Value"])),
            )
            self.loop.run_until_idle()
        elif "IsmEvent" in ev:
            sub = ev["IsmEvent"]
            if sub.get("event") == "WaitTimer":
                ifname = self._iface_by_key(
                    sub.get("iface_key"), sub.get("area_key")
                )
                if ifname:
                    self.loop.send(self.inst.name, WaitTimerMsg(ifname))
                    self.loop.run_until_idle()
        elif "LsaRefresh" in ev:
            key = self._lse_key(ev["LsaRefresh"])
            aid = self._lsdb_area(ev["LsaRefresh"])
            if key is None or aid is None:
                raise Unsupported("unmapped LsaRefresh key")
            self.inst.refresh_lsa(aid, key)
            self.loop.run_until_idle()
        elif "LsaFlush" in ev and ev["LsaFlush"].get("reason") == "Expiry":
            key = self._lse_key(ev["LsaFlush"])
            aid = self._lsdb_area(ev["LsaFlush"])
            if key is None or aid is None:
                raise Unsupported("unmapped LsaFlush key")
            area = self.inst.areas.get(aid)
            if area is not None:
                self.inst._flush_self_lsa(area, key)
            self.loop.run_until_idle()
        elif "GracePeriod" in ev:
            sub = ev["GracePeriod"]
            ifname = self._iface_by_key(
                sub.get("iface_key"), sub.get("area_key")
            )
            nbr_key = sub.get("nbr_key") or {}
            if not ifname or "Value" not in nbr_key:
                raise Unsupported("unmapped GracePeriod keys")
            from holo_tpu.protocols.ospf.neighbor import NsmEvent

            iface = self._find_iface(ifname)
            nbr_id = IPv4Address(nbr_key["Value"])
            if iface is not None and nbr_id in iface.neighbors:
                # Grace period timed out: the helper window closes
                # (events.rs:1486 helper_exit TimedOut) and the deferred
                # kill proceeds.
                nbr = iface.neighbors[nbr_id]
                aid = self.inst._if_area.get(ifname)
                area = self.inst.areas.get(aid)
                if nbr.gr_deadline is not None and area is not None:
                    self.inst.gr_helper_exit(area, iface, nbr, "timed-out")
                self.inst._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)
            self.loop.run_until_idle()
        elif "RxmtInterval" in ev and "Value" in (
            ev["RxmtInterval"].get("nbr_key") or {}
        ):
            sub = ev["RxmtInterval"]
            ifname = self._iface_by_key(
                sub.get("iface_key"), sub.get("area_key")
            )
            if ifname:
                self.inst._rxmt(
                    ifname, IPv4Address(sub["nbr_key"]["Value"])
                )
                self.loop.run_until_idle()
        elif "LsaOrigEvent" in ev and "InterfaceStateChange" in (
            ev["LsaOrigEvent"].get("event") or {}
        ):
            # The reference's ISM just ran an interface state transition:
            # any provisioned-but-down interface of ours comes up HERE.
            sub = ev["LsaOrigEvent"]["event"]["InterfaceStateChange"]
            aid = self.area_by_id.get(sub.get("area_id"))
            ifname = self.iface_by_id.get((aid, sub.get("iface_id")))
            if ifname and ifname in self.ready and ifname not in self.up:
                self.up.add(ifname)
                self.loop.send(self.inst.name, IfUpMsg(ifname))
                self.loop.run_until_idle()
        elif "LsaOrigCheck" in ev:
            # The reference's deferred originate_check position
            # (lsdb.rs:589-660).  The recorded check carries the body the
            # reference built: we use it only as CADENCE — a position
            # whose recorded body differs from the previous recorded body
            # of the same LSA is one where the reference bumped the
            # sequence number, so we rebuild (from OUR state) with a
            # forced bump; an unchanged position was a same-contents
            # no-op there and is skipped here.  Content never comes from
            # the recording.
            chk = ev["LsaOrigCheck"]
            body = chk.get("lsa_body", {})
            kind = next(iter(body), "")
            lsdb = (chk.get("lsdb_key") or {}).get("Area")
            aid = None
            if isinstance(lsdb, dict):
                if "Value" in lsdb:
                    aid = IPv4Address(lsdb["Value"])
                elif "Id" in lsdb:
                    aid = self.area_by_id.get(lsdb["Id"])
            kmap = {"Router": "router", "Network": "network",
                    "OpaqueArea": "ri"}
            if kind in kmap:
                hist_key = (kind, str(aid), chk.get("lsa_id"))
                rec = json.dumps(body, sort_keys=True)
                changed = self._check_hist.get(hist_key) != rec
                self._check_hist[hist_key] = rec
                if changed:
                    self.inst.flush_orig_checks(
                        kmap[kind], area_id=aid, force=True
                    )
            # Other recorded classes (SummaryNetwork, ...) originate via
            # the SPF/ABR machinery on their own triggers — draining the
            # deferred-check queue here would install router/network
            # checks early and desynchronize instance counts.
            self.loop.run_until_idle()
        elif any(
            k in ev
            for k in (
                "LsaOrigEvent",
                "SendLsUpdate",
                "DelayedAck",
                "NsmEvent",
                "RxmtInterval",
                "DbDescFree",
                "LsaFlush",
                "GraceSeqno",
            )
        ):
            pass  # internal plumbing our inline machinery covers
        else:
            raise Unsupported(f"protocol {next(iter(ev))}")

    @staticmethod
    def _lse_key(sub: dict):
        from holo_tpu.protocols.ospf.packet import LsaKey, LsaType

        val = (sub.get("lse_key") or {}).get("Value")
        if not isinstance(val, dict):
            return None
        try:
            return LsaKey(
                LsaType(val["lsa_type"]),
                IPv4Address(val["lsa_id"]),
                IPv4Address(val["adv_rtr"]),
            )
        except (KeyError, ValueError):
            return None

    @staticmethod
    def _lsdb_area(sub: dict):
        lsdb = (sub.get("lsdb_key") or {}).get("Area")
        if isinstance(lsdb, dict) and "Value" in lsdb:
            return IPv4Address(lsdb["Value"])
        return None

    def bring_up(self) -> None:
        self.in_bring_up = True
        for line in (self.rt_dir / "events.jsonl").read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])
        self.in_bring_up = False

    # -- step outputs

    def drain_tx(self) -> list[tuple[str, object, bytes]]:
        out = self.tx.log[:]
        self.tx.log.clear()
        return out

    def compare_protocol_output(self, expected_lines: list[dict]) -> list[str]:
        """Subset-match each expected tx message against ours (unordered,
        greedy matching)."""
        from holo_tpu.protocols.ospf.packet import Packet

        ours = []
        for ifname, dst, data in self.drain_tx():
            try:
                pkt = Packet.decode(data)
            except Exception as e:
                return [f"self-tx undecodable: {e}"]
            j = refjson.packet_to_json(pkt)
            ours.append({"ifname": ifname, "dst": str(dst), "pkt": j})
        problems = []
        # LS Updates are compared as (ifname, lsa) ITEMS, not packets: the
        # reference's debounced flood task coalesces/splits LSAs into
        # packets on timing, which is not semantics.  Other packet types
        # are compared whole.
        want_items = []  # (ifname|None, hdr-subset, lsa-or-packet json)
        got_items = []
        for exp in expected_lines:
            tx = exp.get("NetTxPacket")
            if tx is None:
                problems.append(f"unsupported output {next(iter(exp))}")
                continue
            pk = tx["packet"]
            if "LsUpdate" in pk:
                for lsa in pk["LsUpdate"]["lsas"]:
                    want_items.append(
                        (tx.get("ifname"), {"hdr": pk["LsUpdate"]["hdr"]}, lsa)
                    )
            else:
                want_items.append((tx.get("ifname"), None, pk))
        for got in ours:
            pk = got["pkt"]
            if "Hello" in pk:
                # The reference's testing build stubs the hello-interval
                # task (tasks.rs:383-386 `IntervalTask {}`), so recorded
                # outputs never contain hellos — ours aren't comparable.
                continue
            if "LsUpdate" in pk:
                for lsa in pk["LsUpdate"]["lsas"]:
                    got_items.append(
                        (got["ifname"], {"hdr": pk["LsUpdate"]["hdr"]}, lsa)
                    )
            else:
                got_items.append((got["ifname"], None, pk))

        def matches(w, g):
            wif, whdr, wpk = w
            gif, ghdr, gpk = g
            if wif is not None and wif != gif:
                return False
            if (whdr is None) != (ghdr is None):
                return False
            if whdr is not None and not refjson.subset_match(whdr, ghdr):
                return False
            return refjson.subset_match(wpk, gpk)

        # Bipartite match expected -> ours: greedy steals (an
        # under-specified expected grabbing the item a later, more
        # pinned-down expected needs) are undone by backtracking.
        cand = [
            [i for i, g in enumerate(got_items) if matches(w, g)]
            for w in want_items
        ]
        assign: dict[int, int] = {}  # got index -> want index

        def try_assign(w: int, seen: set) -> bool:
            for i in cand[w]:
                if i in seen:
                    continue
                seen.add(i)
                if i not in assign or try_assign(assign[i], seen):
                    assign[i] = w
                    return True
            return False

        for w, item in enumerate(want_items):
            if not try_assign(w, set()):
                problems.append(
                    "expected tx not sent: " + json.dumps(item[2])[:160]
                )
        # Two-sided: anything we transmitted that no recorded expectation
        # claims is a conformance violation too (stub/mod.rs:320-429
        # diffs the whole output plane, both directions).
        for i, item in enumerate(got_items):
            if i not in assign:
                problems.append(
                    "unexpected tx: " + json.dumps(item[2])[:160]
                )
        return problems

    def drain_ibus(self) -> list:
        out = self.ibus_log[:]
        self.ibus_log.clear()
        return out

    def compare_ibus(self, expected_lines: list[dict]) -> list[str]:
        """Compare expected RouteIpAdd/RouteIpDel against our captured
        ibus route messages (converted to the reference JSON shape)."""
        from holo_tpu.utils.southbound import RouteKeyMsg, RouteMsg

        def canon(msg: dict) -> dict:
            if "RouteIpAdd" in msg:
                m = dict(msg["RouteIpAdd"])
                m["nexthops"] = sorted(
                    (n for n in m.get("nexthops", [])),
                    key=lambda n: json.dumps(n, sort_keys=True),
                )
                return {"RouteIpAdd": m}
            return msg

        ours = []
        for m in self.drain_ibus():
            if isinstance(m, RouteMsg):
                ours.append(
                    canon(
                        {
                            "RouteIpAdd": {
                                "protocol": "ospfv2",
                                "prefix": str(m.prefix),
                                "distance": m.distance,
                                "metric": m.metric,
                                "tag": m.tag,
                                "nexthops": [
                                    {
                                        "Address": {
                                            "ifindex": nh.ifindex,
                                            "addr": str(nh.addr),
                                            "labels": list(nh.labels),
                                        }
                                    }
                                    for nh in m.nexthops
                                ],
                            }
                        }
                    )
                )
            elif isinstance(m, RouteKeyMsg):
                ours.append(
                    {
                        "RouteIpDel": {
                            "protocol": "ospfv2",
                            "prefix": str(m.prefix),
                        }
                    }
                )
        problems = []
        unmatched = list(ours)
        for exp in expected_lines:
            # Per-interface subscription bookkeeping has no analog in our
            # topic-filter ibus; skip those expectations.
            if any(k in exp for k in ("InterfaceSub", "InterfaceUnsub")):
                continue
            exp = canon(exp)
            hit = next(
                (
                    i
                    for i, got in enumerate(unmatched)
                    if refjson.subset_match(exp, got)
                ),
                None,
            )
            if hit is None:
                problems.append(
                    "expected ibus msg not sent: " + json.dumps(exp)[:140]
                )
            else:
                unmatched.pop(hit)
        # Two-sided: ibus messages we emitted that the reference didn't.
        for got in unmatched:
            problems.append("unexpected ibus msg: " + json.dumps(got)[:140])
        return problems

    # -- northbound config-change / RPC inputs

    def apply_rpc(self, rpc: dict) -> None:
        if "ietf-ospf:clear-neighbor" in rpc:
            self.inst.clear_neighbors(
                ifname=rpc["ietf-ospf:clear-neighbor"].get("interface")
            )
        elif "ietf-ospf:clear-database" in rpc:
            self.inst.clear_database()
        else:
            raise Unsupported(f"rpc {next(iter(rpc))}")
        self.loop.run_until_idle()

    def apply_config_change(self, tree: dict) -> None:
        """Apply a recorded YANG config diff (yang:operation annotations).

        Every annotation must be consumed by a handler; anything else
        raises Unsupported so unmodeled config never fake-passes."""
        proto = tree["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]
        ospf = proto.get("ietf-ospf:ospf", {})
        unhandled: list[str] = []

        def op_of(node: dict, leaf: str | None = None):
            ann = node.get("@" + leaf if leaf else "@") or {}
            return ann.get("yang:operation")

        if op_of(ospf, "enabled") == "delete":
            raise Unsupported("enabled delete")
        if op_of(ospf, "enabled") == "replace":
            if ospf.get("enabled") is False:
                self.inst.shutdown_self()
            else:
                # Re-enable = full instance start: RI LSAs (AreaStart),
                # then every operationally-up interface comes back.
                self.inst.enabled = True
                for area in self.inst.areas.values():
                    self.inst._originate_router_info(area)
                for ifname in sorted(self.up):
                    self.inst.if_up(ifname)
        if op_of(ospf, "explicit-router-id") == "replace":
            self.inst.restart_with_router_id(
                IPv4Address(ospf["explicit-router-id"])
            )
        pref = ospf.get("preference", {})
        pref_kw = {}
        pref_all = None
        for leaf, kind in (
            ("all", None),
            ("intra-area", "intra"),
            ("inter-area", "inter"),
            ("internal", "internal"),
            ("external", "external"),
        ):
            op = op_of(pref, leaf)
            if op in ("replace", "create"):
                if kind is None:
                    pref_all = pref[leaf]
                else:
                    pref_kw[kind] = pref[leaf]
            elif op == "delete":
                raise Unsupported(f"preference {leaf} delete")
        if pref_all is not None or pref_kw:
            self.inst.set_preference(pref_all, **pref_kw)
        gr = ospf.get("graceful-restart", {})
        if op_of(gr, "helper-enabled") == "replace":
            self.inst.config.gr_helper_enabled = bool(gr["helper-enabled"])
            # Disabling the helper capability exits helper mode for every
            # restarting neighbor — the adjacency itself survives (it only
            # dies later on the inactivity timer); reference gr.rs:166-203
            # + configuration.rs GrHelperChange.
            if not gr["helper-enabled"]:
                for area in self.inst.areas.values():
                    for iface in area.interfaces.values():
                        for nbr in iface.neighbors.values():
                            if nbr.gr_deadline is not None:
                                self.inst.gr_helper_exit(
                                    area, iface, nbr, "topology-changed"
                                )
            for area in self.inst.areas.values():
                self.inst._originate_router_info(area)

        for area_node in ospf.get("areas", {}).get("area", []):
            aid = IPv4Address(area_node["area-id"])
            area = self.inst.areas.get(aid)
            if op_of(area_node) == "delete":
                if area is not None:
                    deleted_ifnames = list(area.interfaces)
                    for ifname in deleted_ifnames:
                        from holo_tpu.protocols.ospf.instance import IfDownMsg

                        self.loop.send(self.inst.name, IfDownMsg(ifname))
                        self.loop.run_until_idle()
                        self.up.discard(ifname)
                        del area.interfaces[ifname]
                        self.inst._if_area.pop(ifname, None)
                    for key in list(area.lsdb.entries):
                        if key.adv_rtr == self.inst.config.router_id:
                            self.inst._flush_self_lsa(area, key)
                    del self.inst.areas[aid]
                    # ABR status may change: refresh remaining router LSAs.
                    for other in self.inst.areas.values():
                        self.inst._originate_router_lsa(other)
                    # Routes through the deleted area's interfaces are gone
                    # immediately (the reference uninstalls them with the
                    # area, before any SPF).
                    dead_ifs = set(deleted_ifnames)
                    old_routes = self.inst.routes
                    kept = {
                        p: r
                        for p, r in old_routes.items()
                        if getattr(r, "area_id", None) != aid
                        and not any(
                            nh.ifname in dead_ifs for nh in r.nexthops
                        )
                    }
                    self.inst.routes = kept
                    if self.inst.ibus is not None:
                        self.inst._sync_rib(old_routes, kept)
                continue
            if area is None:
                unhandled.append(f"area {aid} create")
                continue
            for leaf in ("default-cost", "summary"):
                if op_of(area_node, leaf) == "delete":
                    raise Unsupported(f"area {leaf} delete")
            if op_of(area_node, "default-cost") in ("replace", "create"):
                area.stub_default_cost = area_node["default-cost"]
            if op_of(area_node, "summary") in ("replace", "create"):
                area.summary = bool(area_node["summary"])
            for rng in (area_node.get("ranges") or {}).get("range", []):
                prefix = IPv4Network(rng["prefix"])
                if op_of(rng) == "delete":
                    area.ranges = [
                        r for r in area.ranges if r["prefix"] != prefix
                    ]
                else:  # create / modify (merge over the existing entry)
                    prev_rng = next(
                        (r for r in area.ranges if r["prefix"] == prefix),
                        {"advertise": True, "cost": None},
                    )
                    area.ranges = [
                        r for r in area.ranges if r["prefix"] != prefix
                    ] + [
                        {
                            "prefix": prefix,
                            "advertise": rng.get(
                                "advertise", prev_rng["advertise"]
                            ),
                            "cost": rng.get("cost", prev_rng["cost"]),
                        }
                    ]
            for if_node in (area_node.get("interfaces") or {}).get(
                "interface", []
            ):
                ifname = if_node["name"]
                iface = self._find_iface(ifname)
                if op_of(if_node) == "delete":
                    from holo_tpu.protocols.ospf.instance import IfDownMsg

                    self.loop.send(self.inst.name, IfDownMsg(ifname))
                    self.loop.run_until_idle()
                    self.up.discard(ifname)
                    if iface is not None:
                        area.interfaces.pop(ifname, None)
                        self.inst._if_area.pop(ifname, None)
                    self.if_conf.pop(ifname, None)
                    # Stale routes keep their entry but lose next hops
                    # through the deleted interface (unresolvable now).
                    for route in self.inst.routes.values():
                        route.nexthops = frozenset(
                            nh for nh in route.nexthops
                            if nh.ifname != ifname
                        )
                    continue
                if op_of(if_node) == "create":
                    self.if_conf[ifname] = if_node
                    self.if_area[ifname] = aid
                    self._ensure_iface(ifname)
                    continue
                if iface is None:
                    unhandled.append(f"iface {ifname} modify (absent)")
                    continue
                if op_of(if_node, "cost") == "delete":
                    raise Unsupported("iface cost delete")
                if op_of(if_node, "cost") in ("replace", "create"):
                    iface.config.cost = if_node["cost"]
                    self.inst._originate_router_lsa(area)
                ac_key = "ietf-ospf-anycast-flag:anycast-flag"
                if op_of(if_node, ac_key) in ("replace", "create"):
                    iface.config.anycast_flag = bool(if_node[ac_key])
                    self.inst.update_ext_prefix_flags()
                nf_key = "ietf-ospf-node-flag:node-flag"
                if op_of(if_node, nf_key) in ("replace", "create"):
                    iface.config.node_flag = bool(if_node[nf_key])
                    self.inst.update_ext_prefix_flags()
                for key in if_node:
                    if key.startswith("@") and key not in (
                        "@", "@cost", "@" + ac_key, "@" + nf_key,
                    ):
                        unhandled.append(f"iface leaf {key[1:]}")
            for key in area_node:
                if key.startswith("@") and key not in (
                    "@",
                    "@default-cost",
                    "@summary",
                ):
                    unhandled.append(f"area leaf {key[1:]}")
        for key in ospf:
            if key.startswith("@") and key not in (
                "@",
                "@enabled",
                "@explicit-router-id",
            ):
                unhandled.append(f"ospf leaf {key[1:]}")
        unhandled += [
            f"graceful-restart {k}"
            for k in gr
            if k.startswith("@") and k != "@helper-enabled"
        ]
        node_tags = ospf.get("node-tags")
        if node_tags is not None:
            tags = []
            ok = True
            for t in node_tags.get("node-tag", []):
                if op_of(t) in ("create", None, "replace"):
                    tags.append(t["tag"])
                elif op_of(t) == "delete":
                    pass
                else:
                    ok = False
            if ok:
                self.inst.set_node_tags(tuple(tags))
            else:
                unhandled.append("node-tags")
        pref_keys = [
            k
            for k in pref
            if k.startswith("@")
            and k
            not in ("@all", "@intra-area", "@inter-area", "@internal", "@external")
        ]
        unhandled += [f"preference {k}" for k in pref_keys]
        if unhandled:
            raise Unsupported("; ".join(sorted(set(unhandled))[:4]))
        # Summaries re-originate from the last SPF's inputs immediately;
        # routes themselves wait for the (recorded) SPF delay timer.
        self.inst.reoriginate_summaries()
        self.loop.run_until_idle()

    def drain_notifs(self) -> list:
        out, self.notif_log = self.notif_log, []
        return out

    def compare_notifs(self, expected_lines: list[dict]) -> list[str]:
        """Both-sided notification-plane compare (multiset, like the
        reference's assert_notifications)."""

        def canon(n: dict) -> str:
            kind, body = next(iter(n.items()))
            body = dict(body)
            # The recordings use the reference's instance name.
            body.pop("routing-protocol-name", None)
            return json.dumps({kind: body}, sort_keys=True)

        got = [canon(n) for n in self.drain_notifs()]
        problems = []
        for exp in expected_lines:
            c = canon(exp)
            if c in got:
                got.remove(c)
            else:
                problems.append(f"expected notif missing: {c[:180]}")
        for item in got:
            problems.append(f"unexpected notif: {item[:180]}")
        return problems

    def compare_state(self, state: dict) -> list[str]:
        """Full-tree compare: the recorded ietf-ospf state plane against
        our YANG-modeled operational state (both-sided, every leaf)."""
        from holo_tpu.protocols.ospf.nb_state import instance_state
        from holo_tpu.tools.treediff import tree_diff

        exp = state["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-ospf:ospf"]
        return tree_diff(exp, instance_state(self.inst), "ospf")


def run_case(case_dir: Path, topo: str, rt: str):
    """Returns (status, detail): status in {'pass','fail','skip'}."""
    run = CaseRun(OSPFV2_DIR / "topologies" / topo, rt)
    try:
        run.bring_up()
    except Unsupported as e:
        return "skip", f"bring-up: {e}"
    run.drain_tx()  # bring-up traffic is asserted by the topology harness

    steps = sorted(
        {f.name.split("-")[0] for f in case_dir.iterdir() if f.name[0].isdigit()}
    )
    problems = []
    for step in steps:
        run.drain_ibus()  # only this step's ibus traffic is asserted
        run.drain_notifs()  # likewise for notifications
        try:
            for kind in ("ibus", "protocol"):
                f = case_dir / f"{step}-input-{kind}.jsonl"
                if f.exists():
                    for line in f.read_text().splitlines():
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if kind == "ibus":
                            run.apply_ibus(ev)
                        else:
                            run.apply_protocol(ev)
            f = case_dir / f"{step}-input-northbound-config-change.json"
            if f.exists():
                run.apply_config_change(json.loads(f.read_text()))
            f = case_dir / f"{step}-input-northbound-rpc.json"
            if f.exists():
                run.apply_rpc(json.loads(f.read_text()))
            # End-of-step quiescence: the reference snapshots after its
            # internal queues drain, so any origination checks queued by
            # this step's triggers rebuild now.
            run.inst.flush_orig_checks()
            run.loop.run_until_idle()
        except Unsupported as e:
            return "skip", f"step {step}: {e}"
        # The reference recorder only writes a plane's file when it
        # emitted something — a MISSING file means "expected nothing",
        # so both-sided comparison still runs against an empty list.
        out_proto = case_dir / f"{step}-output-protocol.jsonl"
        expected = []
        if out_proto.exists():
            expected = [
                json.loads(l)
                for l in out_proto.read_text().splitlines()
                if l.strip()
            ]
        problems += [
            f"step {step}: {p}"
            for p in run.compare_protocol_output(expected)
        ]
        out_ibus = case_dir / f"{step}-output-ibus.jsonl"
        expected = []
        if out_ibus.exists():
            expected = [
                json.loads(l)
                for l in out_ibus.read_text().splitlines()
                if l.strip()
            ]
        problems += [
            f"step {step}: {p}" for p in run.compare_ibus(expected)
        ]
        out_notif = case_dir / f"{step}-output-northbound-notif.jsonl"
        expected_notifs = []
        if out_notif.exists():
            expected_notifs = [
                json.loads(l)
                for l in out_notif.read_text().splitlines()
                if l.strip()
            ]
        problems += [
            f"step {step}: {p}" for p in run.compare_notifs(expected_notifs)
        ]
        out_state = case_dir / f"{step}-output-northbound-state.json"
        if out_state.exists():
            state = json.loads(out_state.read_text())
            problems += [
                f"step {step}: {p}" for p in run.compare_state(state)
            ]
    return ("pass", "") if not problems else ("fail", "; ".join(problems[:6]))


def run_all(conf_dir: Path = OSPFV2_DIR):
    """Run every mapped case; returns {case: (status, detail)}."""
    results = {}
    for case, (topo, rt) in sorted(case_map(conf_dir).items()):
        case_dir = conf_dir / case
        if not case_dir.is_dir():
            continue
        try:
            results[case] = run_case(case_dir, topo, rt)
        except Exception as e:  # noqa: BLE001 — survey run must not die
            results[case] = ("fail", f"exception: {type(e).__name__}: {e}")
    return results

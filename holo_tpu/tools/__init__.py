"""Developer tools (reference: holo-tools + holo-replay, SURVEY.md §2.1).

``python -m holo_tpu.tools.cli <command>``:
  schema      — dump the management schema tree (yang_impls analog)
  coverage    — schema node counts per module (yang_coverage analog)
  validate    — validate a JSON config against the schema
  replay      — feed a recorded event file into a fresh OSPFv2 instance
                and print the resulting LSDB/routes (holo-replay analog)
"""

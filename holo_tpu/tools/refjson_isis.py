"""IS-IS PDU <-> reference-serde-JSON conversion.

The reference's conformance corpus records PDUs in its serde JSON shape
(holo-isis/src/packet/pdu.rs: Hello/Lsp/Snp with LspTlvs/HelloTlvs
containers; timing-dependent fields — seqno, checksum, remaining
lifetime — are skipped on serialization).  This module converts between
that shape and our packet objects in both directions:

- ``pdu_from_json``: step-input PDUs -> our objects (fed to the live
  instance exactly like the reference's testing stub feeds decoded
  PDUs);
- ``pdu_to_json``: our transmitted PDUs -> the reference shape, for
  subset comparison against ``NN-output-protocol.jsonl``.
"""

from __future__ import annotations

from ipaddress import IPv4Address, IPv4Network, IPv6Address, IPv6Network, ip_address, ip_network

from holo_tpu.protocols.isis.packet import (
    PREFIX_ATTR_N,
    PREFIX_ATTR_R,
    PREFIX_ATTR_X,
    AdjState3Way,
    ExtIpReach,
    ExtIsReach,
    HelloLan,
    HelloP2p,
    Lsp,
    LspId,
    P2pAdjState,
    PduType,
    Snp,
)
from holo_tpu.tools.refjson import Unsupported, subset_match  # noqa: F401

_LSP_FLAGS = [
    ("P", 0x80), ("ATT", 0x40), ("OL", 0x04),
    ("IS_TYPE2", 0x02), ("IS_TYPE1", 0x01),
]
_ATTR_FLAGS = [("X", PREFIX_ATTR_X), ("R", PREFIX_ATTR_R), ("N", PREFIX_ATTR_N)]
_SID_FLAGS = [("R", 0x80), ("N", 0x40), ("P", 0x20), ("E", 0x10),
              ("V", 0x08), ("L", 0x04)]
_ADJ_SID_FLAGS = [("F", 0x80), ("B", 0x40), ("V", 0x20), ("L", 0x10),
                  ("S", 0x08), ("P", 0x04)]


def _flags_str(value: int, table) -> str:
    return " | ".join(name for name, bit in table if value & bit)


def _flags_val(s: str, table) -> int:
    bits = dict(table)
    return sum(bits[p.strip()] for p in s.split("|") if p.strip())


def _lsp_id_json(lid: LspId) -> dict:
    return {
        "system_id": list(lid.sysid),
        "pseudonode": lid.pseudonode,
        "fragment": lid.fragment,
    }


def _lsp_id_from(j: dict) -> LspId:
    return LspId(bytes(j["system_id"]), j.get("pseudonode", 0), j.get("fragment", 0))


def _lan_id_json(lan_id: bytes) -> dict:
    return {"system_id": list(lan_id[:6]), "pseudonode": lan_id[6]}


def _lan_id_from(j: dict) -> bytes:
    return bytes(j["system_id"]) + bytes((j.get("pseudonode", 0),))


# -- reach entries

def _sub_tlvs_json(r: ExtIpReach) -> dict:
    out: dict = {}
    if r.attr_flags is not None:
        out["prefix_attr_flags"] = _flags_str(r.attr_flags, _ATTR_FLAGS)
    if r.src_rid4 is not None:
        out["ipv4_source_rid"] = str(r.src_rid4)
    if r.src_rid6 is not None:
        out["ipv6_source_rid"] = str(r.src_rid6)
    if r.sid_index is not None:
        sid = {"algo": "Spf", "sid": {"Index": r.sid_index}}
        flags = _flags_str(getattr(r, "sid_flags", 0), _SID_FLAGS)
        sid["flags"] = flags
        out["prefix_sids"] = {"Spf": sid}
    return out


def _sub_tlvs_from(j: dict) -> dict:
    out: dict = {}
    if "prefix_attr_flags" in j:
        out["attr_flags"] = _flags_val(j["prefix_attr_flags"], _ATTR_FLAGS)
    if "ipv4_source_rid" in j:
        out["src_rid4"] = IPv4Address(j["ipv4_source_rid"])
    if "ipv6_source_rid" in j:
        out["src_rid6"] = IPv6Address(j["ipv6_source_rid"])
    sids = j.get("prefix_sids") or {}
    spf = sids.get("Spf")
    if spf and "Index" in (spf.get("sid") or {}):
        out["sid_index"] = spf["sid"]["Index"]
        if spf.get("flags"):
            out["sid_flags"] = _flags_val(spf["flags"], _SID_FLAGS)
    return out


def _narrow_ip_json(entries, ext_tlv: bool = False) -> list:
    # In TLV 130 the whole TLV is external; its entries' I/E bit stays
    # clear (the reference only sets ie_bit inside TLV 128).
    return [
        {
            "list": [
                {
                    "up_down": r.up_down,
                    "ie_bit": False if ext_tlv else bool(r.external),
                    "metric": r.metric,
                    "prefix": str(r.prefix),
                }
                for r in entries
            ]
        }
    ] if entries else []


def _wide_v4_json(entries) -> list:
    return [
        {
            "list": [
                {
                    "metric": r.metric,
                    "up_down": r.up_down,
                    "prefix": str(r.prefix),
                    "sub_tlvs": _sub_tlvs_json(r),
                }
                for r in entries
            ]
        }
    ] if entries else []


def _v6_json(entries) -> list:
    return [
        {
            "list": [
                {
                    "metric": r.metric,
                    "up_down": r.up_down,
                    "external": r.external,
                    "prefix": str(r.prefix),
                    "sub_tlvs": _sub_tlvs_json(r),
                }
                for r in entries
            ]
        }
    ] if entries else []


def _narrow_is_json(entries) -> list:
    return [
        {
            "list": [
                {
                    "metric": r.metric,
                    "neighbor": _lan_id_json(r.neighbor),
                }
                for r in entries
            ]
        }
    ] if entries else []


def _is_sub_tlvs_json(r) -> dict:
    out: dict = {}
    if r.adj_sids:
        out["adj_sids"] = [
            {
                "flags": _flags_str(flags, _ADJ_SID_FLAGS),
                "weight": weight,
                "nbr_system_id": None,
                "sid": {"Label": label},
            }
            for flags, weight, label in r.adj_sids
        ]
    if r.link_msd:
        out["link_msd"] = {str(t): v for t, v in r.link_msd}
    return out


def _wide_is_json(entries) -> list:
    return [
        {
            "list": [
                {
                    "neighbor": _lan_id_json(r.neighbor),
                    "metric": r.metric,
                    "sub_tlvs": _is_sub_tlvs_json(r),
                }
                for r in entries
            ]
        }
    ] if entries else []


def _entries_of(tlv_list) -> list:
    """Flatten [{"list": [...]}, ...] TLV occurrences."""
    return [e for occ in tlv_list or [] for e in occ.get("list", [])]


def _reach_from(j: dict, v6: bool, narrow: bool) -> ExtIpReach:
    prefix = ip_network(j["prefix"], strict=False)
    kw = _sub_tlvs_from(j.get("sub_tlvs") or {})
    return ExtIpReach(
        prefix,
        j.get("metric", 0),
        up_down=j.get("up_down", False),
        external=j.get("external", j.get("ie_bit", False)),
        **kw,
    )


# -- TLV containers

def lsp_tlvs_to_json(tlvs: dict) -> dict:
    out: dict = {}
    if tlvs.get("protocols_supported") is not None:
        out["protocols_supported"] = {"list": list(tlvs["protocols_supported"])}
    if tlvs.get("area_addresses"):
        out["area_addrs"] = [{"list": [list(a) for a in tlvs["area_addresses"]]}]
    if tlvs.get("hostname"):
        out["hostname"] = {"hostname": tlvs["hostname"]}
    if tlvs.get("lsp_buf_size"):
        out["lsp_buf_size"] = {"size": tlvs["lsp_buf_size"]}
    if tlvs.get("purge_originator"):
        ids = tlvs["purge_originator"]
        out["purge_originator_id"] = {
            "system_id": list(ids[0]),
            "system_id_rcvd": list(ids[1]) if len(ids) > 1 else None,
        }
    if tlvs.get("narrow_is_reach"):
        out["is_reach"] = _narrow_is_json(tlvs["narrow_is_reach"])
    if tlvs.get("ext_is_reach"):
        out["ext_is_reach"] = _wide_is_json(tlvs["ext_is_reach"])
    if tlvs.get("ip_addresses"):
        out["ipv4_addrs"] = [{"list": [str(a) for a in tlvs["ip_addresses"]]}]
    if tlvs.get("narrow_ip_reach"):
        out["ipv4_internal_reach"] = _narrow_ip_json(tlvs["narrow_ip_reach"])
    if tlvs.get("narrow_ip_ext_reach"):
        out["ipv4_external_reach"] = _narrow_ip_json(
            tlvs["narrow_ip_ext_reach"], ext_tlv=True
        )
    if tlvs.get("ext_ip_reach"):
        out["ext_ipv4_reach"] = _wide_v4_json(tlvs["ext_ip_reach"])
    if tlvs.get("ipv6_addresses"):
        out["ipv6_addrs"] = [{"list": [str(a) for a in tlvs["ipv6_addresses"]]}]
    if tlvs.get("ipv6_reach"):
        out["ipv6_reach"] = _v6_json(tlvs["ipv6_reach"])
    if tlvs.get("mt_ipv6_reach"):
        out["mt_ipv6_reach"] = _v6_json([r for _mt, r in tlvs["mt_ipv6_reach"]])
    if tlvs.get("mt_is_reach"):
        out["mt_is_reach"] = _wide_is_json([r for _mt, r in tlvs["mt_is_reach"]])
    if tlvs.get("mt_ids"):
        out["multi_topology"] = [
            {
                "list": [
                    {
                        "flags": " | ".join(
                            n for n, c in (("O", ovl), ("A", att)) if c
                        ),
                        "mt_id": mt_id,
                    }
                    for mt_id, att, ovl in tlvs["mt_ids"]
                ]
            }
        ]
    if tlvs.get("ipv4_router_id") is not None:
        out["ipv4_router_id"] = str(tlvs["ipv4_router_id"])
    if tlvs.get("ipv6_router_id") is not None:
        out["ipv6_router_id"] = str(tlvs["ipv6_router_id"])
    if (
        tlvs.get("sr_cap")
        or tlvs.get("node_tags")
        or tlvs.get("node_msd")
        or tlvs.get("cap_router_id") is not None
    ):
        sub: dict = {}
        if tlvs.get("sr_cap"):
            base, rng = tlvs["sr_cap"]
            fl = tlvs.get("sr_cap_flags", 0xC0)
            names = [n for b, n in ((0x80, "I"), (0x40, "V")) if fl & b]
            sub["sr_cap"] = {
                "flags": " | ".join(names),
                "srgb_entries": [
                    {"range": rng, "first": {"Label": base}}
                ],
            }
            sub["sr_algo"] = [
                {0: "Spf", 1: "StrictSpf"}.get(a, "Spf")
                for a in (tlvs.get("sr_algos") or (0,))
            ]
        if tlvs.get("srlb"):
            base, rng = tlvs["srlb"]
            sub["srlb"] = {
                "entries": [{"range": rng, "first": {"Label": base}}]
            }
        if tlvs.get("node_tags"):
            sub["node_tags"] = [list(tlvs["node_tags"])]
        if tlvs.get("node_msd"):
            sub["node_msd"] = {
                str(t): v for t, v in sorted(tlvs["node_msd"].items())
            }
        cap = {"flags": "", "sub_tlvs": sub}
        rid = tlvs.get("cap_router_id")
        if rid is not None:
            cap["router_id"] = str(rid)
        out["router_cap"] = [cap]
    return out


def lsp_tlvs_from_json(j: dict) -> dict:
    tlvs: dict = {}
    if j.get("protocols_supported"):
        tlvs["protocols_supported"] = list(j["protocols_supported"]["list"])
    if j.get("area_addrs"):
        tlvs["area_addresses"] = [bytes(a) for a in _entries_of(j["area_addrs"])]
    if j.get("hostname"):
        tlvs["hostname"] = j["hostname"]["hostname"]
    if j.get("lsp_buf_size"):
        tlvs["lsp_buf_size"] = j["lsp_buf_size"]["size"]
    if j.get("purge_originator_id"):
        poi = j["purge_originator_id"]
        ids = [bytes(poi["system_id"])]
        if poi.get("system_id_rcvd"):
            ids.append(bytes(poi["system_id_rcvd"]))
        tlvs["purge_originator"] = ids
    if j.get("is_reach"):
        tlvs["narrow_is_reach"] = [
            ExtIsReach(_lan_id_from(e["neighbor"]), e.get("metric", 0))
            for e in _entries_of(j["is_reach"])
        ]
    if j.get("ext_is_reach"):
        tlvs["ext_is_reach"] = [
            ExtIsReach(_lan_id_from(e["neighbor"]), e.get("metric", 0))
            for e in _entries_of(j["ext_is_reach"])
        ]
    if j.get("mt_is_reach"):
        tlvs["mt_is_reach"] = [
            (e.get("mt_id", 2), ExtIsReach(_lan_id_from(e["neighbor"]), e.get("metric", 0)))
            for e in _entries_of(j["mt_is_reach"])
        ]
    if j.get("ipv4_addrs"):
        tlvs["ip_addresses"] = [
            IPv4Address(a) for a in _entries_of(j["ipv4_addrs"])
        ]
    if j.get("ipv4_internal_reach"):
        tlvs["narrow_ip_reach"] = [
            _reach_from(e, False, True)
            for e in _entries_of(j["ipv4_internal_reach"])
        ]
    if j.get("ipv4_external_reach"):
        tlvs["narrow_ip_ext_reach"] = [
            ExtIpReach(
                ip_network(e["prefix"], strict=False), e.get("metric", 0),
                up_down=e.get("up_down", False), external=True,
            )
            for e in _entries_of(j["ipv4_external_reach"])
        ]
    if j.get("ext_ipv4_reach"):
        tlvs["ext_ip_reach"] = [
            _reach_from(e, False, False)
            for e in _entries_of(j["ext_ipv4_reach"])
        ]
    if j.get("ipv6_addrs"):
        tlvs["ipv6_addresses"] = [
            IPv6Address(a) for a in _entries_of(j["ipv6_addrs"])
        ]
    if j.get("ipv6_reach"):
        tlvs["ipv6_reach"] = [
            _reach_from(e, True, False) for e in _entries_of(j["ipv6_reach"])
        ]
    if j.get("mt_ipv6_reach"):
        tlvs["mt_ipv6_reach"] = [
            (e.get("mt_id", 2), _reach_from(e, True, False))
            for e in _entries_of(j["mt_ipv6_reach"])
        ]
    if j.get("multi_topology"):
        tlvs["mt_ids"] = [
            (
                e.get("mt_id", 0),
                "A" in (e.get("flags") or ""),
                "O" in (e.get("flags") or ""),
            )
            for e in _entries_of(j["multi_topology"])
        ]
    if j.get("ipv4_router_id"):
        tlvs["ipv4_router_id"] = IPv4Address(j["ipv4_router_id"])
    if j.get("ipv6_router_id"):
        tlvs["ipv6_router_id"] = IPv6Address(j["ipv6_router_id"])
    if j.get("router_cap"):
        cap = j["router_cap"][0]
        if cap.get("router_id"):
            tlvs["cap_router_id"] = IPv4Address(cap["router_id"])
        sub = cap.get("sub_tlvs") or {}
        if sub.get("node_tags"):
            tlvs["node_tags"] = tuple(
                t for grp in sub["node_tags"] for t in grp
            )
        if sub.get("node_msd"):
            tlvs["node_msd"] = {
                int(t): v for t, v in sub["node_msd"].items()
            }
        sr = sub.get("sr_cap")
        if sr and sr.get("srgb_entries"):
            ent = sr["srgb_entries"][0]
            first = (ent.get("first") or ent.get("first_sid") or {}).get(
                "Label"
            )
            if first is not None:
                tlvs["sr_cap"] = (first, ent.get("range", 0))
            fl = 0
            for name in str(sr.get("flags", "I | V")).split("|"):
                fl |= {"I": 0x80, "V": 0x40}.get(name.strip(), 0)
            tlvs["sr_cap_flags"] = fl
        if sub.get("sr_algo"):
            tlvs["sr_algos"] = tuple(
                {"Spf": 0, "StrictSpf": 1}.get(a, 0)
                for a in sub["sr_algo"]
            )
        lb = sub.get("srlb")
        if lb and lb.get("entries"):
            ent = lb["entries"][0]
            first = (ent.get("first") or {}).get("Label")
            if first is not None:
                tlvs["srlb"] = (first, ent.get("range", 0))
    for key in j:
        if key not in (
            "protocols_supported", "area_addrs", "hostname", "lsp_buf_size",
            "purge_originator_id", "is_reach", "ext_is_reach", "mt_is_reach",
            "ipv4_addrs", "ipv4_internal_reach", "ipv4_external_reach",
            "ext_ipv4_reach", "ipv6_addrs", "ipv6_reach", "mt_ipv6_reach",
            "multi_topology", "router_cap", "ipv4_router_id",
            "ipv6_router_id", "unknown",
        ):
            raise Unsupported(f"lsp tlv {key}")
    return tlvs


def _snp_entries_json(entries) -> list:
    # Timing-dependent entry fields are skipped like the reference's
    # testing serde — except rem_lifetime when 0 (expiration cases).
    def one(lt, lid):
        out = {}
        if lt == 0:
            out["rem_lifetime"] = 0
        out["lsp_id"] = _lsp_id_json(lid)
        return out

    return [
        {"list": [one(lt, lid) for lt, lid, _seq, _ck in entries]}
    ] if entries else []


def _snp_entries_from(j) -> list:
    return [
        (
            e.get("rem_lifetime", 0),
            _lsp_id_from(e["lsp_id"]),
            e.get("seqno", 0),
            e.get("cksum", 0),
        )
        for e in _entries_of(j)
    ]


# -- PDU-level conversion

def flatten_tlv_occurrences(pdu_json: dict) -> dict:
    """Merge multi-occurrence TLV arrays ([{"list": [...]}, ...]) into a
    single occurrence.  Our decoder flattens repeated TLVs (chunk
    boundaries are wire artifacts), so expected PDUs canonicalize the
    same way before comparison."""
    out = json_deepcopy(pdu_json)
    for body in out.values():
        tlvs = body.get("tlvs") if isinstance(body, dict) else None
        if not isinstance(tlvs, dict):
            continue
        for key, val in tlvs.items():
            if (
                isinstance(val, list)
                and len(val) > 1
                and all(isinstance(o, dict) and "list" in o for o in val)
            ):
                tlvs[key] = [
                    {"list": [e for o in val for e in o["list"]]}
                ]
    return out


def json_deepcopy(x):
    import copy

    return copy.deepcopy(x)


_PDU_TYPE_NAMES = {
    PduType.HELLO_LAN_L1: "HelloLanL1",
    PduType.HELLO_LAN_L2: "HelloLanL2",
    PduType.HELLO_P2P: "HelloP2P",
    PduType.LSP_L1: "LspL1",
    PduType.LSP_L2: "LspL2",
    PduType.CSNP_L1: "CsnpL1",
    PduType.CSNP_L2: "CsnpL2",
    PduType.PSNP_L1: "PsnpL1",
    PduType.PSNP_L2: "PsnpL2",
}

_CIRCUIT_TYPES = {1: "L1", 2: "L2", 3: "All"}


def pdu_to_json(pdu) -> dict:
    """Our PDU object -> {"Lsp": ...} / {"Snp": ...} / {"Hello": ...}."""
    if isinstance(pdu, Lsp):
        t = PduType.LSP_L2 if pdu.level == 2 else PduType.LSP_L1
        out = {
            "hdr": {"pdu_type": _PDU_TYPE_NAMES[t], "max_area_addrs": 0},
            "lsp_id": _lsp_id_json(pdu.lsp_id),
            "flags": _flags_str(pdu.flags, _LSP_FLAGS),
            "tlvs": lsp_tlvs_to_json(pdu.tlvs),
        }
        if pdu.lifetime == 0:
            out["rem_lifetime"] = 0
        return {"Lsp": out}
    if isinstance(pdu, Snp):
        if pdu.complete:
            t = PduType.CSNP_L2 if pdu.level == 2 else PduType.CSNP_L1
            summary = [
                _lsp_id_json(pdu.start or LspId(b"\x00" * 6)),
                _lsp_id_json(pdu.end or LspId(b"\xff" * 6, 0xFF, 0xFF)),
            ]
        else:
            t = PduType.PSNP_L2 if pdu.level == 2 else PduType.PSNP_L1
            summary = None
        return {
            "Snp": {
                "hdr": {"pdu_type": _PDU_TYPE_NAMES[t], "max_area_addrs": 0},
                "source": {"system_id": list(pdu.sysid), "pseudonode": 0},
                "summary": summary,
                "tlvs": {"lsp_entries": _snp_entries_json(pdu.entries)},
            }
        }
    if isinstance(pdu, (HelloP2p, HelloLan)):
        tlvs: dict = {}
        if pdu.tlvs.get("protocols_supported"):
            tlvs["protocols_supported"] = {
                "list": list(pdu.tlvs["protocols_supported"])
            }
        if pdu.tlvs.get("area_addresses"):
            tlvs["area_addrs"] = [
                {"list": [list(a) for a in pdu.tlvs["area_addresses"]]}
            ]
        if pdu.tlvs.get("is_neighbors"):
            tlvs["neighbors"] = [
                {"list": [list(m) for m in pdu.tlvs["is_neighbors"]]}
            ]
        if pdu.tlvs.get("ip_addresses"):
            tlvs["ipv4_addrs"] = [
                {"list": [str(a) for a in pdu.tlvs["ip_addresses"]]}
            ]
        if pdu.tlvs.get("ipv6_addresses"):
            tlvs["ipv6_addrs"] = [
                {"list": [str(a) for a in pdu.tlvs["ipv6_addresses"]]}
            ]
        p2p = pdu.tlvs.get("p2p_adj")
        if p2p is not None:
            tw: dict = {
                "state": {0: "Up", 1: "Initializing", 2: "Down"}[int(p2p.state)],
                "local_circuit_id": p2p.ext_circuit_id,
            }
            if p2p.neighbor_sysid is not None:
                tw["neighbor_systemid"] = list(p2p.neighbor_sysid)
                tw["neighbor_circuit_id"] = p2p.neighbor_ext_circuit_id
            tlvs["three_way_adj"] = tw
        if isinstance(pdu, HelloLan):
            t = (
                PduType.HELLO_LAN_L2
                if pdu.level == 2
                else PduType.HELLO_LAN_L1
            )
            variant = {
                "Lan": {
                    "priority": pdu.priority,
                    "lan_id": _lan_id_json(pdu.lan_id),
                }
            }
        else:
            t = PduType.HELLO_P2P
            variant = {"P2P": {"local_circuit_id": pdu.local_circuit_id}}
        return {
            "Hello": {
                "hdr": {"pdu_type": _PDU_TYPE_NAMES[t], "max_area_addrs": 0},
                "circuit_type": _CIRCUIT_TYPES.get(pdu.circuit_type, "All"),
                "source": list(pdu.sysid),
                "holdtime": pdu.hold_time,
                "variant": variant,
                "tlvs": tlvs,
            }
        }
    raise Unsupported(f"pdu_to_json {type(pdu).__name__}")


def pdu_from_json(j: dict):
    """Reference JSON -> (PduType, our PDU object)."""
    if "Lsp" in j:
        sub = j["Lsp"]
        t = sub["hdr"]["pdu_type"]
        level = 2 if t == "LspL2" else 1
        lsp = Lsp(
            level=level,
            lifetime=sub.get("rem_lifetime", 0),
            lsp_id=_lsp_id_from(sub["lsp_id"]),
            seqno=sub.get("seqno", 0),
            flags=_flags_val(sub.get("flags", ""), _LSP_FLAGS),
            tlvs=lsp_tlvs_from_json(sub.get("tlvs") or {}),
        )
        recorded_cksum = sub.get("cksum")
        lsp.encode()  # fills raw + computes the real checksum
        if recorded_cksum is not None:
            # The recorded checksum drives §7.3.16 comparisons — INCLUDING
            # an explicit zero: the reference's testing build stores and
            # compares 0 as-is (RFC 3719 §7 validation is skipped), so a
            # later SNP naming the same zero checksum must not look like
            # LSP confusion.
            lsp.cksum = recorded_cksum
        pdu_type = PduType.LSP_L2 if level == 2 else PduType.LSP_L1
        return pdu_type, lsp
    if "Snp" in j:
        sub = j["Snp"]
        t = sub["hdr"]["pdu_type"]
        level = 2 if t.endswith("L2") else 1
        complete = t.startswith("Csnp")
        start = end = None
        if sub.get("summary"):
            start = _lsp_id_from(sub["summary"][0])
            end = _lsp_id_from(sub["summary"][1])
        jt = sub.get("tlvs") or {}
        entries = _snp_entries_from(jt.get("lsp_entries"))
        snp = Snp(
            level, complete, bytes(sub["source"]["system_id"]),
            entries, start, end,
        )
        esn = jt.get("ext_seqnum")
        if esn:
            snp.tlvs["ext_seqnum"] = (
                esn.get("session", 0), esn.get("packet", 0)
            )
        pdu_type = PduType[
            ("CSNP_" if complete else "PSNP_") + f"L{level}"
        ]
        return pdu_type, snp
    if "Hello" in j:
        sub = j["Hello"]
        t = sub["hdr"]["pdu_type"]
        ct = {"L1": 1, "L2": 2, "All": 3}[sub.get("circuit_type", "All")]
        tlvs: dict = {}
        jt = sub.get("tlvs") or {}
        if jt.get("protocols_supported"):
            tlvs["protocols_supported"] = list(jt["protocols_supported"]["list"])
        if jt.get("area_addrs"):
            tlvs["area_addresses"] = [
                bytes(a) for a in _entries_of(jt["area_addrs"])
            ]
        if jt.get("neighbors"):
            tlvs["is_neighbors"] = [
                bytes(m) for m in _entries_of(jt["neighbors"])
            ]
        if jt.get("ipv4_addrs"):
            tlvs["ip_addresses"] = [
                IPv4Address(a) for a in _entries_of(jt["ipv4_addrs"])
            ]
        if jt.get("ipv6_addrs"):
            tlvs["ipv6_addresses"] = [
                IPv6Address(a) for a in _entries_of(jt["ipv6_addrs"])
            ]
        esn = jt.get("ext_seqnum")
        if esn:
            tlvs["ext_seqnum"] = (
                esn.get("session", 0), esn.get("packet", 0)
            )
        tw = jt.get("three_way_adj")
        if tw is not None:
            tlvs["p2p_adj"] = P2pAdjState(
                {"Up": AdjState3Way.UP, "Initializing": AdjState3Way.INITIALIZING,
                 "Down": AdjState3Way.DOWN}[tw.get("state", "Down")],
                tw.get("local_circuit_id", 0),
                bytes(tw["neighbor_systemid"]) if tw.get("neighbor_systemid") else None,
                tw.get("neighbor_circuit_id"),
            )
        if t == "HelloP2P":
            hello = HelloP2p(
                ct, bytes(sub["source"]), sub.get("holdtime", 9),
                sub.get("variant", {}).get("P2P", {}).get("local_circuit_id", 0),
                tlvs,
            )
            return PduType.HELLO_P2P, hello
        level = 2 if t == "HelloLanL2" else 1
        lan = sub.get("variant", {}).get("Lan", {})
        hello = HelloLan(
            ct, bytes(sub["source"]), sub.get("holdtime", 9),
            lan.get("priority", 64),
            _lan_id_from(lan.get("lan_id", {"system_id": [0] * 6})),
            level, tlvs,
        )
        return (
            PduType.HELLO_LAN_L2 if level == 2 else PduType.HELLO_LAN_L1,
            hello,
        )
    raise Unsupported(f"pdu_from_json {next(iter(j), '?')}")

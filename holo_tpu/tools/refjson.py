"""OSPFv2 packet/LSA <-> reference-JSON mapping.

The reference's conformance corpus serializes packets with serde into a
JSON schema (decoded form; LSAs in step inputs/outputs carry hdr+body
JSON, not raw bytes).  This module maps that schema onto OUR packet
dataclasses in both directions:

- ``packet_from_json``: construct our Packet from a recorded input
  (holo-protocol/src/test/stub serialization of holo-ospf packets).
- ``packet_to_json``: serialize our tx packets into the same schema for
  subset-comparison against ``NN-output-protocol.jsonl``.

Field-name map follows the reference's serde output (holo-ospf
packet/mod.rs, packet/lsa.rs derives); flag sets serialize as " | "
joined names, addresses as dotted quads.
"""

from __future__ import annotations

from ipaddress import IPv4Address

from holo_tpu.protocols.ospf.packet import (
    DbDesc,
    DbDescFlags,
    Hello,
    Lsa,
    LsaAsExternal,
    LsaKey,
    LsaNetwork,
    LsaOpaque,
    LsaRouter,
    LsaSummary,
    LsaType,
    LsAck,
    LsRequest,
    LsUpdate,
    Options,
    Packet,
    RouterFlags,
    RouterLink,
    RouterLinkType,
    decode_grace_tlvs,
    encode_grace_tlvs,
)
from holo_tpu.utils.bytesbuf import Reader

_LINK_TYPES = {
    "PointToPoint": RouterLinkType.POINT_TO_POINT,
    "TransitNetwork": RouterLinkType.TRANSIT_NETWORK,
    "StubNetwork": RouterLinkType.STUB_NETWORK,
    "VirtualLink": RouterLinkType.VIRTUAL_LINK,
}
_LINK_NAMES = {v: k for k, v in _LINK_TYPES.items()}

_OPT_BITS = {
    "E": Options.E,
    "MC": Options.MC,
    "NP": Options.NP,
    "DC": Options.DC,
    "O": Options.O,
}
_RTR_BITS = {"B": RouterFlags.B, "E": RouterFlags.E, "V": RouterFlags.V}
_RI_BITS = {
    "GR": 0x80000000,
    "GR_HELPER": 0x40000000,
    "STUB_ROUTER": 0x20000000,
}
_DD_BITS = {"MS": DbDescFlags.MS, "M": DbDescFlags.M, "I": DbDescFlags.I}


class Unsupported(Exception):
    """JSON carries a construct our codecs don't model."""


def _flags_from_str(s: str | None, table) -> int:
    out = 0
    for part in (s or "").split("|"):
        part = part.strip()
        if part:
            bit = table.get(part)
            if bit is None:
                raise Unsupported(f"flag {part!r}")
            out |= int(bit)
    return out


def _flags_to_str(val, table) -> str:
    return " | ".join(
        name for name, bit in table.items() if int(val) & int(bit)
    )


def _a(s) -> IPv4Address:
    return IPv4Address(s)


def _signed32(v: int) -> int:
    return v - (1 << 32) if v >= (1 << 31) else v


# -- LSA bodies


def lsa_body_from_json(body: dict):
    if not isinstance(body, dict) or len(body) != 1:
        raise Unsupported(f"body {body!r}")
    ((kind, b),) = body.items()
    if kind == "Router":
        return LsaRouter(
            flags=RouterFlags(_flags_from_str(b.get("flags"), _RTR_BITS)),
            links=[
                RouterLink(
                    _LINK_TYPES[l["link_type"]],
                    _a(l["link_id"]),
                    _a(l["link_data"]),
                    l["metric"],
                )
                for l in b.get("links", [])
            ],
        )
    if kind == "Network":
        return LsaNetwork(
            mask=_a(b["mask"]),
            attached=[_a(x) for x in b.get("attached_rtrs", [])],
        )
    if kind in ("SummaryNetwork", "SummaryRouter"):
        return LsaSummary(mask=_a(b["mask"]), metric=b.get("metric", 0))
    if kind == "AsExternal":
        return LsaAsExternal(
            mask=_a(b["mask"]),
            e_bit="E" in (b.get("flags") or ""),
            metric=b.get("metric", 0),
            fwd_addr=_a(b.get("fwd_addr") or "0.0.0.0"),
            tag=b.get("tag", 0),
        )
    if kind == "OpaqueLink" and "Grace" in b:
        g = b["Grace"]
        return LsaOpaque(
            data=encode_grace_tlvs(
                g.get("grace_period", 0),
                g.get("gr_reason", 0),
                _a(g["addr"]) if g.get("addr") else None,
            )
        )
    if kind == "OpaqueArea" and "RouterInfo" in b:
        from holo_tpu.protocols.ospf.packet import encode_router_info

        ri = b["RouterInfo"]
        tags = tuple(
            t for grp in (ri.get("node_tags") or []) for t in grp.get("tags", [])
        )
        return LsaOpaque(
            data=encode_router_info(
                _flags_from_str(ri.get("info_caps"), _RI_BITS),
                (ri.get("info_hostname") or {}).get("hostname"),
                tags,
            )
        )
    raise Unsupported(f"LSA body {kind}")


def lsa_body_to_json(lsa: Lsa):
    body = lsa.body
    t = lsa.type
    if isinstance(body, LsaRouter):
        return {
            "Router": {
                "flags": _flags_to_str(body.flags, _RTR_BITS),
                "links": [
                    {
                        "link_type": _LINK_NAMES[l.link_type],
                        "link_id": str(l.id),
                        "link_data": str(l.data),
                        "metric": l.metric,
                    }
                    for l in body.links
                ],
            }
        }
    if isinstance(body, LsaNetwork):
        return {
            "Network": {
                "mask": str(body.mask),
                "attached_rtrs": [str(a) for a in body.attached],
            }
        }
    if isinstance(body, LsaSummary):
        kind = (
            "SummaryNetwork"
            if t == LsaType.SUMMARY_NETWORK
            else "SummaryRouter"
        )
        return {kind: {"mask": str(body.mask), "metric": body.metric}}
    if isinstance(body, LsaAsExternal):
        return {
            "AsExternal": {
                "flags": "E" if body.e_bit else "",
                "mask": str(body.mask),
                "metric": body.metric,
                "fwd_addr": str(body.fwd_addr) if int(body.fwd_addr) else None,
                "tag": body.tag,
            }
        }
    if isinstance(body, LsaOpaque) and t == LsaType.OPAQUE_LINK:
        g = decode_grace_tlvs(body.data)
        return {
            "OpaqueLink": {
                "Grace": {
                    "grace_period": g.get("grace_period", 0),
                    "gr_reason": g.get("reason", 0),
                    "addr": str(g["addr"]) if "addr" in g else None,
                }
            }
        }
    if isinstance(body, LsaOpaque) and t == LsaType.OPAQUE_AREA and (
        int(lsa.lsid) >> 24 == 7
    ):
        from holo_tpu.protocols.ospf.packet import decode_ext_prefix_entries

        _RT = {0: "Unspecified", 1: "IntraArea", 3: "InterArea",
               5: "AsExternal", 7: "NssaExternal"}
        _PF = {"A": 0x80, "N": 0x40, "AC": 0x10}
        prefixes = {}
        for prefix, rt, flags, sids in decode_ext_prefix_entries(body.data):
            prefixes[str(prefix)] = {
                "route_type": _RT.get(rt, "Unspecified"),
                "af": 0,
                "flags": _flags_to_str(flags, _PF),
                "prefix": str(prefix),
                "prefix_sids": {},
                "unknown_tlvs": [],
            }
        return {"OpaqueArea": {"ExtPrefix": {"prefixes": prefixes}}}
    if isinstance(body, LsaOpaque) and t == LsaType.OPAQUE_AREA and (
        int(lsa.lsid) >> 24 == 4
    ):
        from holo_tpu.protocols.ospf.packet import decode_router_info

        ri = decode_router_info(body.data)
        return {
            "OpaqueArea": {
                "RouterInfo": {
                    "info_caps": _flags_to_str(ri["info_caps"], _RI_BITS),
                    "info_hostname": (
                        {"hostname": ri["hostname"]} if ri["hostname"] else None
                    ),
                    "node_tags": (
                        [{"tags": list(ri["node_tags"])}]
                        if ri["node_tags"]
                        else []
                    ),
                    # TLVs we do not originate: present-but-empty in the
                    # reference's serde output, so emit the same shape.
                    "srgb": [],
                    "srlb": [],
                    "unknown_tlvs": [],
                }
            }
        }
    return {"Unknown": {}}


def lsa_hdr_to_json(lsa: Lsa) -> dict:
    return {
        "age": lsa.age,
        "options": _flags_to_str(lsa.options, _OPT_BITS),
        "lsa_type": int(lsa.type),
        "lsa_id": str(lsa.lsid),
        "adv_rtr": str(lsa.adv_rtr),
        "seq_no": lsa.seq_no & 0xFFFFFFFF,
        "length": lsa.length,
    }


def lsa_from_json(obj: dict) -> Lsa:
    if "raw" in obj:
        return Lsa.decode(Reader(bytes(obj["raw"])))
    hdr = obj["hdr"]
    body_json = obj.get("body")
    if isinstance(body_json, dict) and "Unknown" in body_json:
        # Unknown-type LSA (decode-robustness cases): synthesize the raw
        # header bytes; our decoder discards it by the length field.
        import struct

        raw = (
            struct.pack(
                ">HBB", hdr.get("age", 0),
                _flags_from_str(hdr.get("options"), _OPT_BITS),
                hdr["lsa_type"],
            )
            + _a(hdr["lsa_id"]).packed
            + _a(hdr["adv_rtr"]).packed
            + struct.pack(
                ">IHH", hdr.get("seq_no", 0x80000001) & 0xFFFFFFFF, 0,
                hdr.get("length", 20),
            )
        )
        # Keep the wire image self-consistent with the declared length so
        # the decoder's skip-by-length lands on the next LSA boundary.
        raw = raw.ljust(hdr.get("length", 20), b"\0")
        return Lsa(
            age=hdr.get("age", 0),
            options=Options(0),
            type=LsaType.ROUTER,  # placeholder; raw carries the real type
            lsid=_a(hdr["lsa_id"]),
            adv_rtr=_a(hdr["adv_rtr"]),
            seq_no=_signed32(hdr.get("seq_no", 0x80000001)),
            body=None,
            raw=raw,
        )
    lsa = Lsa(
        age=hdr.get("age", 0),
        options=Options(_flags_from_str(hdr.get("options"), _OPT_BITS)),
        type=LsaType(hdr["lsa_type"]),
        lsid=_a(hdr["lsa_id"]),
        adv_rtr=_a(hdr["adv_rtr"]),
        seq_no=_signed32(hdr.get("seq_no", 0x80000001)),
        body=lsa_body_from_json(obj.get("body")),
    )
    # Round-trip through our codec so length/checksum/raw are consistent.
    out = Lsa.decode(Reader(lsa.encode()))
    if "cksum" in hdr and hdr["cksum"] != out.cksum:
        # The recording carries a DELIBERATELY wrong checksum (validation
        # cases): reproduce the bad wire image instead of repairing it.
        raw = bytearray(out.raw)
        raw[16:18] = int(hdr["cksum"]).to_bytes(2, "big")
        out.raw = bytes(raw)
        out.cksum = int(hdr["cksum"])
    return out


def lsa_to_json(lsa: Lsa) -> dict:
    return {"hdr": lsa_hdr_to_json(lsa), "body": lsa_body_to_json(lsa)}


def _hdr_from_json(h: dict) -> Lsa:
    """Header-only LSA (DD / LS Ack lists)."""
    return Lsa(
        age=h.get("age", 0),
        options=Options(_flags_from_str(h.get("options"), _OPT_BITS)),
        type=LsaType(h["lsa_type"]),
        lsid=_a(h["lsa_id"]),
        adv_rtr=_a(h["adv_rtr"]),
        seq_no=_signed32(h.get("seq_no", 0x80000001)),
        body=None,
        cksum=h.get("cksum", 0),
        length=h.get("length", 20),
    )


# -- packets


def packet_from_json(obj: dict) -> Packet:
    ((kind, p),) = obj.items()
    hdr = p["hdr"]
    rid, aid = _a(hdr["router_id"]), _a(hdr["area_id"])
    if kind == "Hello":
        body = Hello(
            mask=_a(p.get("network_mask") or "0.0.0.0"),
            hello_interval=p.get("hello_interval", 10),
            options=Options(_flags_from_str(p.get("options"), _OPT_BITS)),
            priority=p.get("priority", 1),
            dead_interval=p.get("dead_interval", 40),
            dr=_a(p["dr"]) if p.get("dr") else IPv4Address(0),
            bdr=_a(p["bdr"]) if p.get("bdr") else IPv4Address(0),
            neighbors=[_a(x) for x in p.get("neighbors", [])],
        )
    elif kind == "DbDesc":
        body = DbDesc(
            mtu=p.get("mtu", 1500),
            options=Options(_flags_from_str(p.get("options"), _OPT_BITS)),
            flags=DbDescFlags(_flags_from_str(p.get("dd_flags"), _DD_BITS)),
            dd_seq_no=p.get("dd_seq_no", 0),
            lsa_headers=[_hdr_from_json(h) for h in p.get("lsa_hdrs", [])],
        )
    elif kind == "LsRequest":
        body = LsRequest(
            entries=[
                LsaKey(
                    LsaType(e["lsa_type"]), _a(e["lsa_id"]), _a(e["adv_rtr"])
                )
                for e in p.get("entries", [])
            ]
        )
    elif kind == "LsUpdate":
        body = LsUpdate(lsas=[lsa_from_json(l) for l in p.get("lsas", [])])
    elif kind == "LsAck":
        body = LsAck(
            lsa_headers=[_hdr_from_json(h) for h in p.get("lsa_hdrs", [])]
        )
    else:
        raise Unsupported(f"packet kind {kind}")
    return Packet(router_id=rid, area_id=aid, body=body)


_PKT_NAMES = {
    Hello: "Hello",
    DbDesc: "DbDesc",
    LsRequest: "LsRequest",
    LsUpdate: "LsUpdate",
    LsAck: "LsAck",
}


def packet_to_json(pkt: Packet) -> dict:
    body = pkt.body
    kind = _PKT_NAMES[type(body)]
    hdr = {
        "pkt_type": kind,
        "router_id": str(pkt.router_id),
        "area_id": str(pkt.area_id),
    }
    if isinstance(body, Hello):
        return {
            "Hello": {
                "hdr": hdr,
                "network_mask": str(body.mask),
                "hello_interval": body.hello_interval,
                "options": _flags_to_str(body.options, _OPT_BITS),
                "priority": body.priority,
                "dead_interval": body.dead_interval,
                "dr": str(body.dr) if int(body.dr) else None,
                "bdr": str(body.bdr) if int(body.bdr) else None,
                "neighbors": [str(n) for n in body.neighbors],
            }
        }
    if isinstance(body, DbDesc):
        return {
            "DbDesc": {
                "hdr": hdr,
                "mtu": body.mtu,
                "options": _flags_to_str(body.options, _OPT_BITS),
                "dd_flags": _flags_to_str(body.flags, _DD_BITS),
                "dd_seq_no": body.dd_seq_no,
                "lsa_hdrs": [lsa_hdr_to_json(h) for h in body.lsa_headers],
            }
        }
    if isinstance(body, LsRequest):
        return {
            "LsRequest": {
                "hdr": hdr,
                "entries": [
                    {
                        "lsa_type": int(e.type),
                        "adv_rtr": str(e.adv_rtr),
                        "lsa_id": str(e.lsid),
                    }
                    for e in body.entries
                ],
            }
        }
    if isinstance(body, LsUpdate):
        return {
            "LsUpdate": {"hdr": hdr, "lsas": [lsa_to_json(l) for l in body.lsas]}
        }
    return {
        "LsAck": {
            "hdr": hdr,
            "lsa_hdrs": [lsa_hdr_to_json(h) for h in body.lsa_headers],
        }
    }


def subset_match(expected, actual) -> bool:
    """True if every field ``expected`` pins down equals ``actual``'s.

    The corpus omits serde-default fields (age 0, null members...), so
    comparison is keyed on what the expected JSON actually contains.
    Lists must match element-wise at the same length; flag strings are
    order-insensitive.
    """
    if isinstance(expected, dict):
        if not isinstance(actual, dict):
            return False
        return all(
            k in actual and subset_match(v, actual[k])
            for k, v in expected.items()
            if v is not None
        )
    if isinstance(expected, list):
        return (
            isinstance(actual, list)
            and len(expected) == len(actual)
            and all(subset_match(e, a) for e, a in zip(expected, actual))
        )
    if isinstance(expected, str) and isinstance(actual, str):
        if "|" in expected or "|" in actual:
            return {p.strip() for p in expected.split("|") if p.strip()} == {
                p.strip() for p in actual.split("|") if p.strip()
            }
        return expected == actual
    return expected == actual

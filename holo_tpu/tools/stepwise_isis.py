"""IS-IS stepwise conformance: replay the reference's per-step cases.

Mirrors tools/stepwise.py (OSPFv2) for the ~79 IS-IS case directories
(holo-isis/tests/conformance): each case brings ONE recorded router to
convergence by replaying its events.jsonl through our live IsisInstance
(real adjacency FSM / flooding / SPF machinery), then applies the
numbered step inputs and asserts:

- the protocol-output plane (transmitted PDUs, via refjson_isis);
- the northbound-state planes we model: local-rib routes, the per-level
  LSP database id-set, and per-interface SRM/SSN flooding state;
- the ibus plane (RouteIpAdd/RouteIpDel derived from route diffs).

Level-all routers (two concurrent levels) are reported as skips for
now; 69/79 cases target single-level routers.
"""

from __future__ import annotations

import json
import re
from ipaddress import IPv4Address, ip_interface
from pathlib import Path

from holo_tpu.protocols.isis.instance import (
    AdjacencyState,
    HoldTimerMsg,
    IsisIfConfig,
    IsisInstance,
    IsisInterface,
    LanHoldTimerMsg,
    LspEntry,
)
from holo_tpu.protocols.isis.packet import Lsp, LspId, PduType, decode_pdu
from holo_tpu.tools import refjson_isis
from holo_tpu.tools.refjson import Unsupported
from holo_tpu.tools.refjson_isis import pdu_from_json, pdu_to_json, subset_match
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

ISIS_DIR = Path("/root/reference/holo-isis/tests/conformance")


def case_map(conf_dir: Path = ISIS_DIR) -> dict[str, tuple[str, str]]:
    out = {}
    text = (conf_dir / "mod.rs").read_text()
    for m in re.finditer(
        r'run_test(?:_topology)?::<[^(]*\(\s*"([^"]+)",\s*"([^"]+)",\s*"([^"]+)"',
        text,
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


class _TxCapture(NetIo):
    def __init__(self):
        self.log = []  # (ifname, dst, bytes)

    def send(self, ifname, src, dst, data):
        self.log.append((ifname, dst, data))


def _sysid_str(sysid: bytes) -> str:
    h = sysid.hex()
    return f"{h[0:4]}.{h[4:8]}.{h[8:12]}"


def _lsp_id_str(lid: LspId) -> str:
    return f"{_sysid_str(lid.sysid)}.{lid.pseudonode:02x}-{lid.fragment:02x}"


def _parse_area(s: str) -> bytes:
    return bytes.fromhex(s.replace(".", ""))


class CaseRun:
    def __init__(self, topo_dir: Path, rt: str):
        self.loop = EventLoop(clock=VirtualClock())
        self.tx = _TxCapture()
        self.rt_dir = topo_dir / rt
        cfg = json.loads((self.rt_dir / "config.json").read_text())
        proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-isis:isis"]
        lt = proto.get("level-type", "level-all")
        self.level_all = lt == "level-all"
        self.level = 1 if lt == "level-1" else 2
        mt = (proto.get("metric-type") or {}).get("value", "wide-only")
        metric_style = {
            "old-only": "narrow", "wide-only": "wide", "both": "both"
        }[mt]
        af_list = (proto.get("address-families") or {}).get(
            "address-family-list"
        )
        if af_list is None:
            afs = {"ipv4", "ipv6"}  # YANG default: both enabled
        else:
            afs = {
                af["address-family"]
                for af in af_list
                if af.get("enabled", True)
            }
        protocols = ([0xCC] if "ipv4" in afs else []) + (
            [0x8E] if "ipv6" in afs else []
        )
        self.afs = afs
        self.preference = (proto.get("preference") or {}).get(
            "default", {}
        ).get("value", 115)
        from ipaddress import ip_address

        terid = (proto.get("mpls") or {}).get("te-rid") or {}
        kw = dict(
            netio=self.tx,
            metric_style=metric_style,
            lsp_mtu=proto.get("lsp-mtu", 1492),
            protocols=protocols,
            te_rid4=(
                ip_address(terid["ipv4-router-id"])
                if terid.get("ipv4-router-id")
                else None
            ),
            te_rid6=(
                ip_address(terid["ipv6-router-id"])
                if terid.get("ipv6-router-id")
                else None
            ),
        )
        sysid = _parse_area(proto["system-id"])
        area = _parse_area(proto["area-address"][0])
        # Route-diff capture for the ibus plane.
        self.prev_routes: dict = {}
        self.ibus_log: list = []
        if self.level_all:
            from holo_tpu.protocols.isis.multi import IsisLevelAllInstance

            self.node = IsisLevelAllInstance(
                rt, sysid, area, route_cb=self._routes_changed, **kw
            )
            self.insts = list(self.node.instances())
            self.node.attach_loop(self.loop)
        else:
            inst = IsisInstance(
                name=rt, sysid=sysid, area=area, level=self.level, **kw
            )
            if lt == "level-1":
                inst.is_type = 0x01
            inst.route_cb = self._routes_changed
            self.node = inst
            self.insts = [inst]
            self.loop.register(inst)
        for inst in self.insts:
            # Reference `testing` feature: hello tasks are no-ops, so a
            # recorded case never expects a transmitted hello.
            inst.inline_hellos = False
        self.bfd_log: list = []  # ("reg"/"unreg", ifname, dst, cfg)
        for inst in self.insts:
            inst.hostname = rt
            inst.afs = set(afs)
            inst.deferred_origination = True
            inst.bfd_cb = (
                lambda op, ifname, dst, cfg: self.bfd_log.append(
                    (op, ifname, dst, cfg)
                )
            )
        # Interface config, keyed by name; arena ids are 1-based config
        # order (the reference's arena insertion order).
        self.if_conf: dict[str, dict] = {}
        self.if_order: list[str] = []
        for iface in proto.get("interfaces", {}).get("interface", []):
            self.if_conf[iface["name"]] = iface
            self.if_order.append(iface["name"])
        self.ifindex: dict[str, int] = {}
        self.mac: dict[str, bytes] = {}
        self.addrs: dict[str, list] = {}  # ifname -> [ip_interface]
        self.up: set[str] = set()

    # -- route diff -> ibus plane

    def _routes_changed(self, routes: dict) -> None:
        # The ibus feed carries the INSTALLABLE view (route.rs:285-301):
        # connected prefixes never install, summary discard routes do.
        src = self.node if self.level_all else self.inst
        if routes:  # an explicit {} means "instance down: flush all"
            routes = src.installable_routes()
        for prefix, (metric, nhs) in routes.items():
            old = self.prev_routes.get(prefix)
            if old != (metric, nhs):
                self.ibus_log.append(("add", prefix, metric, nhs))
        for prefix in self.prev_routes.keys() - routes.keys():
            # A more-specific covered by a CONFIGURED summary leaves the
            # table silently: the recorded planes (nb-config-summary2
            # step 3) uninstall only the summary route itself — the
            # reference's summary lifecycle owns that transition.
            if self.level_all and any(
                sp.version == prefix.version
                and prefix != sp
                and prefix.subnet_of(sp)
                for sp in self.node.summaries
            ):
                continue
            self.ibus_log.append(("del", prefix, None, None))
        self.prev_routes = dict(routes)

    def _remerge(self) -> None:
        """Refresh the merged route table after L2 re-origination (the
        active-summary discard routes live in the merge)."""
        if self.level_all:
            self.node._level_routes_changed({})

    @property
    def inst(self):
        """Single-level instance (back-compat); level-all callers use
        _by_level/insts."""
        return self.insts[0]

    def _by_level(self, sub: dict) -> list:
        """Instances addressed by an event's 'level' field (both when
        absent on a level-all router)."""
        lv = sub.get("level") if isinstance(sub, dict) else None
        if lv in ("L1", 1):
            want = 1
        elif lv in ("L2", 2):
            want = 2
        else:
            return list(self.insts)
        return [i for i in self.insts if i.level == want]

    # -- interface lifecycle

    def _iface_by_key(self, key) -> str | None:
        if isinstance(key, dict):
            if "Value" in key:
                return key["Value"]
            if "Id" in key:
                i = key["Id"] - 1
                if 0 <= i < len(self.if_order):
                    return self.if_order[i]
        return None

    def _ensure_iface(self, ifname: str) -> None:
        if ifname in self.up or ifname not in self.if_conf:
            return
        addrs = self.addrs.get(ifname) or []
        v4 = [a for a in addrs if a.version == 4]
        v6g = [a for a in addrs if a.version == 6 and not a.ip.is_link_local]
        v6ll = [a.ip for a in addrs if a.version == 6 and a.ip.is_link_local]
        icfg = self.if_conf[ifname]
        loopback = ifname.startswith("lo")
        if not v4 and not v6g and not loopback:
            return
        passive = icfg.get("passive", False) or loopback
        if not passive and not v4:
            # Non-passive circuits need at least a v4 address for our
            # transmit path; v6-only circuits come later.
            if not v6g and not v6ll:
                return
        circuit = (
            "p2p"
            if icfg.get("interface-type") == "point-to-point"
            else "broadcast"
        )
        hello_int = (icfg.get("hello-interval") or {}).get("value", 10)
        hold_mult = (icfg.get("hello-multiplier") or {}).get("value", 3)
        metric = (icfg.get("metric") or {}).get("value", 10)
        prio = (icfg.get("priority") or {}).get("value", 64)
        self.node.add_interface(
            ifname,
            IsisIfConfig(
                metric=metric,
                hello_interval=hello_int,
                hold_multiplier=hold_mult,
                level=self.level,
                circuit_type=circuit,
                priority=prio,
                passive=passive,
                loopback=loopback,
            ),
            v4[0].ip if v4 else IPv4Address(0),
            v4[0].network if v4 else None,
            addr6=v6ll[0] if v6ll else None,
            addrs4=v4,
            addrs6=v6g,
            mac=self.mac.get(ifname, b""),
            # The reference allocates circuit ids to BROADCAST circuits
            # only (interface.rs:198-205); p2p ids are informational.
            circuit_id=(
                1 + sum(
                    1 for i in self.inst.interfaces.values()
                    if i.is_lan and not i.config.passive
                )
                if circuit == "broadcast" and not passive
                else self.ifindex.get(ifname, 0)
            ),
        )
        self.up.add(ifname)
        self.node.if_up(ifname)
        self.loop.run_until_idle()

    # -- event application

    def apply_ibus(self, ev: dict) -> None:
        if "InterfaceUpd" in ev:
            upd = ev["InterfaceUpd"]
            ifname = upd["ifname"]
            flags_s = upd.get("flags")
            operative = (
                "OPERATIVE" in flags_s if flags_s is not None else True
            )
            if upd.get("mac_address"):
                self.mac[ifname] = bytes(upd["mac_address"])
                for inst in self.insts:
                    iface = inst.interfaces.get(ifname)
                    if iface is not None:
                        iface.mac = self.mac[ifname]
            if upd.get("msd"):
                msd = upd["msd"]
                for inst in self.insts:
                    iface = inst.interfaces.get(ifname)
                    if iface is not None and "BaseMplsImposition" in msd:
                        iface.config.msd = dict(iface.config.msd or {})
                        iface.config.msd[1] = msd["BaseMplsImposition"]
                        inst._originate_lsp()
            if upd.get("ifindex"):
                self.ifindex[ifname] = upd["ifindex"]
            if operative:
                self._ensure_iface(ifname)
            elif ifname in self.up:
                self.node.if_down(ifname)
                self.up.discard(ifname)
                self.loop.run_until_idle()
        elif "InterfaceAddressAdd" in ev:
            upd = ev["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.setdefault(upd["ifname"], [])
            if addr not in lst:
                lst.append(addr)
            ifname = upd["ifname"]
            if ifname in self.up:
                for inst in self.insts:
                    self._sync_iface_addrs(inst.interfaces[ifname])
                    inst._originate_lsp()
                self.loop.run_until_idle()
            else:
                self._ensure_iface(ifname)
        elif "InterfaceAddressDel" in ev:
            upd = ev["InterfaceAddressDel"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.get(upd["ifname"]) or []
            if addr in lst:
                lst.remove(addr)
            ifname = upd["ifname"]
            if ifname in self.up:
                for inst in self.insts:
                    self._sync_iface_addrs(inst.interfaces[ifname])
                    inst._originate_lsp()
                self.loop.run_until_idle()
        elif "HostnameUpdate" in ev:
            for inst in self.insts:
                inst.set_hostname(ev["HostnameUpdate"])
            self.loop.run_until_idle()
        elif "RouterIdUpdate" in ev:
            for inst in self.insts:
                inst.router_id = IPv4Address(ev["RouterIdUpdate"])
        elif "RouteRedistributeAdd" in ev:
            upd = ev["RouteRedistributeAdd"]
            from ipaddress import ip_network

            prefix = ip_network(upd["prefix"])
            for inst in self.insts:
                inst.redist[prefix] = upd.get("metric", 0)
                inst._originate_lsp()
            self.loop.run_until_idle()
        elif "RouteRedistributeDel" in ev:
            upd = ev["RouteRedistributeDel"]
            from ipaddress import ip_network

            prefix = ip_network(upd["prefix"])
            for inst in self.insts:
                inst.redist.pop(prefix, None)
                inst._originate_lsp()
            self.loop.run_until_idle()
        elif "SrCfgUpd" in ev:
            upd = ev["SrCfgUpd"]
            from ipaddress import ip_network

            from holo_tpu.utils.sr import PrefixSid, SrConfig, Srgb

            srgb_cfg = (upd.get("srgb") or [{}])[0]
            srgb = Srgb(
                srgb_cfg.get("lower_bound", 16000),
                srgb_cfg.get("upper_bound", 23999),
            )
            srlb_cfg = (upd.get("srlb") or [None])[0]
            srlb = (
                (srlb_cfg["lower_bound"], srlb_cfg["upper_bound"])
                if srlb_cfg
                else None
            )
            sids = {}
            for (pfx_algo, cfg) in upd.get("prefix_sids", []):
                prefix = ip_network(pfx_algo[0])
                sids[prefix] = PrefixSid(
                    prefix, cfg["index"],
                    no_php=cfg.get("last_hop") == "NoPhp",
                    explicit_null=cfg.get("last_hop") == "ExplicitNull",
                )
            enabled = getattr(self, "_sr_enabled", False)
            for inst in self.insts:
                inst.sr = SrConfig(
                    enabled=enabled, srgb=srgb, prefix_sids=sids, srlb=srlb
                )
                if enabled:
                    inst.sr_allocate_adj_sids()
                    inst._originate_lsp()
            self.loop.run_until_idle()
        elif "BfdStateUpd" in ev:
            upd = ev["BfdStateUpd"]
            key = (upd.get("sess_key") or {}).get("IpSingleHop") or {}
            if upd.get("state") == "Down" and key:
                from ipaddress import ip_address

                for inst in self.insts:
                    inst.bfd_state_down(
                        key["ifname"], ip_address(key["dst"])
                    )
                self.loop.run_until_idle()
                for inst in self.insts:
                    inst._flush_flooding(srm_only=True)
        elif "NodeMsdUpd" in ev:
            # RFC 8491: BaseMplsImposition is MSD-type 1.
            msd = ev["NodeMsdUpd"]
            for inst in self.insts:
                if "BaseMplsImposition" in msd:
                    inst.node_msd[1] = msd["BaseMplsImposition"]
                inst._originate_lsp()
            self.loop.run_until_idle()
        else:
            raise Unsupported(f"ibus {next(iter(ev))}")

    def _sync_iface_addrs(self, iface: IsisInterface) -> None:
        addrs = self.addrs.get(iface.name) or []
        v4 = [a for a in addrs if a.version == 4]
        iface.addrs4 = v4
        iface.addrs6 = [
            a for a in addrs if a.version == 6 and not a.ip.is_link_local
        ]
        v6ll = [a.ip for a in addrs if a.version == 6 and a.ip.is_link_local]
        iface.addr6 = v6ll[0] if v6ll else None
        if v4:
            iface.addr_ip, iface.prefix = v4[0].ip, v4[0].network
        else:
            # No v4 left: the single-pair fallback must not resurrect
            # the deleted address (addr_ip stays as the tx source).
            iface.prefix = None

    def apply_protocol(self, ev: dict) -> None:
        if "NetRxPdu" in ev:
            rx = ev["NetRxPdu"]
            ifname = self._iface_by_key(rx.get("iface_key"))
            if ifname is None:
                raise Unsupported("unmapped iface key")
            if ifname not in self.inst.interfaces:
                return  # circuit not up: reference drops too
            snpa = bytes(rx.get("src") or b"")
            if "bytes" in rx:
                try:
                    pdu_type, pdu = decode_pdu(bytes(rx["bytes"]))
                except Exception:
                    return  # malformed-PDU corpora
            else:
                pj = rx.get("pdu", {})
                if "Err" in pj:
                    return  # decode-error input: instance never sees it
                pdu_type, pdu = pdu_from_json(pj.get("Ok", pj))
            # Level scoping: single-level instances ignore the other
            # level's PDUs (the reference's level gating).
            lvl = getattr(pdu, "level", None)
            if (
                not self.level_all
                and lvl is not None
                and lvl != self.level
            ):
                return
            self.node.rx_pdu(ifname, pdu_type, pdu, snpa)
            self.loop.run_until_idle()
            for inst in self.insts:
                inst._flush_flooding(srm_only=True)
        elif "SendPsnp" in ev:
            ifname = self._iface_by_key(ev["SendPsnp"].get("iface_key"))
            if ifname:
                for inst in self._by_level(ev["SendPsnp"]):
                    inst.send_psnp(ifname)
        elif "SendCsnp" in ev:
            ifname = self._iface_by_key(ev["SendCsnp"].get("iface_key"))
            for inst in self._by_level(ev["SendCsnp"]):
                if ifname and ifname in inst.interfaces:
                    iface = inst.interfaces[ifname]
                    if iface.is_lan and not iface.we_are_dis(
                        inst.sysid, iface.circuit_id
                    ):
                        continue
                    inst.send_csnp(ifname)
        elif "DisElection" in ev:
            ifname = self._iface_by_key(ev["DisElection"].get("iface_key"))
            if ifname:
                for inst in self._by_level(ev["DisElection"]):
                    inst.run_dis_election(ifname)
                self.loop.run_until_idle()
        elif "LspOriginate" in ev:
            for inst in self.insts:
                inst.originate_pending()
            self.loop.run_until_idle()
            for inst in self.insts:
                inst._flush_flooding(srm_only=True)
            self._remerge()
        elif "SpfDelayEvent" in ev:
            sev = ev["SpfDelayEvent"].get("event")
            if sev == "DelayTimer":
                if self.level_all:
                    lv = ev["SpfDelayEvent"].get("level")
                    self.node.run_spf(
                        1 if lv == "L1" else 2 if lv == "L2" else None
                    )
                    if self.insts[1]._orig_pending:
                        self.insts[1].originate_pending()
                    self._remerge()
                else:
                    for inst in self._by_level(ev["SpfDelayEvent"]):
                        inst.run_spf()
                self.loop.run_until_idle()
            elif sev == "LearnTimer":
                for inst in self._by_level(ev["SpfDelayEvent"]):
                    inst.spf_delay_event("learn")
            elif sev == "HoldDownTimer":
                for inst in self._by_level(ev["SpfDelayEvent"]):
                    inst.spf_delay_event("holddown")
        elif "AdjInitLsdbSync" in ev:
            pass  # our adjacency-up path sends the init CSNP inline
        elif "AdjHoldTimer" in ev:
            sub = ev["AdjHoldTimer"]
            if "PointToPoint" in sub:
                ifname = self._iface_by_key(
                    sub["PointToPoint"].get("iface_key")
                )
                if ifname:
                    for inst in self.insts:
                        self.loop.send(inst.name, HoldTimerMsg(ifname))
            else:
                b = sub["Broadcast"]
                ifname = self._iface_by_key(b.get("iface_key"))
                sysid = bytes((b.get("adj_key") or {}).get("Value") or b"")
                if ifname and sysid:
                    for inst in self._by_level(b):
                        self.loop.send(
                            inst.name, LanHoldTimerMsg(ifname, sysid)
                        )
            self.loop.run_until_idle()
            for inst in self.insts:
                inst._flush_flooding(srm_only=True)
        elif "LspRefresh" in ev:
            key = (ev["LspRefresh"].get("lse_key") or {}).get("Value")
            if not isinstance(key, dict):
                raise Unsupported("unmapped LspRefresh key")
            for inst in self._by_level(ev["LspRefresh"]):
                inst.refresh_lsp(refjson_isis._lsp_id_from(key))
            self.loop.run_until_idle()
            for inst in self.insts:
                inst._flush_flooding(srm_only=True)
        elif "LspPurge" in ev:
            key = (ev["LspPurge"].get("lse_key") or {}).get("Value")
            if not isinstance(key, dict):
                raise Unsupported("unmapped LspPurge key")
            for inst in self._by_level(ev["LspPurge"]):
                inst.purge_lsp(refjson_isis._lsp_id_from(key))
            self.loop.run_until_idle()
            for inst in self.insts:
                inst._flush_flooding(srm_only=True)
        elif "LspDelete" in ev:
            key = (ev["LspDelete"].get("lse_key") or {}).get("Value")
            if isinstance(key, dict):
                for inst in self._by_level(ev["LspDelete"]):
                    inst.lsdb.pop(refjson_isis._lsp_id_from(key), None)
        else:
            raise Unsupported(f"protocol {next(iter(ev))}")


    # -- northbound config-change / RPC inputs

    def apply_rpc(self, rpc: dict) -> None:
        if "ietf-isis:clear-adjacency" in rpc:
            for inst in self.insts:
                inst.clear_adjacencies(
                    ifname=rpc["ietf-isis:clear-adjacency"].get("interface")
                )
        elif "ietf-isis:clear-database" in rpc:
            for inst in self.insts:
                inst.clear_database()
        else:
            raise Unsupported(f"rpc {next(iter(rpc))}")
        self.loop.run_until_idle()
        for inst in self.insts:
            inst._flush_flooding(srm_only=True)

    def apply_config_change(self, tree: dict) -> None:
        """Apply a recorded YANG config diff (yang:operation annotations).

        Every annotation must be consumed by a handler; anything else
        raises Unsupported so unmodeled config never fake-passes."""
        proto = tree["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]
        isis = proto.get("ietf-isis:isis", {})
        unhandled: list[str] = []

        def op_of(node: dict, leaf: str | None = None):
            ann = node.get("@" + leaf if leaf else "@") or {}
            return ann.get("yang:operation")

        handled_at = {"@"}

        def leaf(node, name, anchor=""):
            handled_at.add(f"{anchor}@{name}")
            return op_of(node, name)

        if leaf(isis, "enabled") in ("replace", "create"):
            if isis["enabled"] is False:
                # Purge our LSPs, then drop all state (instance stop).
                for inst in self.insts:
                    for lid in list(inst.lsdb):
                        if lid.sysid == inst.sysid:
                            inst.purge_lsp(lid)
                    inst.routes = {}
                self._routes_changed({})
                self.loop.run_until_idle()
                for inst in self.insts:
                    inst._flush_flooding(srm_only=True)
                self.drain_tx()
                for inst in self.insts:
                    inst.lsdb.clear()
                    inst._plain_raw.clear()
                    inst.hostnames.clear()
                    inst.enabled = False
                    for iface in inst.interfaces.values():
                        iface.adj = None
                        iface.adjs.clear()
                        iface.srm.clear()
                        iface.ssn.clear()
            else:
                for inst in self.insts:
                    inst.enabled = True
                    inst._plain_raw.clear()
                    inst._originate_lsp(force=True)
        mt = isis.get("metric-type") or {}
        if op_of(mt, "value") in ("replace", "create"):
            handled_at.update(("@metric-type", "metric-type"))
            for inst in self.insts:
                inst.metric_style = {
                    "old-only": "narrow", "wide-only": "wide", "both": "both"
                }[mt["value"]]
                inst._originate_lsp()
        ov = isis.get("overload") or {}
        if op_of(ov, "status") in ("replace", "create"):
            handled_at.update(("@overload", "overload"))
            for inst in self.insts:
                inst.overload = bool(ov["status"])
                inst._originate_lsp()
        pref = isis.get("preference") or {}
        if op_of(pref, "default") in ("replace", "create"):
            handled_at.update(("@preference", "preference"))
            self.preference = pref["default"]
            # Distance change reinstalls every INSTALLED route.
            src = self.node if self.level_all else self.inst
            for prefix, (metric, nhs) in src.installable_routes().items():
                self.ibus_log.append(("add", prefix, metric, nhs))
        spfc = isis.get("spf-control") or {}
        if op_of(spfc, "paths") in ("replace", "create", "delete"):
            handled_at.update(("@spf-control", "spf-control"))
            for inst in self.insts:
                inst.max_paths = (
                    None
                    if op_of(spfc, "paths") == "delete"
                    else spfc["paths"]
                )
                inst.run_spf()
        nt = isis.get("node-tags")
        if nt is not None:
            handled_at.update(("@node-tags", "node-tags"))
            tags = list(self.inst.node_tags)
            for t in nt.get("node-tag", []):
                if op_of(t) == "create" and t["tag"] not in tags:
                    tags.append(t["tag"])
                elif op_of(t) == "delete" and t["tag"] in tags:
                    tags.remove(t["tag"])
            for inst in self.insts:
                inst.node_tags = tuple(tags)
                inst._originate_lsp()
        terid = (isis.get("mpls") or {}).get("te-rid") or {}
        if terid:
            handled_at.update(("@mpls", "mpls"))
            for name, attr in (
                ("ipv4-router-id", "te_rid4"),
                ("ipv6-router-id", "te_rid6"),
            ):
                op = op_of(terid, name)
                for inst in self.insts:
                    if op in ("replace", "create"):
                        from ipaddress import ip_address

                        setattr(inst, attr, ip_address(terid[name]))
                    elif op == "delete":
                        setattr(inst, attr, None)
            for inst in self.insts:
                inst._originate_lsp()
        if leaf(isis, "ietf-isis:poi-tlv") in ("replace", "create"):
            for inst in self.insts:
                inst.purge_originator = bool(isis["ietf-isis:poi-tlv"])
        afl = (isis.get("address-families") or {}).get(
            "address-family-list"
        )
        if afl is not None:
            handled_at.update(("@address-families", "address-families"))
            for af in afl:
                name = af["address-family"]
                if op_of(af) == "delete" or af.get("enabled") is False:
                    self.afs.discard(name)
                elif op_of(af) == "create" or af.get("enabled"):
                    self.afs.add(name)
            for inst in self.insts:
                inst.protocols = (
                    [0xCC] if "ipv4" in self.afs else []
                ) + ([0x8E] if "ipv6" in self.afs else [])
                inst.afs = set(self.afs)
                inst._originate_lsp()
        for if_node in (isis.get("interfaces") or {}).get("interface", []):
            handled_at.update(("@interfaces", "interfaces"))
            ifname = if_node["name"]
            if op_of(if_node) == "delete":
                if ifname in self.up:
                    self.node.if_down(ifname)
                    self.up.discard(ifname)
                self.if_conf.pop(ifname, None)
                # The LOCAL route table loses next hops through the
                # deleted circuit with NO ibus emission (recorded
                # nb-config-iface-delete1 step 1 emits only
                # InterfaceUnsub), and the reference's reinstall diff at
                # the next SPF runs against this stripped local RIB
                # (update_global_rib's old_rib) — so prev_routes tracks
                # the stripped view, leaving the kernel stale by design.
                for inst in self.insts:
                    for prefix, (metric, nhs) in list(inst.routes.items()):
                        kept = frozenset(
                            nh for nh in nhs if nh[0] != ifname
                        )
                        if kept != nhs:
                            inst.routes[prefix] = (metric, kept)
                            if prefix in self.prev_routes:
                                self.prev_routes[prefix] = (metric, kept)
                    inst._originate_lsp()
                continue
            for key in if_node:
                if not key.startswith("@") or key == "@":
                    continue
                name = key[1:]
                op = op_of(if_node, name)
                if name == "enabled":
                    if if_node["enabled"] is False and ifname in self.up:
                        self.node.if_down(ifname)
                        self.up.discard(ifname)
                        for inst in self.insts:
                            inst._originate_lsp()
                    elif if_node["enabled"] and ifname not in self.up:
                        self._ensure_iface(ifname)
                elif name == "passive":
                    if ifname in self.if_conf:
                        self.if_conf[ifname]["passive"] = bool(
                            if_node["passive"]
                        )
                    for inst in self.insts:
                        iface = inst.interfaces.get(ifname)
                        if iface is None:
                            continue
                        iface.config.passive = bool(if_node["passive"])
                        if iface.config.passive:
                            iface.adj = None
                            iface.adjs.clear()
                            inst._adj_changed()
                        elif inst.inline_hellos:
                            inst._send_hello(ifname)
                else:
                    unhandled.append(f"iface leaf {name}")
            metric = if_node.get("metric") or {}
            if op_of(metric, "value") in ("replace", "create"):
                if ifname in self.if_conf:
                    self.if_conf[ifname].setdefault("metric", {})[
                        "value"
                    ] = metric["value"]
                for inst in self.insts:
                    iface = inst.interfaces.get(ifname)
                    if iface is not None:
                        iface.config.metric = metric["value"]
                        inst._originate_lsp()
            elif set(metric) - {"value", "@value"}:
                unhandled.append("iface metric")
            af_sub = (if_node.get("address-families") or {}).get(
                "address-family-list"
            )
            if af_sub is not None:
                for target in self.insts:
                    ifc = target.interfaces.get(ifname)
                    if ifc is None:
                        continue
                    cur = (
                        set(ifc.config.afs)
                        if ifc.config.afs is not None
                        else set(target.afs)
                    )
                    for af in af_sub:
                        nm = af["address-family"]
                        if op_of(af) == "delete" or af.get("enabled") is False:
                            cur.discard(nm)
                        else:
                            cur.add(nm)
                    ifc.config.afs = cur
                    target._originate_lsp()
            bfd = if_node.get("bfd") or {}
            if bfd:
                enabled_op = op_of(bfd, "enabled")
                mt_node = (bfd.get("min-transmission-interval") or {})
                mr_node = (bfd.get("min-receive-interval") or {})
                min_tx = (
                    mt_node.get("value")
                    if op_of(mt_node, "value") in ("replace", "create")
                    else None
                )
                min_rx = (
                    mr_node.get("value")
                    if op_of(mr_node, "value") in ("replace", "create")
                    else None
                )
                if op_of(bfd, "min-interval") in ("replace", "create"):
                    min_tx = min_rx = bfd["min-interval"]
                for target in self.insts:
                    cur = target.interfaces.get(ifname)
                    enabled = (
                        bool(bfd["enabled"])
                        if enabled_op in ("replace", "create")
                        else (cur.config.bfd_enabled if cur else False)
                    )
                    target.set_bfd_config(
                        ifname, enabled, min_tx=min_tx, min_rx=min_rx
                    )
            esn = if_node.get("holo-isis:extended-sequence-number") or {}
            if esn and op_of(esn, "mode") in ("replace", "create", None):
                for target in self.insts:
                    ifc = target.interfaces.get(ifname)
                    if ifc is not None:
                        ifc.config.esn_mode = esn.get("mode")
        for key in isis:
            if key.startswith("@") and key not in handled_at:
                unhandled.append(f"isis leaf {key[1:]}")
            elif not key.startswith("@") and key not in (
                "enabled", "metric-type", "overload", "preference",
                "spf-control", "node-tags", "mpls", "ietf-isis:poi-tlv",
                "address-families", "interfaces", "level-type",
                "system-id", "area-address", "lsp-mtu",
                "ietf-isis-sr-mpls:segment-routing",
                "holo-isis:attached-bit",
                "holo-isis:inter-level-propagation-policies",
            ):
                unhandled.append(f"isis node {key}")
        srn = isis.get("ietf-isis-sr-mpls:segment-routing") or {}
        if srn:
            handled_at.add("@ietf-isis-sr-mpls:segment-routing")
            if op_of(srn, "enabled") in ("replace", "create"):
                self._sr_enabled = bool(srn["enabled"])
                from holo_tpu.utils.sr import SrConfig

                for i in self.insts:
                    if i.sr is None:
                        i.sr = SrConfig(
                            enabled=self._sr_enabled, srgb_set=False
                        )
                    else:
                        i.sr = SrConfig(
                            enabled=self._sr_enabled, srgb=i.sr.srgb,
                            prefix_sids=i.sr.prefix_sids, srlb=i.sr.srlb,
                            srgb_set=getattr(i.sr, "srgb_set", True),
                        )
                    if self._sr_enabled:
                        i.sr_allocate_adj_sids()
                    i._originate_lsp()
        att = isis.get("holo-isis:attached-bit") or {}
        if att:
            handled_at.update(("@holo-isis:attached-bit",))
            if op_of(att, "ignore-reception") in ("replace", "create"):
                for i in self.insts:
                    i.att_ignore = bool(att["ignore-reception"])
                # Receive-side change recomputes the default route.
                for i in self.insts:
                    i.run_spf()
            if op_of(att, "suppress-advertisement") in ("replace", "create"):
                if not self.level_all:
                    raise Unsupported("att-suppress on single level")
                self.node.att_suppress = bool(att["suppress-advertisement"])
                self.insts[0]._originate_lsp()
        ilpp = isis.get("holo-isis:inter-level-propagation-policies") or {}
        if ilpp:
            handled_at.update(("@holo-isis:inter-level-propagation-policies",))
            if not self.level_all:
                raise Unsupported("inter-level-propagation on single level")
            sp = (ilpp.get("level1-to-level2") or {}).get(
                "summary-prefixes", []
            )
            from ipaddress import ip_network

            for entry in sp:
                prefix = ip_network(entry["prefix"])
                if op_of(entry) == "delete":
                    self.node.summaries.pop(prefix, None)
                else:
                    self.node.summaries[prefix] = entry.get("metric")
            self.insts[1]._originate_lsp()
            if self.insts[1]._orig_pending:
                self.insts[1].originate_pending()
            # Active-summary discard routes join the merged table now.
            self.node._level_routes_changed({})
            self.loop.run_until_idle()
        if unhandled:
            raise Unsupported("; ".join(sorted(set(unhandled))[:4]))
        self.loop.run_until_idle()
        for target in self.insts:
            if target._orig_pending:
                target.originate_pending()
                self.loop.run_until_idle()
            target._flush_flooding(srm_only=True)

    def bring_up(self) -> None:
        for line in (self.rt_dir / "events.jsonl").read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])

    # -- output planes

    def drain_tx(self):
        out = self.tx.log[:]
        self.tx.log.clear()
        return out

    def drain_ibus(self):
        out = self.ibus_log[:]
        self.ibus_log.clear()
        self.bfd_log.clear()
        return out

    def compare_protocol_output(self, expected_lines: list[dict]) -> list[str]:
        ours = []
        for ifname, dst, data in self.drain_tx():
            try:
                _t, pdu = decode_pdu(data)
            except Exception as e:
                return [f"self-tx undecodable: {e}"]
            ours.append({"ifname": ifname, "pdu": pdu_to_json(pdu)})
        problems = []
        want = []
        for exp in expected_lines:
            tx = exp.get("NetTxPdu")
            if tx is None:
                problems.append(f"unsupported output {next(iter(exp))}")
                continue
            want.append(
                {
                    "ifname": tx.get("ifname"),
                    "pdu": refjson_isis.flatten_tlv_occurrences(tx["pdu"]),
                }
            )

        def matches(w, g):
            if w["ifname"] is not None and w["ifname"] != g["ifname"]:
                return False
            return subset_match(w["pdu"], g["pdu"])

        cand = [
            [i for i, g in enumerate(ours) if matches(w, g)] for w in want
        ]
        assign: dict[int, int] = {}

        def try_assign(w: int, seen: set) -> bool:
            for i in cand[w]:
                if i in seen:
                    continue
                seen.add(i)
                if i not in assign or try_assign(assign[i], seen):
                    assign[i] = w
                    return True
            return False

        for w, item in enumerate(want):
            if not try_assign(w, set()):
                problems.append(
                    "expected tx not sent: " + json.dumps(item["pdu"])[:160]
                )
        # Two-sided (stub/mod.rs:320-429 diffs both directions): a PDU we
        # sent that the recording doesn't contain is a failure too.
        for i, got in enumerate(ours):
            if i not in assign:
                problems.append(
                    "unexpected tx: " + json.dumps(got["pdu"])[:160]
                )
        return problems

    def compare_ibus(self, expected_lines: list[dict]) -> list[str]:
        ours = []
        for op, ifname, dst, cfg in self.bfd_log:
            if op == "reg":
                ours.append(
                    {
                        "BfdSessionReg": {
                            "sess_key": {
                                "IpSingleHop": {
                                    "ifname": ifname, "dst": str(dst)
                                }
                            },
                            "client_id": {
                                "protocol": "isis", "name": "test"
                            },
                            "client_config": cfg,
                        }
                    }
                )
            else:
                ours.append(
                    {
                        "BfdSessionUnreg": {
                            "sess_key": {
                                "IpSingleHop": {
                                    "ifname": ifname, "dst": str(dst)
                                }
                            }
                        }
                    }
                )
        self.bfd_log.clear()
        for kind, prefix, metric, nhs in self.drain_ibus():
            if kind == "add":
                ours.append(
                    {
                        "RouteIpAdd": {
                            "protocol": "isis",
                            "prefix": str(prefix),
                            "metric": metric,
                            "nexthops": sorted(
                                (
                                    self.ifindex.get(ifn, 0),
                                    str(addr) if addr else None,
                                )
                                for ifn, addr in nhs
                            ),
                        }
                    }
                )
            else:
                ours.append(
                    {"RouteIpDel": {"protocol": "isis", "prefix": str(prefix)}}
                )
        problems = []
        unmatched = list(ours)
        for exp in expected_lines:
            if not any(
                k in exp
                for k in (
                    "RouteIpAdd", "RouteIpDel",
                    "BfdSessionReg", "BfdSessionUnreg",
                )
            ):
                continue
            if any(k in exp for k in ("BfdSessionReg", "BfdSessionUnreg")):
                hit = next(
                    (
                        i
                        for i, got in enumerate(unmatched)
                        if subset_match(exp, got)
                    ),
                    None,
                )
                if hit is None:
                    problems.append(
                        "expected ibus msg not sent: "
                        + json.dumps(exp)[:140]
                    )
                else:
                    unmatched.pop(hit)
                continue
            if "RouteIpAdd" in exp:
                e = exp["RouteIpAdd"]
                canon = {
                    "RouteIpAdd": {
                        "protocol": e.get("protocol"),
                        "prefix": e.get("prefix"),
                        "metric": e.get("metric"),
                        "nexthops": sorted(
                            (
                                nh.get("Address", {}).get("ifindex", 0),
                                nh.get("Address", {}).get("addr"),
                            )
                            for nh in e.get("nexthops", [])
                        ),
                    }
                }
            else:
                canon = {
                    "RouteIpDel": {
                        "protocol": exp["RouteIpDel"].get("protocol"),
                        "prefix": exp["RouteIpDel"].get("prefix"),
                    }
                }
            hit = next(
                (i for i, got in enumerate(unmatched) if subset_match(canon, got)),
                None,
            )
            if hit is None:
                problems.append(
                    "expected ibus msg not sent: " + json.dumps(canon)[:140]
                )
            else:
                unmatched.pop(hit)
        for got in unmatched:  # two-sided: extra ibus emissions fail
            problems.append(
                "unexpected ibus msg: " + json.dumps(got)[:140]
            )
        return problems

    def compare_state(self, state: dict) -> list[str]:
        """Full-tree compare: the recorded ietf-isis state plane against
        our YANG-modeled operational state (both-sided, every leaf) —
        same contract as the OSPFv2 harness."""
        from holo_tpu.protocols.isis.nb_state import instance_state
        from holo_tpu.tools.treediff import tree_diff

        isis = state["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-isis:isis"]
        ours = instance_state(
            self.insts,
            node=self.node if self.level_all else None,
            ifnames=[n for n in self.if_order if n in self.if_conf],
        )
        return tree_diff(isis, ours, "isis")

def run_case(case_dir: Path, topo: str, rt: str):
    run = CaseRun(ISIS_DIR / "topologies" / topo, rt)
    try:
        run.bring_up()
    except Unsupported as e:
        return "skip", f"bring-up: {e}"
    run.drain_tx()
    run.drain_ibus()

    steps = sorted(
        {f.name.split("-")[0] for f in case_dir.iterdir() if f.name[0].isdigit()}
    )
    problems = []
    for step in steps:
        run.drain_ibus()
        try:
            for kind in ("ibus", "protocol"):
                f = case_dir / f"{step}-input-{kind}.jsonl"
                if f.exists():
                    for line in f.read_text().splitlines():
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if kind == "ibus":
                            run.apply_ibus(ev)
                        else:
                            run.apply_protocol(ev)
            f = case_dir / f"{step}-input-northbound-config-change.json"
            if f.exists():
                run.apply_config_change(json.loads(f.read_text()))
            f = case_dir / f"{step}-input-northbound-rpc.json"
            if f.exists():
                run.apply_rpc(json.loads(f.read_text()))
        except Unsupported as e:
            return "skip", f"step {step}: {e}"
        # Self-posted deferred events (origination enqueued by the step's
        # inputs) drain before the output planes are read — the stub's
        # sync() equivalent.
        for inst in run.insts:
            if inst._orig_pending:
                inst.originate_pending()
                run.loop.run_until_idle()
                inst._flush_flooding(srm_only=True)
        out_proto = case_dir / f"{step}-output-protocol.jsonl"
        if out_proto.exists():
            expected = [
                json.loads(l)
                for l in out_proto.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}"
                for p in run.compare_protocol_output(expected)
            ]
        else:
            run.drain_tx()
        out_ibus = case_dir / f"{step}-output-ibus.jsonl"
        if out_ibus.exists():
            expected = [
                json.loads(l)
                for l in out_ibus.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}" for p in run.compare_ibus(expected)
            ]
        out_state = case_dir / f"{step}-output-northbound-state.json"
        if out_state.exists():
            state = json.loads(out_state.read_text())
            problems += [
                f"step {step}: {p}" for p in run.compare_state(state)
            ]
    return ("pass", "") if not problems else ("fail", "; ".join(problems[:6]))


def run_all(conf_dir: Path = ISIS_DIR):
    results = {}
    for case, (topo, rt) in sorted(case_map(conf_dir).items()):
        case_dir = conf_dir / case
        if not case_dir.is_dir():
            continue
        try:
            results[case] = run_case(case_dir, topo, rt)
        except Exception as e:  # noqa: BLE001 — survey run must not die
            results[case] = ("fail", f"exception: {type(e).__name__}: {e}")
    return results


if __name__ == "__main__":
    res = run_all()
    by = {"pass": [], "fail": [], "skip": []}
    for case, (status, detail) in sorted(res.items()):
        by[status].append(case)
        if status != "pass":
            print(f"{status:5} {case}: {detail[:180]}")
    print(
        f"\npass {len(by['pass'])} fail {len(by['fail'])} "
        f"skip {len(by['skip'])} / {len(res)}"
    )

"""Reference-conformance harness: replay recorded topologies, compare RIBs.

Consumes the reference's conformance corpus
(/root/reference/holo-*/tests/conformance — SURVEY.md §4): per-router
recorded events (whose LS-Update entries carry the raw LSA wire bytes)
and expected operational state.  For each topology:

1. Decode every recorded LSA with OUR codecs (cross-implementation codec
   validation for free) and union them into the converged per-area LSDB
   (newest copy per key).
2. For each router, rebuild its local view (interfaces/addresses from the
   recorded ibus events, FULL p2p neighbors resolved by subnet matching
   across routers) and run OUR SPF + route derivation pipeline.
3. Compare (prefix, metric, next-hop set) against the reference's
   expected ``local-rib`` — the BASELINE.md bit-identical-RIB gate,
   checked against the reference's own expected outputs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, ip_interface
from pathlib import Path

from holo_tpu.protocols.ospf.instance import InstanceConfig, OspfInstance
from holo_tpu.protocols.ospf.interface import IfConfig, IfType
from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
from holo_tpu.protocols.ospf.packet import Lsa
from holo_tpu.utils.bytesbuf import Reader
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

REFERENCE_CONFORMANCE = Path(
    "/root/reference/holo-ospf/tests/conformance/ospfv2/topologies"
)


@dataclass
class ExpectedRoute:
    prefix: IPv4Network
    metric: int
    route_type: str
    nexthops: frozenset  # {(ifname, addr|None)}


@dataclass
class RouterData:
    name: str
    router_id: IPv4Address = None
    # area id -> {ifname: iface config dict}
    areas: dict = field(default_factory=dict)
    # ifname -> IPv4Interface (first v4 address)
    addrs: dict = field(default_factory=dict)
    # area id -> [Lsa] every LSA this router received
    rx_lsas: dict = field(default_factory=dict)
    expected: list = field(default_factory=list)
    # area id -> (stub, nssa, summary, default-cost) from config
    area_flags: dict = field(default_factory=dict)
    # hello source addr -> (claimed DR addr, claimed BDR addr)
    hello_claims: dict = field(default_factory=dict)
    # configured virtual links [(transit area id, peer router id)]
    vlinks: list = field(default_factory=list)
    # The complete recorded ietf-ospf:ospf state tree (full-tree diff).
    full_state: dict = field(default_factory=dict)
    ifindexes: dict = field(default_factory=dict)  # ifname -> ifindex


def load_router(rt_dir: Path) -> RouterData:
    rd = RouterData(name=rt_dir.name)
    cfg = json.loads((rt_dir / "config.json").read_text())
    proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]
    ospf = proto["ietf-ospf:ospf"]
    rd.router_id = IPv4Address(ospf["explicit-router-id"])
    for area in ospf.get("areas", {}).get("area", []):
        aid = IPv4Address(area["area-id"])
        rd.areas[aid] = {}
        for vl in (area.get("virtual-links") or {}).get(
            "virtual-link", []
        ):
            rd.vlinks.append(
                (IPv4Address(vl["transit-area-id"]),
                 IPv4Address(vl["router-id"]))
            )
        atype = area.get("area-type") or ""
        rd.area_flags[aid] = (
            "stub" in atype and "nssa" not in atype,
            "nssa" in atype,
            area.get("summary", True),
            area.get("default-cost", 10),
        )
        for iface in area.get("interfaces", {}).get("interface", []):
            rd.areas[aid][iface["name"]] = iface

    rd.ifindexes = {}
    for line in (rt_dir / "events.jsonl").read_text().splitlines():
        if not line.strip():
            continue
        ev = json.loads(line)
        ibus = ev.get("Ibus")
        if ibus and "InterfaceUpd" in ibus:
            upd = ibus["InterfaceUpd"]
            rd.ifindexes[upd["ifname"]] = upd["ifindex"]
        if ibus and "InterfaceAddressAdd" in ibus:
            upd = ibus["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                continue
            if addr.version == 4 and upd["ifname"] not in rd.addrs:
                rd.addrs[upd["ifname"]] = addr
        pkt_ev = (ev.get("Protocol") or {}).get("NetRxPacket")
        if pkt_ev:
            packet = (pkt_ev.get("packet") or {}).get("Ok") or {}
            hello = packet.get("Hello")
            if hello is not None and (hello.get("dr") or hello.get("bdr")):
                src = pkt_ev.get("src")
                if src:
                    rd.hello_claims[IPv4Address(src)] = (
                        IPv4Address(hello["dr"]) if hello.get("dr") else None,
                        IPv4Address(hello["bdr"]) if hello.get("bdr") else None,
                    )
            upd = packet.get("LsUpdate")
            if not upd:
                continue
            area_id = IPv4Address(upd["hdr"]["area_id"])
            for lsa_obj in upd.get("lsas", []):
                raw = bytes(lsa_obj["raw"])
                try:
                    lsa = Lsa.decode(Reader(raw))
                except Exception:
                    continue  # LSA types we don't implement yet (opaque…)
                rd.rx_lsas.setdefault(area_id, []).append(lsa)

    state = json.loads(
        (rt_dir / "output" / "northbound-state.json").read_text()
    )
    ospf_state = state["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]["ietf-ospf:ospf"]
    rd.full_state = ospf_state
    for route in ospf_state.get("local-rib", {}).get("route", []):
        nhs = set()
        for nh in route.get("next-hops", {}).get("next-hop", []):
            addr = nh.get("next-hop")
            nhs.add(
                (nh.get("outgoing-interface"),
                 IPv4Address(addr) if addr else None)
            )
        rd.expected.append(
            ExpectedRoute(
                prefix=IPv4Network(route["prefix"]),
                metric=route.get("metric", 0),
                route_type=route.get("route-type", ""),
                nexthops=frozenset(nhs),
            )
        )
    return rd


def load_topology(topo_dir: Path) -> dict[str, RouterData]:
    return {
        rt.name: load_router(rt)
        for rt in sorted(topo_dir.iterdir())
        if rt.is_dir() and (rt / "events.jsonl").exists()
    }


def converged_lsdb(routers: dict[str, RouterData]) -> dict:
    """area id -> {LsaKey: Lsa}, newest copy wins."""
    out: dict = {}
    for rd in routers.values():
        for aid, lsas in rd.rx_lsas.items():
            area = out.setdefault(aid, {})
            for lsa in lsas:
                cur = area.get(lsa.key)
                if cur is None or lsa.compare(cur) > 0:
                    area[lsa.key] = lsa
    # A winning MaxAge incarnation is a completed flush: the reference
    # removed it from the database once acked (§14).
    for area in out.values():
        for key in [k for k, l in area.items() if l.is_maxage]:
            del area[key]
    return out


def router_lsdb(rd: RouterData, union: dict) -> dict:
    """This router's LSDB view (same discipline as the v3 sweep):
    foreign LSAs newest-per-key from ITS OWN recorded stream (lsid/label
    reuse across re-originations makes other streams' incarnations
    wrong for this router), self LSAs overlaid from the topology union
    on STRICTLY higher seqno (own stream only carries echoes), and
    completed flushes dropped."""
    out: dict = {}
    for aid, lsas in rd.rx_lsas.items():
        area = out.setdefault(aid, {})
        for lsa in lsas:
            cur = area.get(lsa.key)
            if cur is None or lsa.compare(cur) > 0:
                area[lsa.key] = lsa
    for aid, lsas in union.items():
        area = out.setdefault(aid, {})
        for key, lsa in lsas.items():
            if lsa.adv_rtr != rd.router_id:
                continue
            cur = area.get(key)
            if cur is None or lsa.seq_no > cur.seq_no:
                area[key] = lsa
    for area in out.values():
        for key in [k for k, l in area.items() if l.is_maxage]:
            del area[key]
    return out


class _NullIo(NetIo):
    def send(self, *a):
        pass


def compute_routes(rd: RouterData, lsdb_by_area: dict, routers: dict,
                   backend=None):
    """Run OUR pipeline for one router over the converged LSDB."""
    from holo_tpu.protocols.ospf.interface import IsmState

    loop = EventLoop(clock=VirtualClock())
    inst = OspfInstance(
        name=f"conf-{rd.name}",
        config=InstanceConfig(
            router_id=rd.router_id,
            virtual_links=tuple(rd.vlinks),
        ),
        netio=_NullIo(),
        spf_backend=backend,
    )
    loop.register(inst)

    for aid, ifaces in rd.areas.items():
        for ifname, icfg in ifaces.items():
            addr = rd.addrs.get(ifname)
            if addr is None:
                continue
            if_type = (
                IfType.POINT_TO_POINT
                if icfg.get("interface-type") == "point-to-point"
                else IfType.BROADCAST
            )
            stub, nssa, summary, dcost = rd.area_flags.get(
                aid, (False, False, True, 10)
            )
            iface = inst.add_interface(
                ifname,
                IfConfig(
                    area_id=aid, if_type=if_type,
                    loopback=ifname == "lo" or ifname.startswith("lo:"),
                ),
                addr.network,
                addr.ip,
                stub=stub,
                nssa=nssa,
                stub_default_cost=dcost,
            )
            inst.areas[aid].summary = summary
            iface.ifindex = rd.ifindexes.get(ifname, 0)
            # Synthesize FULL neighbors by subnet matching: the far-side
            # address of the shared link belongs to exactly one other
            # recorded router.
            for other in routers.values():
                if other.name == rd.name:
                    continue
                for oif, oaddr in other.addrs.items():
                    if oaddr.ip != addr.ip and oaddr.ip in addr.network:
                        iface.neighbors[other.router_id] = Neighbor(
                            router_id=other.router_id,
                            src=oaddr.ip,
                            state=NsmState.FULL,
                        )
    # Unnumbered p2p links: our router LSA's link_data is the ifIndex and
    # the neighbor's packets come from its borrowed (router-id) address.
    own_key = None
    for aid, lsas in lsdb_by_area.items():
        for key, lsa in lsas.items():
            if (
                key.adv_rtr == rd.router_id
                and key.type.name == "ROUTER"
                and aid in inst.areas
            ):
                from holo_tpu.protocols.ospf.packet import RouterLinkType

                by_ifindex = {
                    i.ifindex: i
                    for a in inst.areas.values()
                    for i in a.interfaces.values()
                    if i.ifindex
                }
                for link in lsa.body.links:
                    if link.link_type != RouterLinkType.POINT_TO_POINT:
                        continue
                    ld = int(link.data)
                    if ld >= 0x10000:
                        continue  # numbered link
                    iface = by_ifindex.get(ld)
                    if iface is not None and link.id not in iface.neighbors:
                        iface.neighbors[link.id] = Neighbor(
                            router_id=link.id,
                            src=IPv4Address(link.id),
                            state=NsmState.FULL,
                        )
    # Configured areas without physical interfaces (a vlink-attached
    # backbone) still hold an LSDB and join route calc.
    from holo_tpu.protocols.ospf.instance import Area

    for aid in rd.areas:
        if aid not in inst.areas:
            inst.areas[aid] = Area(aid)
    # Inject the converged LSDB (bypassing the flooding machinery).
    for aid, lsas in lsdb_by_area.items():
        if aid not in inst.areas:
            continue
        for lsa in lsas.values():
            inst.areas[aid].lsdb.install(lsa, 0.0)
    from holo_tpu.protocols.ospf.instance import SpfFsmState

    # Minimal pre-SPF posture: non-DOWN interface states so ABR
    # detection (is_abr counts ACTIVE areas) sees the converged truth
    # and summary origination runs.  DR/BDR details stay post-SPF.
    for area in inst.areas.values():
        for iface in area.interfaces.values():
            iface.state = _base_ism_state(iface, IsmState)
    inst.run_spf()
    # Virtual links: the first SPF materialized the vlink interfaces
    # (reachable endpoints); synthesize their FULL adjacencies — the
    # converged truth — and run the SPF again so our backbone
    # router-LSA carries the vlink and routes ride it (production
    # reaches the same state once vlink hellos complete).
    if inst.config.virtual_links:
        now = loop.clock.now()
        for area in inst.areas.values():
            for iface in area.interfaces.values():
                if not iface.name.startswith("vlink-") or iface.neighbors:
                    continue
                parts = iface.name.split("-")
                peer_rid = IPv4Address(parts[-1])
                taid = IPv4Address(parts[-2])
                src = None
                transit = inst.areas.get(taid)
                if transit is not None:
                    src = inst._vlink_endpoint_addr(
                        transit, peer_rid, now
                    )
                iface.neighbors[peer_rid] = Neighbor(
                    router_id=peer_rid,
                    src=src or peer_rid,
                    state=NsmState.FULL,
                )
        # Adjacency changes re-originate router LSAs in production;
        # force the same here so the backbone LSA carries the vlink.
        for area in inst.areas.values():
            inst._originate_router_lsa(area, force=True)
        inst.run_spf()
    # The recorded self RI opaque is authoritative: its contents vary
    # with recording vintage/config (GR-helper caps, SR TLVs); our RI
    # origination parity is asserted by the stepwise corpus instead.
    from holo_tpu.protocols.ospf.packet import RI_OPAQUE_TYPE

    for aid, lsas in lsdb_by_area.items():
        if aid not in inst.areas:
            continue
        for key, lsa in lsas.items():
            if (
                key.adv_rtr == rd.router_id
                and key.type.name == "OPAQUE_AREA"
                and int(key.lsid) >> 24 == RI_OPAQUE_TYPE
            ):
                entry = inst.areas[aid].lsdb.get(key)
                if entry is not None:
                    entry.lsa = lsa
                else:
                    inst.areas[aid].lsdb.install(lsa, 0.0)
    # Converged-state posture for the RENDER ONLY — applied after
    # the SPF so interface-state heuristics cannot perturb route
    # computation (the vlink machinery consults circuit state).
    for area in inst.areas.values():
        for iface in area.interfaces.values():
            iface.state = _base_ism_state(iface, IsmState)
            if iface.state == IsmState.DR_OTHER:
                # Converged DR/BDR from the recorded hello claims of
                # any neighbor on this segment (the reference ran the
                # real election during recording).
                claim = None
                for n in iface.neighbors.values():
                    nc = rd.hello_claims.get(n.src)
                    if nc is not None:
                        claim = nc
                        n.dr, n.bdr = (
                            nc[0] or n.dr, nc[1] or n.bdr
                        )
                if claim is not None:
                    dr, bdr = claim
                    if dr is not None:
                        iface.dr = dr
                    if bdr is not None:
                        iface.bdr = bdr
                else:
                    for key, lsa in lsdb_by_area.get(
                        area.area_id, {}
                    ).items():
                        if key.type.name != "NETWORK":
                            continue
                        members = set(getattr(lsa.body, "attached", ()))
                        if rd.router_id not in members:
                            continue
                        # Per-segment: the network LSA's lsid (the DR
                        # address) must lie on THIS interface's subnet.
                        if (
                            iface.prefix is None
                            or lsa.key.lsid
                            not in iface.prefix
                        ):
                            continue
                        iface.dr = lsa.key.lsid
                        break
                if iface.dr == iface.addr_ip:
                    iface.state = IsmState.DR
                elif iface.bdr == iface.addr_ip:
                    iface.state = IsmState.BACKUP
    inst.spf_state = SpfFsmState.QUIET
    for area in inst.areas.values():
        for iface in area.interfaces.values():
            for nbr in iface.neighbors.values():
                nbr.ls_rxmt.clear()  # converged: all floods acked
    # Drained flushes leave the database (§14) — the recorded trees
    # contain no MaxAge entries.
    for area in inst.areas.values():
        for key in [
            k for k, e in area.lsdb.entries.items() if e.lsa.is_maxage
        ]:
            area.lsdb.remove(key)
    return inst


def compare_router(rd: RouterData, routes: dict) -> list[str]:
    """Returns mismatch descriptions (empty = conformant)."""
    problems = []
    expected_by_prefix = {e.prefix: e for e in rd.expected}
    for prefix, exp in expected_by_prefix.items():
        got = routes.get(prefix)
        if got is None:
            problems.append(f"missing route {prefix}")
            continue
        if got.dist != exp.metric:
            problems.append(
                f"{prefix}: metric {got.dist} != expected {exp.metric}"
            )
        ours = frozenset((nh.ifname, nh.addr) for nh in got.nexthops)
        if ours != exp.nexthops:
            problems.append(
                f"{prefix}: nexthops {sorted(map(str, ours))} != "
                f"expected {sorted(map(str, exp.nexthops))}"
            )
    for prefix in routes.keys() - expected_by_prefix.keys():
        problems.append(f"unexpected extra route {prefix}")
    return problems


def _base_ism_state(iface, IsmState):
    """Converged base ISM state by interface type (used both for the
    pre-SPF ABR-detection posture and the render posture)."""
    if iface.config.loopback:
        return IsmState.LOOPBACK
    if iface.config.if_type in (
        IfType.POINT_TO_POINT,
        IfType.VIRTUAL_LINK,
    ):
        return IsmState.POINT_TO_POINT
    return IsmState.DR_OTHER


def _prune_adj_sid_labels(tree):
    """Blank adj-SID label VALUES in place (structure/flags stay).

    Adjacency SIDs are dynamically allocated labels; these recordings'
    protocol streams carry an earlier allocation than the final state
    snapshot (adjacency flaps reallocate), so the label value is
    temporal — everything else about the sub-TLVs stays strict."""
    if isinstance(tree, dict):
        for k in ("adj-sid-sub-tlv", "lan-adj-sid-sub-tlv"):
            v = tree.get(k)
            if isinstance(v, list):
                for sub in v:
                    sub.pop("sid", None)
        for v in tree.values():
            _prune_adj_sid_labels(v)
    elif isinstance(tree, list):
        for v in tree:
            _prune_adj_sid_labels(v)


def _prune_ri_caps(tree):
    """Drop ri-opaque router-capabilities-tlv subtrees in place.

    These topology recordings are an older render vintage: their own
    recorded wire bytes carry GR-helper + stub-router, but the state
    snapshot renders only stub-router (the current reference — like our
    renderer — emits both, yang.rs:129-152).  The capability RENDER is
    asserted against the current vintage by the stepwise corpus; here
    the vintage-divergent subtree is excluded so everything else stays
    strict."""
    if isinstance(tree, dict):
        ri = tree.get("ri-opaque")
        if isinstance(ri, dict):
            ri.pop("router-capabilities-tlv", None)
        for v in tree.values():
            _prune_ri_caps(v)
    elif isinstance(tree, list):
        for v in tree:
            _prune_ri_caps(v)


def compare_state(rd: RouterData, inst) -> list[str]:
    """Full recorded ietf-ospf tree vs our YANG-modeled render — the
    same both-sided contract the stepwise harness and the v3 topology
    sweep enforce."""
    import copy

    from holo_tpu.protocols.ospf.nb_state import instance_state
    from holo_tpu.tools.treediff import tree_diff

    exp = copy.deepcopy(rd.full_state)
    got = instance_state(inst)
    _prune_ri_caps(exp)
    _prune_ri_caps(got)
    _prune_adj_sid_labels(exp)
    _prune_adj_sid_labels(got)
    return tree_diff(exp, got, "ospf")


def run_topology(topo_dir: Path, backend_factory=None) -> dict[str, list[str]]:
    """backend_factory: () -> SpfBackend (None = scalar default); passing
    TpuSpfBackend proves the TENSOR engine reproduces the reference RIBs."""
    routers = load_topology(topo_dir)
    union = converged_lsdb(routers)
    results = {}
    for name, rd in sorted(routers.items()):
        backend = backend_factory() if backend_factory else None
        inst = compute_routes(rd, router_lsdb(rd, union), routers, backend)
        results[name] = compare_router(rd, inst.routes)
        results[name] += compare_state(rd, inst)
    return results

"""Reference-conformance harness: replay recorded topologies, compare RIBs.

Consumes the reference's conformance corpus
(/root/reference/holo-*/tests/conformance — SURVEY.md §4): per-router
recorded events (whose LS-Update entries carry the raw LSA wire bytes)
and expected operational state.  For each topology:

1. Decode every recorded LSA with OUR codecs (cross-implementation codec
   validation for free) and union them into the converged per-area LSDB
   (newest copy per key).
2. For each router, rebuild its local view (interfaces/addresses from the
   recorded ibus events, FULL p2p neighbors resolved by subnet matching
   across routers) and run OUR SPF + route derivation pipeline.
3. Compare (prefix, metric, next-hop set) against the reference's
   expected ``local-rib`` — the BASELINE.md bit-identical-RIB gate,
   checked against the reference's own expected outputs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, ip_interface
from pathlib import Path

from holo_tpu.protocols.ospf.instance import InstanceConfig, OspfInstance
from holo_tpu.protocols.ospf.interface import IfConfig, IfType
from holo_tpu.protocols.ospf.neighbor import Neighbor, NsmState
from holo_tpu.protocols.ospf.packet import Lsa
from holo_tpu.utils.bytesbuf import Reader
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

REFERENCE_CONFORMANCE = Path(
    "/root/reference/holo-ospf/tests/conformance/ospfv2/topologies"
)


@dataclass
class ExpectedRoute:
    prefix: IPv4Network
    metric: int
    route_type: str
    nexthops: frozenset  # {(ifname, addr|None)}


@dataclass
class RouterData:
    name: str
    router_id: IPv4Address = None
    # area id -> {ifname: iface config dict}
    areas: dict = field(default_factory=dict)
    # ifname -> IPv4Interface (first v4 address)
    addrs: dict = field(default_factory=dict)
    # area id -> [Lsa] every LSA this router received
    rx_lsas: dict = field(default_factory=dict)
    expected: list = field(default_factory=list)
    ifindexes: dict = field(default_factory=dict)  # ifname -> ifindex


def load_router(rt_dir: Path) -> RouterData:
    rd = RouterData(name=rt_dir.name)
    cfg = json.loads((rt_dir / "config.json").read_text())
    proto = cfg["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]
    ospf = proto["ietf-ospf:ospf"]
    rd.router_id = IPv4Address(ospf["explicit-router-id"])
    for area in ospf.get("areas", {}).get("area", []):
        aid = IPv4Address(area["area-id"])
        rd.areas[aid] = {}
        for iface in area.get("interfaces", {}).get("interface", []):
            rd.areas[aid][iface["name"]] = iface

    rd.ifindexes = {}
    for line in (rt_dir / "events.jsonl").read_text().splitlines():
        if not line.strip():
            continue
        ev = json.loads(line)
        ibus = ev.get("Ibus")
        if ibus and "InterfaceUpd" in ibus:
            upd = ibus["InterfaceUpd"]
            rd.ifindexes[upd["ifname"]] = upd["ifindex"]
        if ibus and "InterfaceAddressAdd" in ibus:
            upd = ibus["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                continue
            if addr.version == 4 and upd["ifname"] not in rd.addrs:
                rd.addrs[upd["ifname"]] = addr
        pkt_ev = (ev.get("Protocol") or {}).get("NetRxPacket")
        if pkt_ev:
            packet = (pkt_ev.get("packet") or {}).get("Ok") or {}
            upd = packet.get("LsUpdate")
            if not upd:
                continue
            area_id = IPv4Address(upd["hdr"]["area_id"])
            for lsa_obj in upd.get("lsas", []):
                raw = bytes(lsa_obj["raw"])
                try:
                    lsa = Lsa.decode(Reader(raw))
                except Exception:
                    continue  # LSA types we don't implement yet (opaque…)
                rd.rx_lsas.setdefault(area_id, []).append(lsa)

    state = json.loads(
        (rt_dir / "output" / "northbound-state.json").read_text()
    )
    ospf_state = state["ietf-routing:routing"]["control-plane-protocols"][
        "control-plane-protocol"
    ][0]["ietf-ospf:ospf"]
    for route in ospf_state.get("local-rib", {}).get("route", []):
        nhs = set()
        for nh in route.get("next-hops", {}).get("next-hop", []):
            addr = nh.get("next-hop")
            nhs.add(
                (nh.get("outgoing-interface"),
                 IPv4Address(addr) if addr else None)
            )
        rd.expected.append(
            ExpectedRoute(
                prefix=IPv4Network(route["prefix"]),
                metric=route.get("metric", 0),
                route_type=route.get("route-type", ""),
                nexthops=frozenset(nhs),
            )
        )
    return rd


def load_topology(topo_dir: Path) -> dict[str, RouterData]:
    return {
        rt.name: load_router(rt)
        for rt in sorted(topo_dir.iterdir())
        if rt.is_dir() and (rt / "events.jsonl").exists()
    }


def converged_lsdb(routers: dict[str, RouterData]) -> dict:
    """area id -> {LsaKey: Lsa}, newest copy wins."""
    out: dict = {}
    for rd in routers.values():
        for aid, lsas in rd.rx_lsas.items():
            area = out.setdefault(aid, {})
            for lsa in lsas:
                cur = area.get(lsa.key)
                if cur is None or lsa.compare(cur) > 0:
                    area[lsa.key] = lsa
    return out


class _NullIo(NetIo):
    def send(self, *a):
        pass


def compute_routes(rd: RouterData, lsdb_by_area: dict, routers: dict,
                   backend=None):
    """Run OUR pipeline for one router over the converged LSDB."""
    loop = EventLoop(clock=VirtualClock())
    inst = OspfInstance(
        name=f"conf-{rd.name}",
        config=InstanceConfig(router_id=rd.router_id),
        netio=_NullIo(),
        spf_backend=backend,
    )
    loop.register(inst)

    for aid, ifaces in rd.areas.items():
        for ifname, icfg in ifaces.items():
            addr = rd.addrs.get(ifname)
            if addr is None:
                continue
            if_type = (
                IfType.POINT_TO_POINT
                if icfg.get("interface-type") == "point-to-point"
                else IfType.BROADCAST
            )
            iface = inst.add_interface(
                ifname,
                IfConfig(area_id=aid, if_type=if_type),
                addr.network,
                addr.ip,
            )
            iface.ifindex = rd.ifindexes.get(ifname, 0)
            # Synthesize FULL neighbors by subnet matching: the far-side
            # address of the shared link belongs to exactly one other
            # recorded router.
            for other in routers.values():
                if other.name == rd.name:
                    continue
                for oif, oaddr in other.addrs.items():
                    if oaddr.ip != addr.ip and oaddr.ip in addr.network:
                        iface.neighbors[other.router_id] = Neighbor(
                            router_id=other.router_id,
                            src=oaddr.ip,
                            state=NsmState.FULL,
                        )
    # Unnumbered p2p links: our router LSA's link_data is the ifIndex and
    # the neighbor's packets come from its borrowed (router-id) address.
    own_key = None
    for aid, lsas in lsdb_by_area.items():
        for key, lsa in lsas.items():
            if (
                key.adv_rtr == rd.router_id
                and key.type.name == "ROUTER"
                and aid in inst.areas
            ):
                from holo_tpu.protocols.ospf.packet import RouterLinkType

                by_ifindex = {
                    i.ifindex: i
                    for a in inst.areas.values()
                    for i in a.interfaces.values()
                    if i.ifindex
                }
                for link in lsa.body.links:
                    if link.link_type != RouterLinkType.POINT_TO_POINT:
                        continue
                    ld = int(link.data)
                    if ld >= 0x10000:
                        continue  # numbered link
                    iface = by_ifindex.get(ld)
                    if iface is not None and link.id not in iface.neighbors:
                        iface.neighbors[link.id] = Neighbor(
                            router_id=link.id,
                            src=IPv4Address(link.id),
                            state=NsmState.FULL,
                        )
    # Inject the converged LSDB (bypassing the flooding machinery).
    for aid, lsas in lsdb_by_area.items():
        if aid not in inst.areas:
            continue
        for lsa in lsas.values():
            inst.areas[aid].lsdb.install(lsa, 0.0)
    inst.run_spf()
    return inst.routes


def compare_router(rd: RouterData, routes: dict) -> list[str]:
    """Returns mismatch descriptions (empty = conformant)."""
    problems = []
    expected_by_prefix = {e.prefix: e for e in rd.expected}
    for prefix, exp in expected_by_prefix.items():
        got = routes.get(prefix)
        if got is None:
            problems.append(f"missing route {prefix}")
            continue
        if got.dist != exp.metric:
            problems.append(
                f"{prefix}: metric {got.dist} != expected {exp.metric}"
            )
        ours = frozenset((nh.ifname, nh.addr) for nh in got.nexthops)
        if ours != exp.nexthops:
            problems.append(
                f"{prefix}: nexthops {sorted(map(str, ours))} != "
                f"expected {sorted(map(str, exp.nexthops))}"
            )
    for prefix in routes.keys() - expected_by_prefix.keys():
        problems.append(f"unexpected extra route {prefix}")
    return problems


def run_topology(topo_dir: Path, backend_factory=None) -> dict[str, list[str]]:
    """backend_factory: () -> SpfBackend (None = scalar default); passing
    TpuSpfBackend proves the TENSOR engine reproduces the reference RIBs."""
    routers = load_topology(topo_dir)
    lsdb = converged_lsdb(routers)
    results = {}
    for name, rd in sorted(routers.items()):
        backend = backend_factory() if backend_factory else None
        routes = compute_routes(rd, lsdb, routers, backend)
        results[name] = compare_router(rd, routes)
    return results

"""VRRP stepwise conformance: replay the reference's recorded cases.

holo-vrrp's ProtocolInstance is per INTERFACE (interface.rs:36) hosting
one virtual router per (af, vrid).  The replay mirrors that: a CaseRun
owns the interface's VrrpInstance objects, drives them with the
recorded inputs (decoded advertisements, master-down timers, ibus
interface/address events, config changes) and asserts:

- the protocol plane: Vrrp advertisements plus the gratuitous ARP /
  unsolicited neighbor-advertisement bursts on master transitions;
- the ibus plane: MacvlanAdd/Del and virtual-address add/del requests;
- the northbound-state plane (per-instance oper state).
"""

from __future__ import annotations

import json
import re
from ipaddress import ip_address, ip_interface
from pathlib import Path

from holo_tpu.protocols.vrrp import (
    VrrpConfig,
    VrrpInstance,
    VrrpPacket,
    VrrpState,
)
from holo_tpu.tools.refjson import Unsupported, subset_match
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import EventLoop, VirtualClock

VRRP_DIR = Path("/root/reference/holo-vrrp/tests/conformance")


def case_map(conf_dir: Path = VRRP_DIR) -> dict[str, tuple[str, str]]:
    out = {}
    text = (conf_dir / "mod.rs").read_text()
    for m in re.finditer(
        r'run_test(?:_topology)?::<[^(]*\(\s*"([^"]+)",\s*"([^"]+)",\s*"([^"]+)"',
        text,
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


class _TxCapture(NetIo):
    def __init__(self):
        self.log = []

    def send(self, ifname, src, dst, data):
        self.log.append((ifname, src, dst, data))


def _virtual_mac(af: int, vrid: int) -> list[int]:
    return [0, 0, 0x5E, 0, 1 if af == 4 else 2, vrid]


def _mvlan_name(af: int, vrid: int) -> str:
    return f"mvlan{af}-vrrp-{vrid}"


def _pkt_from_json(j: dict) -> tuple[VrrpPacket, int]:
    v = j["version"]
    if v == "V2":
        version, af = 2, 4
    elif v == {"V3": "Ipv4"} or v == "V3":
        version, af = 3, 4
    else:
        version, af = 3, 6
    return (
        VrrpPacket(
            version=version,
            vrid=j["vrid"],
            priority=j["priority"],
            max_advert_int=j.get("adver_int", 1),
            addresses=[ip_address(a) for a in j.get("ip_addresses", [])],
            af=af,
        ),
        af,
    )


def _pkt_to_json(pkt: VrrpPacket) -> dict:
    if pkt.version == 2:
        version = "V2"
    else:
        version = {"V3": "Ipv4" if pkt.af == 4 else "Ipv6"}
    return {
        "version": version,
        "hdr_type": 1,
        "vrid": pkt.vrid,
        "priority": pkt.priority,
        "count_ip": len(pkt.addresses),
        "adver_int": pkt.max_advert_int,
        "checksum": 0,
        "ip_addresses": [str(a) for a in pkt.addresses],
    }


class CaseRun:
    def __init__(self, topo_dir: Path, rt: str):
        self.loop = EventLoop(clock=VirtualClock())
        self.tx = _TxCapture()
        self.rt_dir = topo_dir / rt
        cfg = json.loads((self.rt_dir / "config.json").read_text())
        self.ibus_log: list = []
        self.tx_extra: list = []  # structured Arp/NAdv emissions
        self.instances: dict = {}  # (af, vrid) -> VrrpInstance
        self.inst_conf: dict = {}  # (af, vrid) -> config node
        self.parent: str | None = None
        self.parent_v4 = None
        self.parent_v6_ll = None
        self.addrs: dict = {}  # ifname -> [ip_interface]
        self.ifindex: dict = {}
        self.oper_up: set = set()
        self.last_state: dict = {}
        for iface in cfg["ietf-interfaces:interfaces"]["interface"]:
            for af, ip_key in ((4, "ietf-ip:ipv4"), (6, "ietf-ip:ipv6")):
                vr = (iface.get(ip_key) or {}).get("ietf-vrrp:vrrp") or {}
                for inst in vr.get("vrrp-instance", []):
                    self.parent = iface["name"]
                    self.inst_conf[(af, inst["vrid"])] = inst
        if self.parent is None:
            raise Unsupported("no vrrp instances configured")

    # -- instance lifecycle

    def _ensure_instances(self) -> None:
        if self.parent not in self.oper_up:
            return
        for (af, vrid), conf in self.inst_conf.items():
            if (af, vrid) in self.instances:
                continue
            self._create_instance(af, vrid, conf)

    def _create_instance(self, af: int, vrid: int, conf: dict) -> None:
        version_s = conf.get(
            "version", "vrrp:vrrp-v2" if af == 4 else "vrrp:vrrp-v3"
        )
        version = 2 if version_s.endswith("v2") else 3
        if af == 4:
            addr_list = (conf.get("virtual-ipv4-addresses") or {}).get(
                "virtual-ipv4-address", []
            )
            addrs = [ip_address(a["ipv4-address"]) for a in addr_list]
            advert = conf.get("advertise-interval-sec", 1)
        else:
            addr_list = (conf.get("virtual-ipv6-addresses") or {}).get(
                "virtual-ipv6-address", []
            )
            addrs = [ip_address(a["ipv6-address"]) for a in addr_list]
            advert = conf.get("advertise-interval-centi-sec", 100) / 100.0
        src = self.parent_v4 if af == 4 else self.parent_v6_ll
        # The virtual router rides a macvlan with the virtual MAC.
        self.ibus_log.append(
            (
                "MacvlanAdd",
                {
                    "parent_ifname": self.parent,
                    "ifname": _mvlan_name(af, vrid),
                    "mac_addr": _virtual_mac(af, vrid),
                },
            )
        )
        inst = VrrpInstance(
            f"vrrp-{af}-{vrid}",
            VrrpConfig(
                vrid=vrid,
                ifname=self.parent,
                version=version,
                af=af,
                priority=conf.get("priority", 100),
                advert_interval=advert,
                addresses=addrs,
            ),
            src if src is not None else ip_address("0.0.0.0"),
            self.tx,
            on_state=lambda st, a=af, v=vrid: self._state_change(a, v, st),
            garp_cb=lambda addr, a=af, v=vrid: self.tx_extra.append(
                ("garp", a, v, addr)
            ),
        )
        self.loop.register(inst)
        self.instances[(af, vrid)] = inst
        self.last_state[(af, vrid)] = VrrpState.INITIALIZE
        # Startup waits for the kernel's macvlan confirmation (the
        # recorded InterfaceUpd for mvlanX-vrrp-N).
        if _mvlan_name(af, vrid) in self.oper_up:
            inst.startup()
        self.loop.run_until_idle()

    def _remove_instance(self, af: int, vrid: int) -> None:
        inst = self.instances.pop((af, vrid), None)
        if inst is None:
            return
        # No address withdrawal first: deleting the macvlan removes its
        # addresses with it (recorded nb-config-instance2 emits ONLY the
        # MacvlanDel).
        inst.shutdown()
        self.ibus_log.append(
            ("MacvlanDel", {"ifname": _mvlan_name(af, vrid)})
        )

    def _state_change(self, af: int, vrid: int, state: VrrpState) -> None:
        inst = self.instances.get((af, vrid))
        prev = self.last_state.get((af, vrid))
        self.last_state[(af, vrid)] = state
        mvlan = _mvlan_name(af, vrid)
        if state == VrrpState.MASTER and inst is not None:
            for a in inst.config.addresses:
                plen = 32 if af == 4 else 128
                self.ibus_log.append(
                    (
                        "InterfaceIpAddRequest",
                        {"ifname": mvlan, "addr": f"{a}/{plen}"},
                    )
                )
        elif inst is not None and prev == VrrpState.MASTER:
            self._withdraw_addrs(af, vrid, inst)

    def _withdraw_addrs(self, af: int, vrid: int, inst) -> None:
        mvlan = _mvlan_name(af, vrid)
        for a in inst.config.addresses:
            plen = 32 if af == 4 else 128
            self.ibus_log.append(
                (
                    "InterfaceIpDelRequest",
                    {"ifname": mvlan, "addr": f"{a}/{plen}"},
                )
            )

    # -- event application

    def apply_ibus(self, ev: dict) -> None:
        if "InterfaceUpd" in ev:
            upd = ev["InterfaceUpd"]
            ifname = upd["ifname"]
            if upd.get("ifindex"):
                self.ifindex[ifname] = upd["ifindex"]
            flags_s = upd.get("flags")
            operative = (
                "OPERATIVE" in flags_s if flags_s is not None else True
            )
            if operative:
                self.oper_up.add(ifname)
                self._ensure_instances()
                # Macvlan confirmation starts the pending instance.
                for (af, vrid), inst in self.instances.items():
                    if (
                        _mvlan_name(af, vrid) == ifname
                        and inst.state == VrrpState.INITIALIZE
                    ):
                        inst.startup()
            else:
                self.oper_up.discard(ifname)
                if ifname == self.parent:
                    for (af, vrid), inst in list(self.instances.items()):
                        if inst.state == VrrpState.MASTER:
                            self._withdraw_addrs(af, vrid, inst)
                        inst.shutdown()
                    self.loop.run_until_idle()
                else:
                    # A macvlan going away stops its virtual router.
                    for (af, vrid), inst in list(self.instances.items()):
                        if _mvlan_name(af, vrid) != ifname:
                            continue
                        if inst.state == VrrpState.MASTER:
                            self._withdraw_addrs(af, vrid, inst)
                        inst.shutdown()
                        self.last_state[(af, vrid)] = VrrpState.INITIALIZE
                    self.loop.run_until_idle()
        elif "InterfaceAddressAdd" in ev:
            upd = ev["InterfaceAddressAdd"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            self.addrs.setdefault(upd["ifname"], []).append(addr)
            if upd["ifname"] == self.parent:
                if addr.version == 4 and self.parent_v4 is None:
                    self.parent_v4 = addr.ip
                if addr.version == 6 and addr.ip.is_link_local:
                    self.parent_v6_ll = addr.ip
                self._ensure_instances()
                # Late-arriving parent addresses become the advert source.
                for (af, _vrid), inst in self.instances.items():
                    src = self.parent_v4 if af == 4 else self.parent_v6_ll
                    if src is not None and int(inst.iface_addr) == 0:
                        inst.iface_addr = src
        elif "InterfaceAddressDel" in ev:
            upd = ev["InterfaceAddressDel"]
            try:
                addr = ip_interface(upd["addr"])
            except ValueError:
                return
            lst = self.addrs.get(upd["ifname"]) or []
            if addr in lst:
                lst.remove(addr)
        else:
            raise Unsupported(f"ibus {next(iter(ev))}")
        self.loop.run_until_idle()

    def apply_protocol(self, ev: dict) -> None:
        if "VrrpNetRxPacket" in ev:
            rx = ev["VrrpNetRxPacket"]
            pj = rx.get("packet", {})
            if "Err" in pj:
                return
            pkt, af = _pkt_from_json(pj.get("Ok", pj))
            inst = self.instances.get((af, pkt.vrid))
            if inst is not None:
                inst.rx_packet(ip_address(rx["src"]), pkt)
        elif "MasterDownTimer" in ev:
            sub = ev["MasterDownTimer"]
            af = 6 if sub.get("version") == {"V3": "Ipv6"} else 4
            inst = self.instances.get((af, sub.get("vrid")))
            if inst is not None and inst.state == VrrpState.BACKUP:
                inst._become_master()
        else:
            raise Unsupported(f"protocol {next(iter(ev))}")
        self.loop.run_until_idle()

    def bring_up(self) -> None:
        for line in (self.rt_dir / "events.jsonl").read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])

    # -- config changes

    def apply_config_change(self, tree: dict) -> None:
        for iface in tree.get("ietf-interfaces:interfaces", {}).get(
            "interface", []
        ):
            for af, ip_key in ((4, "ietf-ip:ipv4"), (6, "ietf-ip:ipv6")):
                vr = (iface.get(ip_key) or {}).get("ietf-vrrp:vrrp") or {}
                for inst_node in vr.get("vrrp-instance", []):
                    vrid = inst_node["vrid"]
                    op = (inst_node.get("@") or {}).get("yang:operation")
                    if op == "delete":
                        self._remove_instance(af, vrid)
                        self.inst_conf.pop((af, vrid), None)
                        continue
                    if op == "create":
                        self.parent = iface["name"]
                        self.inst_conf[(af, vrid)] = inst_node
                        self._ensure_instances()
                        continue
                    # Virtual-address list changes.
                    key = (
                        "virtual-ipv4-addresses"
                        if af == 4
                        else "virtual-ipv6-addresses"
                    )
                    akey = "ipv4-address" if af == 4 else "ipv6-address"
                    inst = self.instances.get((af, vrid))
                    for a in (inst_node.get(key) or {}).get(
                        f"virtual-{akey}", []
                    ):
                        aop = (a.get("@") or {}).get("yang:operation")
                        addr = ip_address(a[akey])
                        plen = 32 if af == 4 else 128
                        mvlan = _mvlan_name(af, vrid)
                        if inst is None:
                            continue
                        if aop == "delete":
                            if addr in inst.config.addresses:
                                inst.config.addresses.remove(addr)
                                if inst.state == VrrpState.MASTER:
                                    self.ibus_log.append(
                                        (
                                            "InterfaceIpDelRequest",
                                            {
                                                "ifname": mvlan,
                                                "addr": f"{addr}/{plen}",
                                            },
                                        )
                                    )
                        elif aop == "create":
                            if addr not in inst.config.addresses:
                                inst.config.addresses.append(addr)
                                if inst.state == VrrpState.MASTER:
                                    self.ibus_log.append(
                                        (
                                            "InterfaceIpAddRequest",
                                            {
                                                "ifname": mvlan,
                                                "addr": f"{addr}/{plen}",
                                            },
                                        )
                                    )
        self.loop.run_until_idle()

    # -- output planes

    def drain_tx(self):
        out = []
        for ifname, src, dst, data in self.tx.log:
            # The only raw frames are advertisements; recover the AF by
            # the instance that sent on this circuit.
            for (af, _vrid), inst in self.instances.items():
                try:
                    pkt = VrrpPacket.decode(data, af=af)
                except Exception:
                    continue
                if pkt.vrid == inst.config.vrid:
                    out.append(("vrrp", src, pkt))
                    break
        self.tx.log.clear()
        for kind, af, vrid, addr in self.tx_extra:
            out.append(("garp", (af, vrid), addr))
        self.tx_extra.clear()
        return out

    def compare_protocol_output(self, expected_lines: list[dict]) -> list[str]:
        problems = []
        ours = []
        for entry in self.drain_tx():
            if entry[0] == "vrrp":
                _k, src, pkt = entry
                ours.append(
                    {
                        "Vrrp": {
                            "packet": {
                                "ip": {"src_address": str(src)},
                                "vrrp": _pkt_to_json(pkt),
                            }
                        }
                    }
                )
            else:
                _k, (af, vrid), addr = entry
                mvlan_idx = self.ifindex.get(_mvlan_name(af, vrid), 0)
                mac = _virtual_mac(af, vrid)
                if af == 4:
                    ours.append(
                        {
                            "Arp": {
                                "vrid": vrid,
                                "ifindex": mvlan_idx,
                                "eth_hdr": {
                                    "dst_mac": [255] * 6,
                                    "src_mac": mac,
                                    "ethertype": 2054,
                                },
                                "arp_hdr": {
                                    "sender_hw_address": mac,
                                    "sender_proto_address": str(addr),
                                    "target_proto_address": str(addr),
                                },
                            }
                        }
                    )
                else:
                    ours.append(
                        {
                            "NAdv": {
                                "vrid": vrid,
                                "ifindex": mvlan_idx,
                                "nadv_hdr": {"target_address": str(addr)},
                            }
                        }
                    )
        unmatched = list(ours)
        for exp in expected_lines:
            tx = exp.get("NetTxPacket")
            if tx is None:
                problems.append(f"unsupported output {next(iter(exp))}")
                continue
            # Checksums are environment-dependent: drop them from the
            # expected VRRP header before the subset match.
            tx = json.loads(json.dumps(tx))
            if "Vrrp" in tx:
                tx["Vrrp"]["packet"]["vrrp"].pop("checksum", None)
                tx["Vrrp"]["packet"].get("ip", {}).pop("total_length", None)
            hit = next(
                (
                    i
                    for i, got in enumerate(unmatched)
                    if subset_match(tx, got)
                ),
                None,
            )
            if hit is None:
                problems.append(
                    "expected tx not sent: " + json.dumps(tx)[:150]
                )
            else:
                unmatched.pop(hit)
        for got in unmatched:  # two-sided (stub/mod.rs:320-429)
            problems.append("unexpected tx: " + json.dumps(got)[:150])
        return problems

    def drain_ibus(self):
        out = self.ibus_log[:]
        self.ibus_log.clear()
        return out

    def compare_ibus(self, expected_lines: list[dict]) -> list[str]:
        problems = []
        unmatched = [{k: v} for k, v in self.drain_ibus()]
        for exp in expected_lines:
            if "InterfaceSub" in exp or "InterfaceUnsub" in exp:
                continue
            hit = next(
                (
                    i
                    for i, got in enumerate(unmatched)
                    if subset_match(exp, got)
                ),
                None,
            )
            if hit is None:
                problems.append(
                    "expected ibus msg not sent: " + json.dumps(exp)[:140]
                )
            else:
                unmatched.pop(hit)
        for got in unmatched:  # two-sided: extra ibus emissions fail
            problems.append(
                "unexpected ibus msg: " + json.dumps(got)[:140]
            )
        return problems

    def compare_state(self, state: dict) -> list[str]:
        problems = []
        for iface in state.get("ietf-interfaces:interfaces", {}).get(
            "interface", []
        ):
            for af, ip_key in ((4, "ietf-ip:ipv4"), (6, "ietf-ip:ipv6")):
                vr = (iface.get(ip_key) or {}).get("ietf-vrrp:vrrp") or {}
                for inst_node in vr.get("vrrp-instance", []):
                    vrid = inst_node["vrid"]
                    want = inst_node.get("state")
                    if want is None:
                        continue
                    inst = self.instances.get((af, vrid))
                    got = (
                        inst.state.value if inst is not None else "initialize"
                    )
                    if got != want:
                        problems.append(
                            f"af{af} vrid {vrid}: state {got} != {want}"
                        )
        return problems


def run_case(case_dir: Path, topo: str, rt: str):
    run = CaseRun(VRRP_DIR / "topologies" / topo, rt)
    try:
        run.bring_up()
    except Unsupported as e:
        return "skip", f"bring-up: {e}"
    run.drain_tx()
    run.drain_ibus()

    steps = sorted(
        {f.name.split("-")[0] for f in case_dir.iterdir() if f.name[0].isdigit()}
    )
    problems = []
    for step in steps:
        run.drain_ibus()
        try:
            for kind in ("ibus", "protocol"):
                f = case_dir / f"{step}-input-{kind}.jsonl"
                if f.exists():
                    for line in f.read_text().splitlines():
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if kind == "ibus":
                            run.apply_ibus(ev)
                        else:
                            run.apply_protocol(ev)
            f = case_dir / f"{step}-input-northbound-config-change.json"
            if f.exists():
                run.apply_config_change(json.loads(f.read_text()))
        except Unsupported as e:
            return "skip", f"step {step}: {e}"
        out_proto = case_dir / f"{step}-output-protocol.jsonl"
        if out_proto.exists():
            expected = [
                json.loads(l)
                for l in out_proto.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}"
                for p in run.compare_protocol_output(expected)
            ]
        else:
            run.drain_tx()
        out_ibus = case_dir / f"{step}-output-ibus.jsonl"
        if out_ibus.exists():
            expected = [
                json.loads(l)
                for l in out_ibus.read_text().splitlines()
                if l.strip()
            ]
            problems += [
                f"step {step}: {p}" for p in run.compare_ibus(expected)
            ]
        out_state = case_dir / f"{step}-output-northbound-state.json"
        if out_state.exists():
            state = json.loads(out_state.read_text())
            problems += [
                f"step {step}: {p}" for p in run.compare_state(state)
            ]
    return ("pass", "") if not problems else ("fail", "; ".join(problems[:6]))


def run_all(conf_dir: Path = VRRP_DIR):
    results = {}
    for case, (topo, rt) in sorted(case_map(conf_dir).items()):
        case_dir = conf_dir / case
        if not case_dir.is_dir():
            continue
        try:
            results[case] = run_case(case_dir, topo, rt)
        except Exception as e:  # noqa: BLE001 — survey run must not die
            results[case] = ("fail", f"exception: {type(e).__name__}: {e}")
    return results


if __name__ == "__main__":
    res = run_all()
    by = {"pass": [], "fail": [], "skip": []}
    for case, (status, detail) in sorted(res.items()):
        by[status].append(case)
        if status != "pass":
            print(f"{status:5} {case}: {detail[:170]}")
    print(
        f"\npass {len(by['pass'])} fail {len(by['fail'])} "
        f"skip {len(by['skip'])} / {len(res)}"
    )

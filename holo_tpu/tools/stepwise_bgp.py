"""BGP topology conformance: replay the reference's recorded snapshots.

Drives holo-bgp/tests/conformance/topologies (10 router snapshots across
topo1-1 eBGP mesh and topo2-1 iBGP/multipath) through the live BgpEngine,
replaying each router's recorded view — TCP accept/connect, wire messages,
policy-worker results, decision-process triggers, nexthop-tracking
updates — and comparing ALL FOUR recorded output planes:

- protocol: every SendMessage/SendMessageList/UpdateCapabilities emitted
  during bring-up (multiset over flattened messages);
- ibus: RouterIdSub / RouteRedistributeSub / NexthopTrack(+Untrack) /
  RouteIpAdd / RouteIpDel (multiset);
- northbound-notif: established / backward-transition events (multiset);
- northbound-state: the full ietf-bgp operational tree.  Attr-set indexes
  are XxHash64 outputs in the recording and engine-local ids here, so the
  comparison dereferences every attr-index into the attr-set CONTENTS on
  both sides before the deep diff — structurally exact, hash-free.
"""

from __future__ import annotations

import json
from pathlib import Path

from holo_tpu.protocols.bgp_engine import (
    AfiSafiCfg,
    BgpEngine,
    NeighborCfg,
    origin_from_json,
    _attrs_from_json,
)

BGP_DIR = Path("/root/reference/holo-bgp/tests/conformance/topologies")

AFS_MAP = {"Ipv4Unicast": "ipv4-unicast", "Ipv6Unicast": "ipv6-unicast"}


def _loads_lenient(text: str):
    return json.JSONDecoder().raw_decode(text)[0]


class CaseRun:
    def __init__(self, rt_dir: Path):
        self.rt_dir = rt_dir
        self.tx_log: list = []
        self.ibus_log: list = []
        self.notif_log: list = []
        self.engine = BgpEngine(
            "test",
            send_cb=lambda kind, payload: self.tx_log.append(
                {"NbrTx": {kind: payload}}
            ),
            ibus_cb=lambda kind, payload: self.ibus_log.append(
                {kind: payload}
            ),
            notif_cb=lambda data: self.notif_log.append(data),
        )
        self._apply_config(
            _loads_lenient((rt_dir / "config.json").read_text())
        )

    def _apply_config(self, cfg: dict) -> None:
        protos = cfg["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ]
        proto = next(p["ietf-bgp:bgp"] for p in protos if "ietf-bgp:bgp" in p)
        eng = self.engine
        g = proto.get("global", {})
        eng.asn = g.get("as", 0)
        eng.cfg_identifier = g.get("identifier")
        for af in (g.get("afi-safis") or {}).get("afi-safi", []):
            name = af["name"].split(":")[-1]
            eng.afi_safi_enabled.add(name)
            fam = af.get("ipv4-unicast") or af.get("ipv6-unicast") or {}
            for redist in fam.get("holo-bgp:redistribution", []):
                eng.redistribution.setdefault(name, set()).add(
                    redist["type"].split(":")[-1]
                )
            mp = af.get("use-multiple-paths")
            if mp is not None:
                ebgp = mp.get("ebgp") or {}
                ibgp = mp.get("ibgp") or {}
                eng.multipath[name] = {
                    "enabled": mp.get("enabled", False),
                    "ebgp_max": ebgp.get("maximum-paths", 1),
                    "ibgp_max": ibgp.get("maximum-paths", 1),
                    "allow_multiple_as": ebgp.get(
                        "allow-multiple-as", False
                    ),
                }
        for nbr in (proto.get("neighbors") or {}).get("neighbor", []):
            ncfg = NeighborCfg(peer_as=nbr.get("peer-as", 0))
            transport = nbr.get("transport") or {}
            ncfg.local_address = transport.get("local-address")
            ncfg.passive_mode = (transport.get("passive-mode")) or False
            for af in (nbr.get("afi-safis") or {}).get("afi-safi", []):
                name = af["name"].split(":")[-1]
                pol = af.get("apply-policy") or {}
                ncfg.afi_safi[name] = AfiSafiCfg(
                    enabled=af.get("enabled", False),
                    default_import_policy=pol.get(
                        "default-import-policy", "reject-route"
                    ),
                    default_export_policy=pol.get(
                        "default-export-policy", "reject-route"
                    ),
                )
            eng.neighbor_cfg[str(nbr["remote-address"])] = ncfg

    # ---- events

    def apply_ibus(self, ev: dict) -> None:
        kind, body = next(iter(ev.items()))
        eng = self.engine
        if kind == "RouterIdUpdate":
            eng.router_id_update(str(body) if body is not None else None)
        elif kind == "NexthopUpd":
            eng.nexthop_update(str(body["addr"]), body.get("metric"))
        elif kind in (
            "PolicyUpd",
            "PolicyMatchSetsUpd",
            "PolicyDel",
            "RouteRedistributeSub",
        ):
            pass  # policy evaluation results arrive as recorded events
        elif kind == "RouteRedistributeAdd":
            pass  # triggers worker policy; result is a recorded event
        elif kind == "RouteRedistributeDel":
            afs = (
                "ipv6-unicast" if ":" in body["prefix"] else "ipv4-unicast"
            )
            table = eng.tables[afs]
            dest = table.prefixes.get(body["prefix"])
            if dest is not None:
                dest.redistribute = None
                table.queued.add(body["prefix"])
        elif kind in (
            "RouteIpAdd",
            "RouteIpDel",
            "InterfaceUpd",
            "InterfaceAddressAdd",
            "InterfaceAddressDel",
        ):
            pass  # own routes echoed back / iface events BGP ignores
        else:
            raise ValueError(f"unsupported ibus {kind}")

    def apply_protocol(self, ev: dict) -> None:
        kind, body = next(iter(ev.items()))
        eng = self.engine
        if kind == "TcpAccept":
            eng.tcp_accept(body["conn_info"])
        elif kind == "TcpConnect":
            eng.tcp_connect(body["conn_info"])
        elif kind == "NbrRx":
            msg = body["msg"]
            if "Err" in msg:
                err = msg["Err"]
                ekind = err if isinstance(err, str) else next(iter(err))
                if ekind == "TcpConnClosed":
                    eng.nbr_rx(str(body["nbr_addr"]), "conn-closed")
                else:
                    raise ValueError(f"nbr rx err {ekind}")
            else:
                eng.nbr_rx(str(body["nbr_addr"]), msg["Ok"])
        elif kind == "NbrTimer":
            eng.nbr_timer(str(body["nbr_addr"]), body["timer"])
        elif kind == "TriggerDecisionProcess":
            eng.run_decision_process()
        elif kind == "PolicyResult":
            self._apply_policy_result(body)
        else:
            raise ValueError(f"unsupported protocol {kind}")

    def _apply_policy_result(self, pr: dict) -> None:
        eng = self.engine
        if "Redistribute" in pr:
            body = pr["Redistribute"]
            afs = AFS_MAP[body["afi_safi"]]
            eng.policy_result_redistribute(
                afs, body["prefix"], _result_from_json(body["result"])
            )
        elif "Neighbor" in pr:
            body = pr["Neighbor"]
            afs = AFS_MAP[body["afi_safi"]]
            routes = [
                (prefix, _result_from_json(result))
                for prefix, result in body["routes"]
            ]
            eng.policy_result_neighbor(
                body["policy_type"],
                str(body["nbr_addr"]),
                afs,
                routes,
            )
        else:
            raise ValueError(f"policy result {next(iter(pr))}")

    def bring_up(self) -> None:
        for line in (
            (self.rt_dir / "events.jsonl").read_text().splitlines()
        ):
            line = line.strip()
            if not line:
                continue
            ev = _loads_lenient(line)
            if "Ibus" in ev:
                self.apply_ibus(ev["Ibus"])
            elif "Protocol" in ev:
                self.apply_protocol(ev["Protocol"])

    # ---- comparisons

    def compare_protocol(self, expected_lines: list[dict]) -> list[str]:
        def flatten(entries):
            out = []
            for e in entries:
                body = e.get("NbrTx", {})
                if "SendMessage" in body:
                    m = body["SendMessage"]
                    out.append(
                        (
                            "msg",
                            str(m["nbr_addr"]),
                            _canon_msg(m["msg"]),
                        )
                    )
                elif "SendMessageList" in body:
                    m = body["SendMessageList"]
                    for msg in m["msg_list"]:
                        out.append(
                            (
                                "msg",
                                str(m["nbr_addr"]),
                                _canon_msg(msg),
                            )
                        )
                elif "UpdateCapabilities" in body:
                    out.append(
                        (
                            "caps",
                            json.dumps(
                                body["UpdateCapabilities"],
                                sort_keys=True,
                            ),
                        )
                    )
            return out

        return _multiset_diff(
            flatten(expected_lines), flatten(self.tx_log), "protocol"
        )

    def compare_ibus(self, expected_lines: list[dict]) -> list[str]:
        def canon(entries):
            return [json.dumps(e, sort_keys=True) for e in entries]

        return _multiset_diff(
            canon(expected_lines), canon(self.ibus_log), "ibus"
        )

    def compare_notifs(self, expected_lines: list[dict]) -> list[str]:
        def canon(entries):
            return [json.dumps(e, sort_keys=True) for e in entries]

        return _multiset_diff(
            canon(expected_lines), canon(self.notif_log), "notif"
        )

    def compare_state(self, expected: dict) -> list[str]:
        exp = expected["ietf-routing:routing"]["control-plane-protocols"][
            "control-plane-protocol"
        ][0]["ietf-bgp:bgp"]
        got = self.engine.northbound_state()
        return _tree_diff(
            _deref_attr_indexes(exp), _deref_attr_indexes(got), "bgp"
        )


def _result_from_json(j):
    if j == "Reject" or (isinstance(j, dict) and "Reject" in j):
        return None
    body = j["Accept"]
    return {
        "origin": origin_from_json(body["origin"]),
        "route_type": body["route_type"],
        "attrs": _attrs_from_json(body.get("attrs", {})),
    }


def _canon_msg(msg: dict) -> str:
    """Canonical string for a protocol message; Update prefix lists are
    sorted (BTreeSet order on both sides, but belt-and-braces)."""
    msg = json.loads(json.dumps(msg))
    if "Update" in msg:
        upd = msg["Update"]
        for key in ("reach", "unreach"):
            if upd.get(key):
                upd[key]["prefixes"] = sorted(upd[key]["prefixes"])
        for key in ("mp_reach", "mp_unreach"):
            if upd.get(key):
                for body in upd[key].values():
                    if "prefixes" in body:
                        body["prefixes"] = sorted(body["prefixes"])
    return json.dumps(msg, sort_keys=True)


def _multiset_diff(want, got, plane: str) -> list[str]:
    problems = []
    got = list(got)
    for item in want:
        if item in got:
            got.remove(item)
        else:
            problems.append(f"{plane} missing: {str(item)[:200]}")
    for item in got:
        problems.append(f"{plane} unexpected: {str(item)[:200]}")
    return problems


def _deref_attr_indexes(tree):
    """Replace attr-index leaf values with the attr-set contents and drop
    the raw indexes (engine-local vs XxHash64 in the recording)."""
    tree = json.loads(json.dumps(tree))
    sets = {}
    for attr_set in (
        tree.get("rib", {}).get("attr-sets", {}).get("attr-set", [])
    ):
        sets[str(attr_set["index"])] = attr_set.get("attributes", {})

    def walk(node):
        if isinstance(node, dict):
            if "attr-index" in node:
                node["attr-index"] = sets.get(
                    str(node["attr-index"]), node["attr-index"]
                )
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(tree)
    if "attr-sets" in tree.get("rib", {}):
        tree["rib"]["attr-sets"] = {
            "attr-set": sorted(
                (
                    {"attributes": s.get("attributes", {})}
                    for s in tree["rib"]["attr-sets"]["attr-set"]
                ),
                key=lambda s: json.dumps(s, sort_keys=True),
            )
        }
    return tree


_LIST_KEYS = {
    "neighbor": ("remote-address", "neighbor-address"),
    "afi-safi": ("name",),
    "route": ("prefix",),
    "attr-set": (),
    "advertised-capabilities": ("index",),
    "received-capabilities": ("index",),
    "segment": (),
}


def _tree_diff(exp, got, path: str) -> list[str]:
    problems: list[str] = []
    if isinstance(exp, dict) and isinstance(got, dict):
        for k in exp:
            if k not in got:
                problems.append(f"{path}/{k}: missing")
            else:
                problems += _tree_diff(exp[k], got[k], f"{path}/{k}")
        for k in got:
            if k not in exp:
                problems.append(f"{path}/{k}: unexpected")
        return problems
    if isinstance(exp, list) and isinstance(got, list):
        name = path.rsplit("/", 1)[-1]
        keys = _LIST_KEYS.get(name)

        def keyfn(entry):
            if keys and isinstance(entry, dict):
                return json.dumps(
                    [entry.get(k) for k in keys], sort_keys=True
                )
            return json.dumps(entry, sort_keys=True)

        exp_s = sorted(exp, key=keyfn)
        got_s = sorted(got, key=keyfn)
        if len(exp_s) != len(got_s):
            problems.append(
                f"{path}: list length {len(got_s)} != {len(exp_s)}"
            )
        for i, (e, g) in enumerate(zip(exp_s, got_s)):
            problems += _tree_diff(e, g, f"{path}[{i}]")
        return problems
    if exp != got:
        problems.append(f"{path}: {got!r} != {exp!r}")
    return problems


def run_router(topo: str, rt: str):
    rt_dir = BGP_DIR / topo / rt
    run = CaseRun(rt_dir)
    run.bring_up()
    problems = []
    out = rt_dir / "output"
    for fname, cmp in (
        ("protocol.jsonl", run.compare_protocol),
        ("ibus.jsonl", run.compare_ibus),
        ("northbound-notif.jsonl", run.compare_notifs),
    ):
        f = out / fname
        expected = (
            [
                _loads_lenient(line)
                for line in f.read_text().splitlines()
                if line.strip()
            ]
            if f.exists()
            else []
        )
        problems += cmp(expected)
    f = out / "northbound-state.json"
    if f.exists():
        problems += run.compare_state(_loads_lenient(f.read_text()))
    return ("pass", "") if not problems else (
        "fail", "; ".join(problems[:8])
    )


def run_all():
    results = {}
    for topo_dir in sorted(BGP_DIR.iterdir()):
        if not topo_dir.is_dir():
            continue
        for rt_dir in sorted(topo_dir.iterdir()):
            if not rt_dir.is_dir():
                continue
            name = f"{topo_dir.name}/{rt_dir.name}"
            try:
                results[name] = run_router(topo_dir.name, rt_dir.name)
            except Exception as e:  # noqa: BLE001 — sweep must not die
                results[name] = (
                    "fail",
                    f"exception: {type(e).__name__}: {e}",
                )
    return results


if __name__ == "__main__":
    import sys

    res = run_all()
    by = {"pass": [], "fail": []}
    for case, (status, detail) in sorted(res.items()):
        by.setdefault(status, []).append(case)
        if status != "pass" and "-v" in sys.argv:
            print(f"{status:5} {case}: {detail[:400]}")
    print(f"pass {len(by['pass'])} fail {len(by['fail'])} / {len(res)}")

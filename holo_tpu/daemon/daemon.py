"""Daemon assembly: loop + ibus + providers + northbound + gRPC.

Reference startup order: holo-daemon/src/northbound/core.rs:670-731
(interface → keychain → policy → system → routing), clients after
providers (:734-755).
"""

from __future__ import annotations

import logging
import os
from pathlib import Path

from holo_tpu.daemon.config import DaemonConfig
from holo_tpu.daemon.providers import (
    InterfaceProvider,
    KeychainProvider,
    PolicyProvider,
    RoutingProvider,
    SystemProvider,
)
from holo_tpu.northbound.core import Northbound
from holo_tpu.northbound.provider import Provider as NbProvider
from holo_tpu.routing.rib import Kernel
from holo_tpu.utils.ibus import Ibus
from holo_tpu.utils.netio import MockFabric, NetIo
from holo_tpu.utils.runtime import EventLoop, RealClock, VirtualClock
from holo_tpu.yang.modules import full_schema

log = logging.getLogger("holo_tpu.daemon")


class Daemon:
    """One holo_tpu daemon process (testable in-process: pass a virtual
    clock and a MockFabric netio)."""

    def __init__(
        self,
        config: DaemonConfig | None = None,
        clock=None,
        netio: NetIo | None = None,
        kernel: Kernel | None = None,
        loop: EventLoop | None = None,
        name: str = "",
    ):
        """``loop``/``name`` support multi-daemon simulations: several
        daemons sharing one virtual-clock loop get name-prefixed actors."""
        import threading

        self.config = config or DaemonConfig()
        self.loop = loop if loop is not None else EventLoop(clock=clock or RealClock())
        # The EventLoop is single-threaded by design; every external entry
        # point (gRPC worker threads, the main timer loop) must hold this
        # lock around loop access.
        self.lock = threading.RLock()
        self.name = name
        self._p = f"{name}." if name else ""

        # Preemptive isolation (reference holo-protocol/src/lib.rs:419-430,
        # [runtime] isolation = "threaded"): protocol instances each get
        # their own OS thread + loop; shared services stay on the primary
        # loop and reach instances through the router.  Requires the real
        # clock — virtual-clock (test) daemons stay cooperative, like the
        # reference's `testing` feature.
        self.instance_loops: dict = {}
        self.loop_router = None
        send_loop = self.loop
        if self.config.runtime.isolation == "threaded":
            if not isinstance(self.loop.clock, RealClock):
                # The reference's `testing` feature makes the same
                # downgrade: deterministic single-loop scheduling under
                # a virtual clock, threaded in production.  An operator
                # who EXPLICITLY asked for threaded deserves the
                # warning; the defaulted case downgrades quietly.
                msg = (
                    "isolation=threaded requires the real clock; "
                    "falling back to cooperative scheduling"
                )
                if self.config.runtime.isolation_explicit:
                    log.warning(msg)
                else:
                    log.debug(msg)
            else:
                from holo_tpu.utils.preempt import CallRunner, LoopRouter

                self.loop_router = LoopRouter(self.loop)
                send_loop = self.loop_router
                self.loop.register(
                    CallRunner(), name=f"{self._p}call-runner"
                )
        self.ibus = Ibus(send_loop)
        self.fabric = None
        if netio is None:
            self.fabric = MockFabric(send_loop)
            netio = self.fabric.sender_for
        elif isinstance(netio, MockFabric):
            self.fabric = netio
            netio = netio.sender_for
        self.netio = netio

        # Providers in reference startup order.
        self.interface = InterfaceProvider(self.ibus)
        self.keychain = KeychainProvider(self.ibus)
        self.policy = PolicyProvider(self.ibus)
        self.system = SystemProvider(self.ibus)
        # Durable state store (boot counters, GR info) next to the txn db
        # (reference: pickledb, holo-daemon/src/main.rs:148-157).
        self.nvstore = None
        if self.config.db_path:
            from holo_tpu.utils.nvstore import NvStore

            nv = Path(self.config.db_path)
            self.nvstore = NvStore(nv.with_name(nv.stem + "_nv.json"))
        self.routing = RoutingProvider(
            send_loop, self.ibus, netio, self.interface, kernel,
            prefix=self._p, policy_engine=self.policy.engine,
            keychains=self.keychain, nvstore=self.nvstore,
            yang_notify=self._dispatch_yang_notification,
        )
        if self.loop_router is not None:
            self.routing.instance_placer = self._place_instance
            self.routing.instance_unplacer = self._unplace_instance
        self.interface.routing_actor = f"{self._p}routing-rib"
        for p in (self.interface, self.keychain, self.policy, self.system, self.routing):
            # Through send_loop: with isolation the router's register()
            # attaches ITSELF as the provider's loop, so provider sends
            # keep reaching instances that live on their own threads.
            send_loop.register(p, name=self._p + p.name)

        db = Path(self.config.db_path) if self.config.db_path else None
        from holo_tpu.telemetry.provider import TelemetryStateProvider

        self.northbound = Northbound(
            full_schema(),
            [self.interface, self.keychain, self.policy, self.system,
             self.routing, _RuntimeStateProvider(self),
             TelemetryStateProvider()],
            db_path=db,
        )
        self._grpc_server = None
        self._telemetry_server = None

        # Event recorder (reference holo-protocol/src/lib.rs:266-269 +
        # holod.toml [event_recorder]): every message delivered on the
        # daemon loop is journaled BEFORE its actor handles it, so a
        # production incident can be replayed bit-for-bit through
        # `holo-tpu-cli replay` / utils.event_recorder.replay.  Protocol
        # instances register on this loop lazily at commit time, so the
        # loop-level hook covers them without per-instance wiring.
        self.recorder = None
        if self.config.event_recorder.enabled:
            from holo_tpu.utils.event_recorder import (
                EventRecorder,
                instrument,
            )

            self.recorder = EventRecorder(
                Path(self.config.event_recorder.dir)
                / f"{self.name or 'holo'}-events.jsonl"
            )
            instrument(self.loop, self.recorder)

        # Flight recorder + deep profiling ([telemetry], ISSUE 5): the
        # ring is armed here (process-wide — breaker/supervisor/SIGTERM
        # postmortem triggers all reach the same recorder) with THIS
        # daemon's loop clock, so virtual-clock runs produce
        # deterministic bundles and production stamps real time.
        tcfg = self.config.telemetry
        if tcfg.flight_buffer_entries:
            from holo_tpu.telemetry import flight

            flight.configure(
                entries=tcfg.flight_buffer_entries,
                postmortem_dir=tcfg.postmortem_dir,
                clock=self.loop.clock.now,
            )
        if tcfg.profile_device_time:
            from holo_tpu.telemetry import profiling

            profiling.set_device_profiling(True)
        # Dispatch observatory ([telemetry] observatory, ISSUE 12):
        # streaming sketches + roofline attribution + the warn-only
        # regression sentinel.  It feeds off the profiling sub-span
        # walls, so arming it arms device profiling too.
        if tcfg.observatory:
            from holo_tpu.telemetry import observatory, profiling

            profiling.set_device_profiling(True)
            observatory.configure(
                ledger_path=tcfg.observatory_ledger,
                peaks=tcfg.roofline_peaks,
            )
        # Device-trace capture ([telemetry] device-trace-dir, ISSUE 11
        # carry-over): one real jax.profiler.trace() around a seeded
        # SPF dispatch when a TPU is attached.  Relay-probe-aware — no
        # TPU yields an explicit `relay: not-used` row and never blocks
        # the boot.
        self._device_trace = None
        if tcfg.device_trace_dir:
            from holo_tpu.telemetry import profiling

            try:
                self._device_trace = profiling.capture_device_trace(
                    tcfg.device_trace_dir
                )
                log.info("device trace: %s", self._device_trace)
            except Exception as e:  # noqa: BLE001 — never a boot blocker
                self._device_trace = {
                    "relay": "not-used",
                    "captured": False,
                    "error": f"{type(e).__name__}: {e}",
                }
                log.warning("device trace capture failed: %s", e)
        # Convergence observatory ([telemetry] convergence-events,
        # ISSUE 6): causal event→FIB tracing on this daemon's loop
        # clock; timelines land in the flight ring when it is armed.
        if tcfg.convergence_events:
            from holo_tpu.telemetry import convergence

            convergence.configure(
                tcfg.convergence_events, clock=self.loop.clock.now
            )
        # SLO plane ([telemetry] slo, ISSUE 20): error budgets +
        # burn-rate sentinels graded from the convergence / shed /
        # relay streams the subsystems above produce.  The engine keeps
        # its default profiling clock (burn windows are REAL-time
        # quantities even when the loop clock is virtual).
        if tcfg.slo:
            from holo_tpu.telemetry import slo

            slo.configure(
                True,
                objectives=tcfg.slo_objectives or None,
                fast_window=tcfg.slo_fast_window,
                slow_window=tcfg.slo_slow_window,
                fast_burn=tcfg.slo_fast_burn,
            )
        # Synthetic canary ([telemetry] canary, ISSUE 20): a standing
        # probe instance on THIS loop — heartbeat topology deltas
        # through the real dispatch path as background tickets, closing
        # at fib_commit (config validation guarantees the convergence
        # tracker above is armed).
        if tcfg.canary:
            from holo_tpu.telemetry import canary

            canary.configure(
                True,
                loop=self.loop,
                period=tcfg.canary_period,
                deadline=tcfg.canary_deadline,
            )

        # Actor supervision ([resilience], holo_tpu/resilience/): crashed
        # protocol actors restart under an exponential-backoff policy
        # with deterministic jitter; crash loops park the actor in a
        # permanent degraded state.  The supervisor is itself an actor
        # on the primary loop, so with the event recorder enabled every
        # crash notice / restart tick is journaled and replayable.
        self.supervisor = None
        rc = self.config.resilience
        if rc.supervision:
            from holo_tpu.resilience.supervisor import (
                RestartPolicy,
                Supervisor,
            )

            self.supervisor = Supervisor(
                policy=RestartPolicy(
                    base_delay=rc.restart_base_delay,
                    max_delay=rc.restart_max_delay,
                    crash_loop_threshold=rc.crash_loop_threshold,
                    crash_loop_window=rc.crash_loop_window,
                ),
                name=f"{self._p}supervisor",
            ).install(self.loop)
            # Dispatch survivability (ISSUE 19): the process pipeline's
            # worker thread and the hung-dispatch sentinel ride the
            # same RestartPolicy as the protocol pumps (watch_pump
            # parity) — a worker death from any cause respawns under
            # backoff with the queued tickets intact.
            from holo_tpu.pipeline import process_pipeline
            from holo_tpu.resilience.watchdog import process_watchdog

            pipe = process_pipeline()
            if pipe is not None and not pipe.closed:
                self.supervisor.watch_worker(pipe, "pipeline")
            wd = process_watchdog()
            if wd is not None:
                self.supervisor.watch_worker(wd, wd.name)

    # -- preemptive instance placement ([runtime] isolation = "threaded")

    # Instance-side callbacks the providers install: these mutate shared
    # provider/RIB state and must run on the primary loop, not on the
    # instance's thread.
    _MARSHALLED_CALLBACKS = ("route_cb", "lib_cb", "on_state", "notif_cb")

    def _place_instance(self, inst):
        from holo_tpu.utils.preempt import (
            InstanceHandle,
            ThreadedLoop,
            _MarshalCall,
        )

        tl = ThreadedLoop(name=f"{self._p}inst-{inst.name}")
        if self.supervisor is not None:
            # Crashes on the instance's own thread marshal back to the
            # primary-loop supervisor as messages; the restart itself is
            # marshaled the other way (tl.send posts + wakes the pump)
            # so on_restart and held-mail redelivery run single-writer
            # on the instance's thread.
            self.supervisor.adopt(tl.loop, sender=tl.send)
            # The pump THREAD itself is supervised too: a loop-machinery
            # exception killing the pump respawns it under the same
            # restart policy instead of leaving the instance deaf.
            self.supervisor.watch_pump(tl)
        if self.recorder is not None:
            # Instance messages bypass the primary loop under isolation;
            # journal them on the instance's own loop (same recorder —
            # it serializes cross-thread appends).
            from holo_tpu.utils.event_recorder import instrument

            instrument(tl.loop, self.recorder)
        # Route BEFORE the pump starts: a send in the window lands on the
        # (not yet registered) remote loop and is reported undeliverable,
        # never silently swallowed by the primary loop.
        # Multi-actor nodes (the IS-IS L1/L2 pair) place BOTH actors on
        # the one loop — single-writer per thread still holds.
        subs = [inst]
        if hasattr(inst, "instances") and callable(inst.instances):
            # The node itself stays registered too: it is the packet
            # entry point that fans out to the per-level actors.
            subs += list(inst.instances())
        for sub in subs:
            self.loop_router.register_remote(sub.name, tl)
        # Per-interface Tx tasks (reference tasks.rs:288-348): packet
        # production decouples from the wire send; a slow interface
        # backpressures its own producer only.
        shared_netio = next(
            (
                n
                for n in (getattr(s, "netio", None) for s in subs)
                if n is not None
            ),
            None,
        )
        if shared_netio is not None:
            from holo_tpu.utils.txqueue import TxTaskNetIo

            wrapped = TxTaskNetIo(shared_netio)
            for sub in subs:
                if getattr(sub, "netio", None) is not None:
                    sub.netio = wrapped
        for sub in subs:
            tl.register(sub)
        # Provider-installed callbacks run as primary-loop messages.
        runner = f"{self._p}call-runner"
        for attr in self._MARSHALLED_CALLBACKS:
            cb = getattr(inst, attr, None)
            if cb is None or not callable(cb):
                continue
            setattr(
                inst,
                attr,
                (lambda cb: lambda *a: self.loop.send(
                    runner, _MarshalCall(cb, a)
                ))(cb),
            )
        tl.start()
        self.instance_loops[inst.name] = tl
        return InstanceHandle(inst, tl)

    def _unplace_instance(self, name: str) -> None:
        # Stop routing first (no new messages), then kill the pump, THEN
        # unregister: pending messages are dropped, matching cooperative
        # unregister semantics — a queued SPF result must not re-install
        # routes after _drop_instance_routes purged them.
        self.loop_router.unregister_remote(name)
        tl = self.instance_loops.pop(name, None)
        if tl is not None:
            if self.supervisor is not None:
                # Deliberate teardown is not a crash: drop the loop and
                # per-actor verdicts so a re-created instance under the
                # same name is supervised afresh.
                self.supervisor.unadopt(tl.loop)
            actors = list(tl.loop.actors)
            insts = [tl.loop.actors[a] for a in actors]
            for a in actors:  # multi-actor nodes route every sub-name
                self.loop_router.unregister_remote(a)
            tl.stop()
            for a in actors:
                tl.loop.unregister(a)
            closed = set()
            for inst in insts:
                netio = getattr(inst, "netio", None)
                if (
                    netio is not None
                    and hasattr(netio, "close")
                    and id(netio) not in closed
                ):
                    netio.close()  # drain + join the per-interface Tx tasks
                    closed.add(id(netio))

    # -- config entry points

    def candidate(self):
        with self.lock:
            return self.northbound.running.copy()

    def commit(self, candidate, **kw):
        with self.lock:
            txn = self.northbound.commit(candidate, **kw)
            # Commit atomicity REQUIRES pumping the loop under the lock:
            # a gNMI Get between commit and convergence would render
            # half-applied state.  self.lock is a reentrant RLock and
            # handlers run on THIS thread, so re-acquisition cannot
            # deadlock; the cost is commit-latency for concurrent
            # readers, which is the documented semantics.
            self.loop.run_until_idle()  # holo-lint: disable=HL202
        # Commit notifications fan out to every management surface
        # (gRPC Subscribe, gNMI Subscribe, ...), regardless of which one
        # performed the commit.
        for listener in list(getattr(self, "commit_listeners", [])):
            try:
                listener(txn)
            except Exception:
                log.exception("commit listener failed")
        return txn

    def add_commit_listener(self, fn) -> None:
        if not hasattr(self, "commit_listeners"):
            self.commit_listeners = []
        self.commit_listeners.append(fn)

    # -- YANG notifications (reference holo-northbound/src/notification.rs:
    # protocol instances emit, the daemon fans out to every management
    # surface's Subscribe stream)

    def _dispatch_yang_notification(self, payload: dict) -> None:
        for fn in list(getattr(self, "notification_listeners", [])):
            try:
                fn(payload)
            except Exception:
                log.exception("notification listener failed")

    def add_notification_listener(self, fn) -> None:
        if not hasattr(self, "notification_listeners"):
            self.notification_listeners = []
        self.notification_listeners.append(fn)

    # -- gRPC

    def start_grpc(self, address: str | None = None):
        from holo_tpu.daemon.grpc_server import serve

        self._grpc_server = serve(
            self,
            address or self.config.grpc.address,
            tls_cert=self.config.grpc.tls_cert,
            tls_key=self.config.grpc.tls_key,
        )
        return self._grpc_server

    def start_gnmi(self, address: str | None = None):
        from holo_tpu.daemon.gnmi_server import serve_gnmi

        self._gnmi_server = serve_gnmi(
            self,
            address or self.config.gnmi.address,
            tls_cert=self.config.gnmi.tls_cert,
            tls_key=self.config.gnmi.tls_key,
        )
        return self._gnmi_server

    def start_telemetry(self, address: str | None = None):
        """Prometheus text endpoint on a stdlib HTTP thread ([telemetry]
        config section; the gNMI/gRPC state subtree is always served)."""
        from holo_tpu import telemetry
        from holo_tpu.telemetry.prometheus import start_http_server

        self._telemetry_server = start_http_server(
            telemetry.registry(),
            address or self.config.telemetry.address,
        )
        return self._telemetry_server

    def stop(self):
        if self._telemetry_server is not None:
            self._telemetry_server.shutdown()
            # shutdown() only exits serve_forever; the listening fd must
            # be closed explicitly or a stop/start cycle races GC for
            # the port (EADDRINUSE).
            self._telemetry_server.server_close()
            self._telemetry_server = None
        if self.config.telemetry.trace_dump:
            from holo_tpu import telemetry

            try:
                telemetry.tracer().dump(self.config.telemetry.trace_dump)
            except OSError:
                log.exception("trace dump failed")
        if self.config.telemetry.observatory:
            # Close the final sentinel window: checkpoint() seeds and
            # compares every key once more and persists the baseline
            # when anything changed (writes only ever happen at
            # checkpoint boundaries — never on the dispatch thread).
            import sys as _sys

            obsm = _sys.modules.get("holo_tpu.telemetry.observatory")
            if obsm is not None and obsm.active() is not None:
                obsm.active().checkpoint()
        if self.config.telemetry.canary:
            # Stop the heartbeat timer before the instance loops drain:
            # a probe injected into a stopping loop would close as
            # unattributed and pollute the availability objective's
            # final window for no operational reason.
            import sys as _sys

            cam = _sys.modules.get("holo_tpu.telemetry.canary")
            if cam is not None and cam.active() is not None:
                cam.configure(False)
        if self.config.telemetry.slo:
            # Final budget settlement: trim windows, run every sentinel
            # check once more, and feed the latency sketches through the
            # observatory ledger (warn-only) so a short-lived daemon
            # still leaves one baseline row per objective behind.
            import sys as _sys

            slm = _sys.modules.get("holo_tpu.telemetry.slo")
            if slm is not None and slm.active() is not None:
                slm.active().checkpoint()
                slm.configure(False)
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=0.5)
        if getattr(self, "_gnmi_server", None) is not None:
            # serve_gnmi folds the fan-out ticker join into stop().
            self._gnmi_server.stop(grace=0.5)
        for name, tl in list(self.instance_loops.items()):
            if self.loop_router is not None:
                self.loop_router.unregister_remote(name)
            if self.supervisor is not None:
                self.supervisor.unadopt(tl.loop)
            inst = tl.loop.actors.get(name)
            tl.stop()
            netio = getattr(inst, "netio", None)
            if netio is not None and hasattr(netio, "close"):
                netio.close()  # drain + join the per-interface Tx tasks
        self.instance_loops.clear()
        if self.recorder is not None:
            # Flush AFTER the tx queues drained so the journal's tail
            # covers everything the daemon actually sent; fsync so the
            # post-mortem trace survives a crash-restart cycle.
            self.recorder.close()


class _RuntimeStateProvider(NbProvider):
    """Scheduler introspection served as operational state — the
    always-on analog of the reference's optional tokio-console runtime
    instrumentation (holo-daemon/src/main.rs:115-133).  Read-only: it
    owns no config subtree and vetoes nothing (base-class defaults)."""

    name = "runtime"

    def __init__(self, daemon: "Daemon"):
        self._daemon = daemon

    def filter_changes(self, changes):
        return []  # no config subtree: never part of a commit fan-out

    def get_state(self, path: str | None = None) -> dict:
        if path and not "holo-runtime".startswith(path.split("/")[0]):
            return {}
        d = self._daemon
        out = {"main-loop": d.loop.introspect()}
        if d.instance_loops:
            out["instance-loops"] = {
                name: tl.introspect()
                for name, tl in d.instance_loops.items()
            }
        return {"holo-runtime": out}


def _resolve_level(level, fallback: int, what: str) -> int:
    """Level-name → logging constant.  "trace" maps to DEBUG (Python
    logging's most verbose level); an unknown name is a config error
    worth a visible warning, not a silent fallback.  One resolver for
    the root level and the per-subsystem overrides so the two accept
    the same vocabulary."""
    lname = str(level).upper()
    resolved = {"TRACE": logging.DEBUG}.get(lname, getattr(logging, lname, None))
    if not isinstance(resolved, int):
        logging.getLogger(__name__).warning(
            "unknown log level %r for %s; using %s",
            level, what, logging.getLevelName(fallback),
        )
        resolved = fallback
    return resolved


def setup_logging(cfg) -> None:
    """Apply [logging]: root level, output style (compact / full / json),
    optional file sink, and per-subsystem level overrides — the
    reference's tracing-subscriber configuration (main.rs:59-146)."""
    lvl = _resolve_level(cfg.logging.level, logging.INFO, "root logger")
    if cfg.logging.style == "json":
        import json as _json

        from holo_tpu import telemetry

        class _JsonFormatter(logging.Formatter):
            def format(self, record):
                # Correlation keys: the active telemetry span id (join
                # log lines against Chrome trace dumps) and the protocol
                # instance name (an explicit ``instance`` record attr
                # wins; else the innermost span's instance tag).
                out = {
                    "ts": self.formatTime(record),
                    "level": record.levelname.lower(),
                    "target": record.name,
                    "message": record.getMessage(),
                    "instance": getattr(record, "instance", None)
                    or telemetry.current_instance(),
                    "span": telemetry.current_span_id(),
                }
                if record.exc_info:
                    out["exception"] = self.formatException(record.exc_info)
                if record.stack_info:
                    out["stack"] = record.stack_info
                return _json.dumps(out)

        fmt: logging.Formatter = _JsonFormatter()
    elif cfg.logging.style == "full":
        fmt = logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s "
            "[%(filename)s:%(lineno)d] %(message)s"
        )
    else:  # compact
        fmt = logging.Formatter("%(asctime)s %(levelname).1s %(name)s %(message)s")
    handler: logging.Handler = (
        logging.FileHandler(cfg.logging.file)
        if cfg.logging.file
        else logging.StreamHandler()
    )
    handler.setFormatter(fmt)
    root = logging.getLogger()
    for old in root.handlers:
        if isinstance(old, logging.FileHandler):
            old.close()  # re-config must not leak the previous sink's fd
    root.handlers[:] = [handler]
    root.setLevel(lvl)
    # Per-subsystem overrides: "ospf" -> holo_tpu.ospf / providers etc.
    for name, level in cfg.logging.subsystems.items():
        target = name if name.startswith("holo_tpu") else f"holo_tpu.{name}"
        logging.getLogger(target).setLevel(
            _resolve_level(level, logging.DEBUG, f"subsystem {name}")
        )


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(prog="holo-tpu-daemon")
    ap.add_argument("-f", "--config", default=None, help="TOML static config")
    args = ap.parse_args(argv)
    cfg = DaemonConfig.load(args.config)
    setup_logging(cfg)
    # Dispatch-breaker knobs apply process-wide (protocol code builds
    # its SPF/FRR engines — and so their breakers — internally).  Set
    # at daemon BOOT only: merely constructing a Daemon object (tests,
    # simulations) must not rewrite process globals.
    from holo_tpu.resilience.breaker import configure_defaults

    configure_defaults(
        failure_threshold=cfg.resilience.breaker_failure_threshold,
        recovery_timeout=cfg.resilience.breaker_recovery_timeout,
        deadline=cfg.resilience.breaker_deadline,
    )
    # Multi-chip dispatch mesh ([parallel], ISSUE 8): process-wide like
    # the breaker knobs, installed at BOOT only (constructing a Daemon
    # object must not rewrite process globals).  A shape that does not
    # fit the device count degrades to single-device dispatch with a
    # warning rather than refusing to boot.
    if cfg.parallel.enabled:
        try:
            from holo_tpu.parallel.mesh import configure_process_mesh

            mesh = configure_process_mesh(
                cfg.parallel.batch, cfg.parallel.node
            )
            log.info(
                "parallel dispatch mesh %s over %d device(s)",
                dict(mesh.shape),
                mesh.size,
            )
        except Exception as e:  # noqa: BLE001 — mesh is an optimization
            log.warning(
                "parallel mesh unavailable (%s); single-device dispatch", e
            )
    # Async dispatch pipeline + engine auto-tuner ([pipeline], ISSUE 9):
    # process-wide like the mesh, installed at BOOT only.  The tuner can
    # arm independently (the synchronous dispatch path consults it too);
    # a configured tuner-cache path restores the learned per-shape
    # winners so restarts don't re-learn.
    if cfg.pipeline.enabled or cfg.pipeline.tuner:
        from holo_tpu import pipeline as _pipeline

        if cfg.pipeline.tuner:
            tuner = _pipeline.configure_engine_tuner(
                path=cfg.pipeline.tuner_cache
            )
            log.info(
                "engine auto-tuner armed (%d persisted shape buckets)",
                tuner.stats()["buckets"],
            )
        if cfg.pipeline.enabled:
            _pipe = _pipeline.configure_process_pipeline(
                depth=cfg.pipeline.depth, capacity=cfg.pipeline.queue,
                advisory_deadline=cfg.pipeline.advisory_deadline,
            )
            log.info(
                "async dispatch pipeline armed (depth=%d queue=%d "
                "advisory-deadline=%s)",
                cfg.pipeline.depth, cfg.pipeline.queue,
                cfg.pipeline.advisory_deadline,
            )
            if cfg.pipeline.watchdog:
                # Hung-dispatch sentinel ([pipeline] watchdog, ISSUE
                # 19): budgets learned from the observatory's p99
                # sketches, floor-clamped while sites are cold.
                from holo_tpu.resilience.watchdog import (
                    configure_process_watchdog,
                )

                configure_process_watchdog(
                    _pipe,
                    multiplier=cfg.pipeline.watchdog_multiplier,
                    floor=cfg.pipeline.watchdog_floor,
                )
                log.info(
                    "dispatch watchdog armed (multiplier=%.1f "
                    "floor=%.1fs)",
                    cfg.pipeline.watchdog_multiplier,
                    cfg.pipeline.watchdog_floor,
                )
    from holo_tpu.daemon import hardening

    lock_fd = None
    if cfg.lock_path:
        lock_fd = hardening.acquire_instance_lock(cfg.lock_path)
    daemon = Daemon(config=cfg)
    if cfg.grpc.enabled:
        daemon.start_grpc()
        log.info("gRPC northbound on %s", cfg.grpc.address)
    if cfg.gnmi.enabled:
        daemon.start_gnmi()
        log.info("gNMI northbound on %s", cfg.gnmi.address)
    if cfg.telemetry.enabled:
        daemon.start_telemetry()
        log.info("telemetry /metrics on %s", cfg.telemetry.address)
    log.info("holo_tpu daemon running")
    # Kernel link/address monitor (production path; requires NETLINK).
    monitor = None
    if os.geteuid() == 0:
        try:
            from holo_tpu.routing.netlink import (
                LinkManager,
                NetlinkMonitor,
                link_table,
            )

            monitor = NetlinkMonitor()
            # Real link actuation: VRRP macvlans + admin/MTU apply.
            lm = LinkManager()
            daemon.routing.link_mgr = lm
            daemon.interface.link_mgr = lm
            log.info("kernel interface monitor + link actuation active")
        except OSError as e:
            log.warning("kernel monitor unavailable: %s", e)

    if cfg.user:
        # Privileged sockets (raw, netlink, port 179) are open; drop now.
        from holo_tpu.daemon import hardening

        hardening.drop_privileges(cfg.user)
    stopping = []
    from holo_tpu.daemon import hardening as _h

    # The dump queries ONLY the runtime provider — a full get_state fan-out
    # would render every provider's whole tree inside a signal handler.
    rt_provider = next(
        p for p in daemon.northbound.providers
        if isinstance(p, _RuntimeStateProvider)
    )
    from holo_tpu.telemetry import flight as _flight

    _h.install_signal_handlers(
        lambda: stopping.append(True),
        dump_cb=lambda: rt_provider.get_state().get("holo-runtime"),
        # First thing on SIGTERM/SIGINT: fsync the event journal so the
        # post-mortem trace survives even if the orderly drain hangs.
        flush_cb=(
            daemon.recorder.flush if daemon.recorder is not None else None
        ),
        # Then freeze the flight ring to a bundle (no-op unless
        # [telemetry] flight-buffer-entries + postmortem-dir are set).
        postmortem_cb=lambda: _flight.trigger("sigterm"),
    )
    try:
        import time

        while not stopping:
            with daemon.lock:
                if monitor is not None:
                    events = monitor.drain()
                    if monitor.overflowed:
                        log.warning("netlink queue overflow: full resync")
                        monitor.overflowed = False
                        events = monitor.resync()
                    for ev in events:
                        daemon.interface.apply_kernel_event(ev)

                tcp = getattr(daemon.routing, "bgp_tcp_io", None)
                if tcp is not None:
                    from holo_tpu.utils.tcpio import pump_once

                    pump_once([tcp], timeout_ms=0)
                daemon.loop.run_until_idle()
                daemon.northbound.check_confirmed_timeout(time.time())
                nd = daemon.loop.next_deadline()
                now = daemon.loop.clock.now()
            wait = min(max(nd - now, 0.01), 0.2) if nd else 0.2
            if tcp is not None:
                # Block in select on the BGP fds (no state touched, so no
                # lock needed) so inbound traffic is handled immediately
                # instead of on the next 200 ms tick; the pump itself runs
                # under the lock at the top of the loop.
                from holo_tpu.utils.tcpio import wait_ready

                wait_ready([tcp], int(wait * 1000))
            else:
                time.sleep(wait)
        daemon.stop()
        log.info("daemon stopped")
    except KeyboardInterrupt:
        daemon.stop()
    finally:
        if cfg.pipeline.tuner and cfg.pipeline.tuner_cache:
            # Final table flush (promotions already saved eagerly):
            # the learned winners must survive an orderly shutdown.
            from holo_tpu.pipeline import active_tuner

            t = active_tuner()
            if t is not None:
                t.save()
        if lock_fd is not None:
            os.close(lock_fd)


if __name__ == "__main__":
    main()

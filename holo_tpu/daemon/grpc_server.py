"""gRPC Northbound service (hand-registered handlers over generated
protobuf messages — no grpc codegen plugin in this environment).

Reference surface: the 10-RPC service of /root/reference/proto/holo.proto
(Capabilities, GetSchema, GetConfig, GetState, Validate, Commit, Execute,
ListTransactions, GetTransaction, Subscribe), re-specified in
proto/holo_tpu.proto with JSON-encoded data trees.
"""

from __future__ import annotations

import json
import queue
import sys
import time
from concurrent import futures
from pathlib import Path

import grpc

sys.path.insert(0, str(Path(__file__).resolve().parent))
import holo_tpu_pb2 as pb  # noqa: E402  (generated)

import holo_tpu
from holo_tpu.northbound.provider import CommitError
from holo_tpu.yang.data import DataTree
from holo_tpu.yang.schema import SchemaError


class NorthboundService:
    """Service implementation bound to a Daemon."""

    def __init__(self, daemon):
        self.daemon = daemon
        self._subscribers: list[queue.Queue] = []

    # -- RPC implementations (each takes request, context)

    def Capabilities(self, request, context):
        return pb.CapabilitiesResponse(
            version=holo_tpu.__version__,
            modules=sorted(self.daemon.northbound.schema.roots.keys()),
        )

    def GetSchema(self, request, context):
        def describe(node):
            from holo_tpu.yang.schema import Container, Leaf, LeafList, List

            if isinstance(node, Leaf):
                return {"kind": "leaf", "type": node.type, "default": str(node.default)}
            if isinstance(node, LeafList):
                return {"kind": "leaf-list", "type": node.type}
            if isinstance(node, List):
                return {
                    "kind": "list",
                    "key": node.key,
                    "children": {n: describe(c) for n, c in node.children.items()},
                }
            return {
                "kind": "container",
                "children": {n: describe(c) for n, c in node.children.items()},
            }

        roots = self.daemon.northbound.schema.roots
        if request.module:
            node = roots.get(request.module)
            out = {request.module: describe(node)} if node else {}
        else:
            out = {n: describe(c) for n, c in roots.items()}
        return pb.GetSchemaResponse(schema_json=json.dumps(out))

    @staticmethod
    def _encode_payload(obj, encoding, root_tag: str) -> str:
        """YANG-XML / LYB-lite (base64) per the request's DataEncoding
        (reference client grpc.rs:43-454).  ``obj`` must already be a
        JSON-plain tree (scalars stringified, keyed maps expanded)."""
        if not isinstance(obj, dict):
            obj = {"value": obj}
        if encoding == pb.XML:
            from holo_tpu.yang.serde import to_xml

            return to_xml(obj, root_tag)
        import base64

        from holo_tpu.yang.serde import to_lyb

        return base64.b64encode(to_lyb(obj)).decode()

    def GetConfig(self, request, context):
        with self.daemon.lock:
            tree = self.daemon.northbound.running
            if request.encoding == pb.JSON:
                if request.path:
                    payload = json.dumps(tree.get(request.path), default=str)
                else:
                    payload = tree.to_json()
            else:
                from holo_tpu.yang.serde import config_to_plain

                schema = self.daemon.northbound.schema
                if request.path:
                    obj = tree.get(request.path)
                    try:
                        node = schema.resolve(request.path)
                    except Exception:  # noqa: BLE001 — leaf paths etc.
                        node = None
                    obj = config_to_plain(node, obj)
                else:
                    obj = {
                        name: config_to_plain(
                            schema.roots.get(name), val
                        )
                        for name, val in tree.root.items()
                    }
                obj = json.loads(json.dumps(obj, default=str))
                payload = self._encode_payload(
                    obj, request.encoding, "config"
                )
        return pb.GetConfigResponse(config_json=payload)

    def GetState(self, request, context):
        with self.daemon.lock:
            state = self.daemon.northbound.get_state(request.path or None)
        if request.encoding == pb.JSON:
            payload = json.dumps(state, default=str)
        else:
            # State trees are already plain (dicts = containers, JSON
            # lists = list entries) — no keyed maps to expand.
            state = json.loads(json.dumps(state, default=str))
            payload = self._encode_payload(state, request.encoding, "state")
        return pb.GetStateResponse(state_json=payload)

    def Validate(self, request, context):
        try:
            cand = DataTree.from_json(
                self.daemon.northbound.schema, request.config_json
            )
            with self.daemon.lock:
                for p in self.daemon.northbound.providers:
                    p.validate(cand)
            return pb.ValidateResponse(error="")
        except (SchemaError, CommitError) as e:
            return pb.ValidateResponse(error=str(e))

    def Commit(self, request, context):
        nb = self.daemon.northbound
        try:
            if request.operation == pb.CommitOperation.CHANGE or request.edits:
                cand = nb.running.copy()
                for edit in request.edits:
                    if edit.operation == "delete":
                        cand.delete(edit.path)
                    else:
                        value = edit.value if edit.value != "" else None
                        # Leaf-lists cross the wire as JSON arrays (a
                        # PathEdit value is a string); scalars that
                        # merely look like JSON stay strings unless the
                        # parse yields a list.
                        if isinstance(value, str) and value.lstrip().startswith("["):
                            try:
                                parsed = json.loads(value)
                                if isinstance(parsed, list):
                                    value = parsed
                            except ValueError:
                                pass
                        cand.set(edit.path, value)
            elif request.operation == pb.CommitOperation.REPLACE:
                cand = DataTree.from_json(nb.schema, request.config_json)
            else:  # MERGE
                cand = nb.running.copy()
                merged = DataTree.from_json(nb.schema, request.config_json)
                _merge_tree(cand.root, merged.root)
            txn = self.daemon.commit(
                cand,
                comment=request.comment,
                confirmed_timeout=request.confirmed_timeout or None,
            )
            return pb.CommitResponse(transaction_id=txn.id, error="")
        except (SchemaError, CommitError) as e:
            return pb.CommitResponse(transaction_id=0, error=str(e))

    def Execute(self, request, context):
        try:
            input_ = json.loads(request.input_json) if request.input_json else {}
            with self.daemon.lock:
                if request.rpc_name == "confirm-commit":
                    self.daemon.northbound.confirm()
                    return pb.ExecuteResponse(output_json="{}")
                for p in self.daemon.northbound.providers:
                    try:
                        out = p.rpc(request.rpc_name, input_)
                        return pb.ExecuteResponse(
                            output_json=json.dumps(out, default=str)
                        )
                    except KeyError:
                        continue
            return pb.ExecuteResponse(output_json=json.dumps({"error": "unknown rpc"}))
        except Exception as e:  # surface provider errors to the client
            return pb.ExecuteResponse(output_json=json.dumps({"error": str(e)}))

    def ListTransactions(self, request, context):
        return pb.ListTransactionsResponse(
            transactions=[
                pb.TransactionInfo(id=t.id, timestamp=t.timestamp, comment=t.comment)
                for t in self.daemon.northbound.txn_log
            ]
        )

    def GetTransaction(self, request, context):
        try:
            t = self.daemon.northbound.get_transaction(request.id)
        except KeyError:
            context.abort(grpc.StatusCode.NOT_FOUND, f"no transaction {request.id}")
        return pb.GetTransactionResponse(
            info=pb.TransactionInfo(id=t.id, timestamp=t.timestamp, comment=t.comment),
            changes_json=t.changes_json,
            config_json=t.config_json,
        )

    def Subscribe(self, request, context):
        q: queue.Queue = queue.Queue(maxsize=256)
        self._subscribers.append(q)
        topics = set(request.topics)
        try:
            while context.is_active():
                try:
                    topic, payload = q.get(timeout=1.0)
                except queue.Empty:
                    continue
                if topics and topic not in topics:
                    continue
                yield pb.Notification(
                    topic=topic,
                    payload_json=json.dumps(payload, default=str),
                    timestamp=time.time(),
                )
        finally:
            self._subscribers.remove(q)

    def _notify(self, topic: str, payload) -> None:
        for q in list(self._subscribers):
            try:
                q.put_nowait((topic, payload))
            except queue.Full:
                pass


def _merge_tree(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge_tree(dst[k], v)
        else:
            dst[k] = v


_UNARY = [
    "Capabilities",
    "GetSchema",
    "GetConfig",
    "GetState",
    "Validate",
    "Commit",
    "Execute",
    "ListTransactions",
    "GetTransaction",
]


def _handlers(service: NorthboundService) -> grpc.GenericRpcHandler:
    method_handlers = {}
    svc = pb.DESCRIPTOR.services_by_name["Northbound"]
    for m in svc.methods:
        req_cls = getattr(pb, m.input_type.name)
        resp_cls = getattr(pb, m.output_type.name)
        fn = getattr(service, m.name)
        if m.name in _UNARY:
            method_handlers[m.name] = grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        else:  # Subscribe: unary -> stream
            method_handlers[m.name] = grpc.unary_stream_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
    return grpc.method_handlers_generic_handler("holo_tpu.Northbound", method_handlers)


def _bind(server, address: str, tls_cert=None, tls_key=None) -> None:
    """Bind the listen port, with TLS when both PEM paths are set
    (holo-daemon grpc.rs TLS option).  A half-configured TLS pair is a
    hard error — never silently fail open to plaintext."""
    if bool(tls_cert) != bool(tls_key):
        raise ValueError(
            "TLS misconfigured: need both tls-cert and tls-key"
        )
    if tls_cert and tls_key:
        creds = grpc.ssl_server_credentials(
            [(Path(tls_key).read_bytes(), Path(tls_cert).read_bytes())]
        )
        server._bound_port = server.add_secure_port(address, creds)
    else:
        server._bound_port = server.add_insecure_port(address)


def serve(daemon, address: str, tls_cert=None, tls_key=None) -> grpc.Server:
    service = NorthboundService(daemon)
    daemon.add_commit_listener(
        lambda txn: service._notify(
            "commit", {"transaction-id": txn.id, "comment": txn.comment}
        )
    )
    # Protocol YANG notifications stream on their own topic (the
    # notification's qualified name), so Subscribe(topics=[...]) can
    # filter e.g. just "ietf-ospf:nbr-state-change".
    daemon.add_notification_listener(
        lambda payload: [
            service._notify(kind, body) for kind, body in payload.items()
        ]
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_handlers(service),))
    _bind(server, address, tls_cert, tls_key)
    server.start()
    daemon._grpc_service = service
    return server


class NorthboundClient:
    """Minimal client for tests/CLI (generic channel callables)."""

    def __init__(self, address: str):
        self.channel = grpc.insecure_channel(address)
        svc = pb.DESCRIPTOR.services_by_name["Northbound"]
        self._calls = {}
        for m in svc.methods:
            req_cls = getattr(pb, m.input_type.name)
            resp_cls = getattr(pb, m.output_type.name)
            path = f"/holo_tpu.Northbound/{m.name}"
            if m.name in _UNARY:
                self._calls[m.name] = self.channel.unary_unary(
                    path,
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )
            else:
                self._calls[m.name] = self.channel.unary_stream(
                    path,
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )

    def __getattr__(self, name):
        try:
            return self._calls[name]
        except KeyError as e:
            raise AttributeError(name) from e

"""Daemon core: process assembly, static config, gRPC northbound.

Reference: holo-daemon (SURVEY.md §2.1, §3.1) — entry point, TOML static
config, provider startup order, northbound transaction engine, gRPC
service.  Privilege handling and netlink programming are gated behind the
kernel interface (mock by default; Linux netlink when running as root).
"""

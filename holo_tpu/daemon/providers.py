"""Base system providers: interface, system, keychain, policy, routing.

Reference: SURVEY.md §2.2 — each is an actor + northbound provider + ibus
server.  The routing provider owns the RIB manager and spawns/stops
protocol instances from configuration (the reference does this in
holo-routing/src/northbound/configuration.rs:1228-1301).
"""

from __future__ import annotations

import logging

log = logging.getLogger("holo_tpu.providers")

from dataclasses import dataclass, field
from ipaddress import IPv4Address, ip_interface

from holo_tpu.northbound.provider import CommitPhase, Provider
from holo_tpu.protocols.ospf.instance import (
    IfConfig,
    IfUpMsg,
    InstanceConfig,
    OspfInstance,
    SpfTimers,
)
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.routing.rib import Kernel, MockKernel, RibManager
from holo_tpu.spf.backend import ScalarSpfBackend, TpuSpfBackend
from holo_tpu.utils.ibus import (
    TOPIC_ADDRESS_ADD,
    TOPIC_HOSTNAME,
    TOPIC_INTERFACE_UPD,
    TOPIC_KEYCHAIN_UPD,
    TOPIC_POLICY_UPD,
    TOPIC_ROUTER_ID,
    Ibus,
)
from holo_tpu.utils.netio import NetIo
from holo_tpu.utils.runtime import Actor, EventLoop
from holo_tpu.utils.southbound import InterfaceUpdMsg


@dataclass
class IfaceState:
    name: str
    ifindex: int
    mtu: int = 1500
    enabled: bool = True
    operative: bool = True
    addresses: list = field(default_factory=list)
    # (parent, vlan-id) of the kernel 802.1Q device we actuated for this
    # interface; None = no vlan device created by us.
    vlan_actuated: tuple | None = None


class InterfaceProvider(Provider, Actor):
    """Interface table owner.  In the daemon this mirrors the OS via
    netlink (holo-interface/src/netlink.rs); under test it is driven by
    config + synthetic link events."""

    name = "interface"
    subtree_prefixes = ("interfaces",)

    def __init__(self, ibus: Ibus):
        self.ibus = ibus
        self.interfaces: dict[str, IfaceState] = {}
        self._next_ifindex = 1
        # Set by the daemon: where connected (direct) routes are sent.
        self.routing_actor: str | None = None
        self._direct: set = set()  # prefixes currently installed as direct
        # Set by the daemon when kernel actuation is available: config
        # admin-status/MTU changes then apply via netlink (reference
        # holo-interface/src/netlink.rs:242-270).
        self.link_mgr = None

    def handle(self, msg):
        pass

    def validate(self, new_tree) -> None:
        # Fail-closed at commit time (same pattern as the keychain
        # lifetime validation): a bad vlan-id or a vlan interface
        # without its parent must reject the commit, not silently skip
        # device creation at apply time.
        from holo_tpu.northbound.provider import CommitError

        for name, entry in (
            new_tree.get("interfaces/interface", {}) or {}
        ).items():
            if entry.get("type") != "vlan":
                continue
            vid = entry.get("vlan-id")
            if vid is not None and not 1 <= vid <= 4094:
                raise CommitError(
                    f"interface {name}: vlan-id must be 1-4094, got {vid}"
                )
            if (vid is None) != (not entry.get("parent-interface")):
                raise CommitError(
                    f"interface {name}: vlan interfaces need BOTH "
                    f"parent-interface and vlan-id"
                )

    def _sync_direct_routes(self) -> None:
        """Connected prefixes go into the RIB as protocol 'direct' at
        distance 0 with an empty next-hop set — they win over any IGP copy
        of the same prefix and the empty set keeps them out of the kernel
        FIB (which already has them)."""
        from holo_tpu.utils.southbound import Protocol, RouteKeyMsg, RouteMsg

        if self.routing_actor is None:
            return
        wanted = {
            a.network
            for st in self.interfaces.values()
            if st.operative
            for a in st.addresses
        }
        for prefix in self._direct - wanted:
            self.ibus.request(
                self.routing_actor,
                RouteKeyMsg(Protocol.DIRECT, prefix),
                sender=self.name,
            )
        for prefix in wanted - self._direct:
            self.ibus.request(
                self.routing_actor,
                RouteMsg(Protocol.DIRECT, prefix, 0, 0, frozenset()),
                sender=self.name,
            )
        self._direct = wanted

    def commit(self, phase, old, new, changes):
        if phase != CommitPhase.APPLY:
            return
        conf = new.get("interfaces/interface", {}) or {}
        for name, entry in conf.items():
            st = self.interfaces.get(name)
            if st is None:
                st = IfaceState(name=name, ifindex=self._next_ifindex)
                self._next_ifindex += 1
                self.interfaces[name] = st
            # 802.1Q subinterface actuation is CHANGE-driven (reference
            # configuration.rs:122-131,354-365 Event::VlanCreate fires
            # on the config change, not on map appearance): whenever the
            # wanted (parent, vlan-id) differs from what we actuated,
            # tear the old device down and create the new one.
            want_vlan = (
                (entry.get("parent-interface"), entry.get("vlan-id"))
                if entry.get("type") == "vlan"
                and entry.get("parent-interface")
                and entry.get("vlan-id") is not None
                else None
            )
            if self.link_mgr is not None and want_vlan != st.vlan_actuated:
                try:
                    if st.vlan_actuated is not None:
                        self.link_mgr.delete_link(name)
                        st.vlan_actuated = None
                    if want_vlan is not None:
                        self.link_mgr.create_vlan(
                            want_vlan[0], name, want_vlan[1]
                        )
                        st.vlan_actuated = want_vlan
                except (OSError, ValueError) as e:
                    log.error("vlan actuation failed for %s: %s", name, e)
            new_mtu = entry.get("mtu", 1500)
            new_enabled = entry.get("enabled", True)
            if self.link_mgr is not None and (
                new_mtu != st.mtu or new_enabled != st.enabled
            ):
                try:
                    self.link_mgr.set_link(
                        name,
                        up=new_enabled if new_enabled != st.enabled else None,
                        mtu=new_mtu if new_mtu != st.mtu else None,
                    )
                except OSError as e:
                    log.error("link apply failed for %s: %s", name, e)
            st.mtu = new_mtu
            st.enabled = new_enabled
            st.addresses = [ip_interface(a) for a in entry.get("address", [])]
            # Causal origin: an interface config change is a topology
            # event (convergence trigger class "ifconfig").
            from holo_tpu.telemetry import convergence

            eid = convergence.begin(
                convergence.TRIGGER_IFCONFIG, ifname=name,
                operative=st.enabled and st.operative,
            )
            with convergence.activation(eid):
                self.ibus.publish(
                    TOPIC_INTERFACE_UPD,
                    # operative = admin AND carrier: a config commit must
                    # not report a carrier-down link as up (the RIB treats
                    # operative=True as an FRR restore signal).
                    InterfaceUpdMsg(ifname=name, ifindex=st.ifindex,
                                    mtu=st.mtu,
                                    operative=st.enabled and st.operative),
                    ifname=name,
                )
            for addr in st.addresses:
                self.ibus.publish(TOPIC_ADDRESS_ADD, (name, addr), ifname=name)
        from holo_tpu.utils.ibus import TOPIC_INTERFACE_DEL

        for name in list(self.interfaces):
            if name not in conf:
                st = self.interfaces.pop(name)
                # Symmetric teardown: a vlan device WE created goes away
                # with its config entry, or the kernel link leaks and a
                # later re-add with a different id fails changelink.
                if st.vlan_actuated is not None and self.link_mgr is not None:
                    try:
                        self.link_mgr.delete_link(name)
                    except OSError as e:
                        log.error("vlan teardown failed for %s: %s", name, e)
                self.ibus.publish(TOPIC_INTERFACE_DEL, name, ifname=name)
        self._publish_router_id()
        self._sync_direct_routes()

    def _publish_router_id(self):
        """Router-ID derivation: highest interface address (reference
        holo-interface/src/interface.rs Router-ID logic)."""
        best = None
        for st in self.interfaces.values():
            for a in st.addresses:
                if a.version == 4 and (best is None or int(a.ip) > int(best)):
                    best = a.ip
        self.ibus.publish(TOPIC_ROUTER_ID, best)

    def apply_kernel_event(self, ev) -> None:
        """Feed a NetlinkMonitor LinkEvent into the provider table (the
        production path; config-driven interfaces take precedence)."""
        from holo_tpu.utils.ibus import TOPIC_INTERFACE_DEL

        if ev.kind == "link":
            st = self.interfaces.get(ev.ifname)
            if st is None:
                st = IfaceState(name=ev.ifname, ifindex=ev.ifindex)
                self.interfaces[ev.ifname] = st
            st.ifindex = ev.ifindex
            st.operative = ev.up and ev.running
            if ev.mtu:
                st.mtu = ev.mtu
            # Causal origin: a kernel link event is the carrier-loss /
            # carrier-recovery moment (convergence trigger "carrier").
            from holo_tpu.telemetry import convergence

            eid = convergence.begin(
                convergence.TRIGGER_CARRIER, ifname=ev.ifname,
                operative=st.operative,
            )
            with convergence.activation(eid):
                self.ibus.publish(
                    TOPIC_INTERFACE_UPD,
                    InterfaceUpdMsg(ifname=ev.ifname, ifindex=st.ifindex,
                                    mtu=st.mtu, operative=st.operative),
                    ifname=ev.ifname,
                )
        elif ev.kind == "link-del":
            if self.interfaces.pop(ev.ifname, None) is not None:
                self.ibus.publish(TOPIC_INTERFACE_DEL, ev.ifname,
                                  ifname=ev.ifname)
                self._publish_router_id()
        elif ev.kind in ("addr", "addr-del"):
            for st in self.interfaces.values():
                if st.ifindex == ev.ifindex:
                    if ev.kind == "addr" and ev.addr not in st.addresses:
                        st.addresses.append(ev.addr)
                        self.ibus.publish(TOPIC_ADDRESS_ADD,
                                          (st.name, ev.addr), ifname=st.name)
                    elif ev.kind == "addr-del" and ev.addr in st.addresses:
                        st.addresses.remove(ev.addr)
                    self._publish_router_id()
                    self._sync_direct_routes()
                    break

    def get_state(self, path=None):
        return {
            "interfaces": {
                "interface": {
                    name: {
                        "name": name,
                        "if-index": st.ifindex,
                        "oper-status": "up" if st.operative else "down",
                        "mtu": st.mtu,
                    }
                    for name, st in self.interfaces.items()
                }
            }
        }


class SystemProvider(Provider, Actor):
    name = "system"
    subtree_prefixes = ("system",)

    def __init__(self, ibus: Ibus):
        self.ibus = ibus
        self.hostname = ""

    def handle(self, msg):
        pass

    def commit(self, phase, old, new, changes):
        if phase != CommitPhase.APPLY:
            return
        hostname = new.get("system/hostname")
        if hostname != self.hostname:
            self.hostname = hostname or ""
            self.ibus.publish(TOPIC_HOSTNAME, self.hostname)

    def get_state(self, path=None):
        return {"system": {"hostname": self.hostname}}


class KeychainProvider(Provider, Actor):
    name = "keychain"
    subtree_prefixes = ("key-chains",)

    def __init__(self, ibus: Ibus):
        self.ibus = ibus
        self.keychains: dict = {}

    def handle(self, msg):
        pass

    def validate(self, new_tree) -> None:
        # FAIL-CLOSED on lifetimes: a malformed date-and-time must
        # reject the commit, never silently become an unbounded key.
        from holo_tpu.northbound.provider import CommitError
        from holo_tpu.utils.keychain import Keychain

        for name, chain in (
            new_tree.get("key-chains/key-chain", {}) or {}
        ).items():
            try:
                Keychain.from_config(name, chain)
            except ValueError as e:
                raise CommitError(f"key-chain {name!r}: {e}") from e

    def commit(self, phase, old, new, changes):
        from holo_tpu.utils.ibus import TOPIC_KEYCHAIN_DEL

        if phase != CommitPhase.APPLY:
            return
        prev = self.keychains
        self.keychains = new.get("key-chains/key-chain", {}) or {}
        for name in prev.keys() - self.keychains.keys():
            self.ibus.publish(TOPIC_KEYCHAIN_DEL, name)
        for name, chain in self.keychains.items():
            if prev.get(name) != chain:  # changed or new only
                self.ibus.publish(TOPIC_KEYCHAIN_UPD, name)


class PolicyProvider(Provider, Actor):
    name = "policy"
    subtree_prefixes = ("routing-policy",)

    def __init__(self, ibus: Ibus):
        from holo_tpu.utils.policy import PolicyEngine

        self.ibus = ibus
        self.engine = PolicyEngine()
        self.policies: dict = {}
        self.defined_sets: dict = {}

    def handle(self, msg):
        pass

    def commit(self, phase, old, new, changes):
        if phase != CommitPhase.APPLY:
            return
        self.policies = new.get("routing-policy/policy-definition", {}) or {}
        self.defined_sets = new.get("routing-policy/defined-sets", {}) or {}
        self.engine.load_from_config(
            {
                "defined-sets": self.defined_sets,
                "policy-definition": self.policies,
            }
        )
        for name in self.policies:
            self.ibus.publish(TOPIC_POLICY_UPD, name)


def _parse_system_id(s: str) -> bytes | None:
    """Parse an IS-IS system id: dotted-hex ('1921.6800.1001') or six
    dotted-decimal octets ('0.0.0.0.0.1').  Returns None if invalid."""
    parts = s.split(".")
    try:
        if len(parts) == 3 and all(len(p) == 4 for p in parts):
            return bytes.fromhex("".join(parts))
        if len(parts) == 6:
            vals = [int(p) for p in parts]
            if all(0 <= v <= 255 for v in vals):
                return bytes(vals)
    except ValueError:
        pass
    return None


class RoutingProvider(Provider, Actor):
    """RIB owner + protocol instance lifecycle from configuration."""

    name = "routing"
    subtree_prefixes = ("routing",)

    # Optional placement hooks (set by the daemon): with preemptive
    # isolation each protocol instance is registered on its own
    # ThreadedLoop instead of the shared loop (utils/preempt.py).
    instance_placer = None
    instance_unplacer = None

    def _place_instance(self, inst):
        """Registers the instance and returns the object the provider
        should hold: the instance itself (cooperative), or a marshalling
        handle when the daemon placed it on its own thread."""
        if self.instance_placer is not None:
            return self.instance_placer(inst) or inst
        if hasattr(inst, "attach_loop"):
            # Multi-actor node (IS-IS L1/L2): registers the per-level
            # actors plus the node's own packet entry point.
            inst.attach_loop(self.loop)
        else:
            self.loop.register(inst)
        return inst

    def _unplace_instance(self, name: str) -> None:
        if self.instance_unplacer is not None:
            self.instance_unplacer(name)
            return
        if name in self.loop.actors:
            self.loop.unregister(name)
        # Multi-actor node: its per-level actors carry "<name>-..." names.
        for sub in [a for a in self.loop.actors if a.startswith(f"{name}-")]:
            self.loop.unregister(sub)

    def validate(self, new_tree) -> None:
        from holo_tpu.northbound.provider import CommitError

        sid = new_tree.get("routing/control-plane-protocols/isis/system-id")
        if sid is not None and _parse_system_id(sid) is None:
            raise CommitError(f"invalid IS-IS system-id {sid!r}")
        # RFC 2080: RIPng relies on IPsec, it has no in-protocol auth.
        for ifname, if_conf in (
            new_tree.get("routing/control-plane-protocols/ripng/interface")
            or {}
        ).items():
            if if_conf.get("authentication"):
                raise CommitError(
                    f"ripng interface {ifname}: RIPng has no in-protocol "
                    f"authentication (RFC 2080)"
                )
        # Keychain references must resolve within the same candidate.
        chains = new_tree.get("key-chains/key-chain", {}) or {}
        areas = new_tree.get(
            "routing/control-plane-protocols/ospfv2/area", {}
        ) or {}
        for area_conf in areas.values():
            for ifname, if_conf in (area_conf.get("interface") or {}).items():
                kc = (if_conf.get("authentication") or {}).get("key-chain")
                if kc is None:
                    continue
                if kc not in chains:
                    raise CommitError(
                        f"interface {ifname}: unknown key-chain {kc!r}"
                    )
                if not (chains[kc].get("key") or {}):
                    raise CommitError(
                        f"interface {ifname}: key-chain {kc!r} has no keys"
                    )
        # Same resolution check for EVERY key-chain consumer — a typo'd
        # name must fail the commit, not silently run with the random
        # fail-closed key.
        isis_base = "routing/control-plane-protocols/isis"
        kc_refs = [
            (
                "isis authentication",
                (new_tree.get(f"{isis_base}/authentication") or {}).get(
                    "key-chain"
                ),
            )
        ]
        for ifname, if_conf in (
            new_tree.get(f"{isis_base}/interface") or {}
        ).items():
            kc_refs.append(
                (
                    f"isis interface {ifname} hello-authentication",
                    (if_conf.get("hello-authentication") or {}).get(
                        "key-chain"
                    ),
                )
            )
        for ifname, if_conf in (
            new_tree.get("routing/control-plane-protocols/ripv2/interface")
            or {}
        ).items():
            kc_refs.append(
                (
                    f"ripv2 interface {ifname}",
                    (if_conf.get("authentication") or {}).get("key-chain"),
                )
            )
        for where, kc in kc_refs:
            if kc is None:
                continue
            if kc not in chains:
                raise CommitError(f"{where}: unknown key-chain {kc!r}")
            if not (chains[kc].get("key") or {}):
                # An empty chain resolves to the fail-closed random key
                # — a silent auth outage nobody asked for.
                raise CommitError(f"{where}: key-chain {kc!r} has no keys")
        # OSPFv3 authentication is the RFC 7166 trailer (HMAC family):
        # v2-style simple/md5 types have no v3 encoding — reject them,
        # and key-chain references must resolve.
        v3_areas = new_tree.get(
            "routing/control-plane-protocols/ospfv3/area", {}
        ) or {}
        for area_conf in v3_areas.values():
            for ifname, if_conf in (area_conf.get("interface") or {}).items():
                auth = if_conf.get("authentication") or {}
                if auth.get("type") in ("simple", "md5"):
                    raise CommitError(
                        f"ospfv3 interface {ifname}: OSPFv3 uses the "
                        f"RFC 7166 authentication trailer (key + "
                        f"crypto-algorithm or key-chain), not v2-style "
                        f"{auth['type']!r}"
                    )
                kc = auth.get("key-chain")
                if kc is not None and kc not in chains:
                    raise CommitError(
                        f"ospfv3 interface {ifname}: unknown key-chain "
                        f"{kc!r}"
                    )
                if kc is not None:
                    if not (chains[kc].get("key") or {}):
                        raise CommitError(
                            f"ospfv3 interface {ifname}: key-chain {kc!r} "
                            f"has no keys"
                        )
                    # Every key must carry an RFC 7166-capable algorithm
                    # or its active window would be a silent auth outage
                    # (resolve_send -> None -> unauthenticated sends).
                    from holo_tpu.protocols.ospf.packet_v3 import (
                        _AT_KEYCHAIN_ALGO,
                    )

                    bad = [
                        kid
                        for kid, kconf in (
                            chains[kc].get("key") or {}
                        ).items()
                        if _AT_KEYCHAIN_ALGO.get(
                            kconf.get("crypto-algorithm", "md5")
                        )
                        is None
                    ]
                    if bad:
                        raise CommitError(
                            f"ospfv3 interface {ifname}: key-chain {kc!r} "
                            f"key(s) {bad} have no RFC 7166 algorithm "
                            f"(md5 is not valid for OSPFv3)"
                        )
        if new_tree.get("routing/control-plane-protocols/ospfv3/redistribute"):
            raise CommitError(
                "ospfv3 redistribution is not supported yet"
            )
        # RFC 2328: the backbone can never be a stub area (any spelling of
        # area id 0 counts).
        for proto in ("ospfv2", "ospfv3"):
            areas_conf = new_tree.get(
                f"routing/control-plane-protocols/{proto}/area", {}
            ) or {}
            for area_id, area_conf in areas_conf.items():
                try:
                    is_backbone = int(IPv4Address(area_id)) == 0
                except Exception:
                    is_backbone = area_id in ("0", "0.0.0.0")
                if is_backbone and area_conf.get("area-type") in (
                    "stub", "nssa"
                ):
                    raise CommitError(
                        "the backbone area cannot be stub or NSSA"
                    )

    def __init__(
        self,
        loop: EventLoop,
        ibus: Ibus,
        netio,
        interface_provider: InterfaceProvider,
        kernel: Kernel | None = None,
        prefix: str = "",
        policy_engine=None,
        keychains: "KeychainProvider | None" = None,
        nvstore=None,
        link_mgr=None,
        yang_notify=None,
        microloop_delay: float = 0.0,
    ):
        self.loop = loop
        self.ibus = ibus
        # Sink for protocol YANG notifications (reference notification.rs
        # -> northbound -> management clients); the daemon points this at
        # its fan-out so gRPC/gNMI Subscribe streams see them.
        self.yang_notify = yang_notify
        self.policy_engine = policy_engine
        self.keychains = keychains
        self.nvstore = nvstore
        # Link actuation (macvlans, admin/MTU): LinkManager in production,
        # MockLinkManager under test.
        if link_mgr is None:
            from holo_tpu.routing.netlink import MockLinkManager

            link_mgr = MockLinkManager()
        self.link_mgr = link_mgr
        # netio: either a NetIo (shared sender) or a callable actor->NetIo
        # (MockFabric.sender_for) so each protocol actor receives its own
        # bound transmit handle.
        self.netio_factory = netio if callable(netio) else (lambda _actor: netio)
        self.ifp = interface_provider
        self.prefix = prefix
        self.rib = RibManager(
            ibus, kernel or MockKernel(), microloop_delay=microloop_delay
        )
        self.rib.on_change = self._rib_changed
        self.instances: dict[str, OspfInstance] = {}

    def attach(self, loop_):
        super().attach(loop_)
        loop_.register(self.rib, name=f"{self.prefix}routing-rib")
        from holo_tpu.utils.ibus import (
            TOPIC_INTERFACE_DEL,
            TOPIC_KEYCHAIN_DEL,
            TOPIC_KEYCHAIN_UPD,
        )

        from holo_tpu.utils.ibus import (
            TOPIC_REDISTRIBUTE_ADD,
            TOPIC_REDISTRIBUTE_DEL,
        )

        self.ibus.subscribe(TOPIC_INTERFACE_DEL, self.name)
        self.ibus.subscribe(TOPIC_KEYCHAIN_UPD, self.name)
        self.ibus.subscribe(TOPIC_KEYCHAIN_DEL, self.name)
        self.ibus.subscribe(TOPIC_REDISTRIBUTE_ADD, self.name)
        self.ibus.subscribe(TOPIC_REDISTRIBUTE_DEL, self.name)
        # BFD is always-on, spawned at startup inside the routing provider
        # (reference holo-routing/src/lib.rs:261-281).
        from holo_tpu.protocols.bfd import BfdInstance

        self.bfd = BfdInstance(
            self.netio_factory(f"{self.prefix}bfd"), self.ibus,
            notif_cb=self.yang_notify,
        )
        loop_.register(self.bfd, name=f"{self.prefix}bfd")

    def handle(self, msg):
        from holo_tpu.utils.ibus import (
            TOPIC_INTERFACE_DEL,
            TOPIC_KEYCHAIN_DEL,
            TOPIC_KEYCHAIN_UPD,
            IbusMsg,
        )

        from holo_tpu.utils.ibus import (
            TOPIC_REDISTRIBUTE_ADD,
            TOPIC_REDISTRIBUTE_DEL,
        )

        if isinstance(msg, IbusMsg) and msg.topic in (
            TOPIC_REDISTRIBUTE_ADD,
            TOPIC_REDISTRIBUTE_DEL,
        ):
            self._handle_redistribution(msg)
            return
        if isinstance(msg, IbusMsg) and msg.topic in (
            TOPIC_KEYCHAIN_UPD,
            TOPIC_KEYCHAIN_DEL,
        ):
            # Key rotation: re-resolve AuthCtx for interfaces referencing
            # the changed keychain (in place — adjacencies re-key live).
            self._refresh_ospf_auth()
            self._refresh_ospfv3_auth()
            self._refresh_isis_auth()
            self._refresh_rip_auth()
            return
        if isinstance(msg, IbusMsg) and msg.topic == TOPIC_INTERFACE_DEL:
            # Interface removed from the system: down it in every protocol
            # instance that uses it (stops hellos, withdraws the subnet).
            from holo_tpu.protocols.isis.instance import IsisIfDownMsg, IsisInstance
            from holo_tpu.protocols.ospf.instance import IfDownMsg
            from holo_tpu.protocols.ospf.instance_v3 import (
                OspfV3Instance,
                V3IfDownMsg,
            )

            ifname = msg.payload
            for inst in self.instances.values():
                if isinstance(inst, OspfInstance) and ifname in inst._if_area:
                    self.loop.send(inst.name, IfDownMsg(ifname))
                elif isinstance(inst, OspfV3Instance) and ifname in inst.interfaces:
                    self.loop.send(inst.name, V3IfDownMsg(ifname))
                elif isinstance(inst, IsisInstance) and ifname in inst.interfaces:
                    self.loop.send(inst.name, IsisIfDownMsg(ifname))
                elif (
                    hasattr(inst, "instances")
                    and hasattr(inst, "if_down")
                    and ifname in inst.interfaces
                ):
                    # IS-IS L1/L2 node: marshalled call downs both levels.
                    inst.if_down(ifname)

    def commit(self, phase, old, new, changes):
        if phase != CommitPhase.APPLY:
            return
        self._last_tree = new
        self._apply_ospfv2(new)
        self._apply_ospfv3(new)
        self._apply_isis(new)
        self._apply_bgp(new)
        self._apply_vrrp(new)
        self._apply_ldp(new)
        self._apply_rip(new)
        self._apply_igmp(new)
        self._apply_static(new)

    def _handle_redistribution(self, msg) -> None:
        """RIB redistribution → OSPF type-5 origination (reference:
        redistribution pub/sub, holo-routing/src/rib.rs:71)."""
        from holo_tpu.utils.ibus import TOPIC_REDISTRIBUTE_ADD
        from holo_tpu.utils.southbound import Protocol

        inst = self.instances.get("ospfv2")
        wanted = getattr(self, "_ospf_redistribute", set())
        if inst is None:
            return
        payload = msg.payload
        proto = payload.protocol
        if proto in (Protocol.OSPFV2,):
            return  # never re-inject our own routes
        if payload.prefix.version != 4:
            return
        if msg.topic == TOPIC_REDISTRIBUTE_ADD:
            if proto.value in wanted:
                inst.redistribute(payload.prefix, metric=max(payload.metric, 1))
            elif payload.prefix in inst.redistributed:
                # Best route switched to a non-redistributed protocol: the
                # type-5 must go (the RIB only publishes DEL on full
                # removal, so the ADD with the new winner is our signal).
                inst.withdraw_redistributed(payload.prefix)
        else:
            inst.withdraw_redistributed(payload.prefix)

    def _refresh_ospf_auth(self) -> None:
        tree = getattr(self, "_last_tree", None)
        inst = self.instances.get("ospfv2")
        if tree is None or inst is None:
            return
        areas = tree.get("routing/control-plane-protocols/ospfv2/area", {}) or {}
        for area_conf in areas.values():
            for ifname, if_conf in (area_conf.get("interface") or {}).items():
                ai = inst._iface(ifname)
                if ai is not None:
                    ai[1].config.auth = self._ospf_auth(
                        if_conf.get("authentication")
                    )

    # -- OSPFv2 lifecycle (holo-routing northbound/configuration.rs analog)

    def _apply_ospfv2(self, new):
        base = "routing/control-plane-protocols/ospfv2"
        conf = new.get(base)
        enabled = bool(conf) and new.get(f"{base}/enabled", True)
        inst = self.instances.get("ospfv2")
        if not enabled:
            if inst is not None:
                # Withdraw every route the instance installed before it goes
                # (reference: instance stop purges its RIB contributions).
                from holo_tpu.utils.southbound import Protocol, RouteKeyMsg

                for prefix in inst.routes:
                    self.rib.route_del(RouteKeyMsg(Protocol.OSPFV2, prefix))
                self._unplace_instance(inst.name)
                del self.instances["ospfv2"]
            return
        router_id = new.get(f"{base}/router-id")
        if router_id is None:
            return  # not ready (reference: instance waits for router-id)
        spf = new.get(f"{base}/spf-control", {}) or {}
        delay = spf.get("ietf-spf-delay", {}) or {}
        timers = SpfTimers(
            initial_delay=delay.get("initial-delay", 50) / 1000,
            short_delay=delay.get("short-delay", 200) / 1000,
            long_delay=delay.get("long-delay", 5000) / 1000,
            hold_down=delay.get("hold-down", 10000) / 1000,
            time_to_learn=delay.get("time-to-learn", 500) / 1000,
        )
        backend_name = spf.get("backend", "scalar")
        # Reuse the live backend when the engine kind is unchanged (the
        # ensure_engine pattern): a rebuilt TpuSpfBackend on every
        # commit would discard the warm jit/graph caches and mint a
        # fresh breaker metric series each time.
        want = TpuSpfBackend if backend_name == "tpu" else ScalarSpfBackend
        prev = getattr(inst, "backend", None) if inst is not None else None
        # A pipelined backend wraps the real one (AsyncSpfBackend.inner,
        # ISSUE 9): the reuse check looks through the facade, and a
        # fresh tpu backend rides the process pipeline when one is
        # armed (wrap_spf_backend is the identity otherwise).
        from holo_tpu.pipeline import wrap_spf_backend

        prev_core = getattr(prev, "inner", prev)
        backend = prev if type(prev_core) is want else wrap_spf_backend(want())
        old_redist = getattr(self, "_ospf_redistribute", set())
        self._ospf_redistribute = set(new.get(f"{base}/redistribute") or [])
        redist_changed = old_redist != self._ospf_redistribute
        if inst is None:
            inst = OspfInstance(
                name=f"{self.prefix}ospfv2",
                config=InstanceConfig(router_id=IPv4Address(router_id), spf=timers),
                netio=self.netio_factory(f"{self.prefix}ospfv2"),
                spf_backend=backend,
                notif_cb=self.yang_notify,
                nvstore=self.nvstore,
            )
            inst = self._place_instance(inst)
            inst.attach_ibus(
                self.ibus,
                routing_actor=f"{self.prefix}routing-rib",
                bfd_actor=f"{self.prefix}bfd",
            )
            self.instances["ospfv2"] = inst
        else:
            inst.config.router_id = IPv4Address(router_id)
            inst.config.spf = timers
            inst.backend = backend
        # IP fast reroute (mirrors the reference YANG fast-reroute
        # container: ietf-ospf fast-reroute/lfa plus holo's remote-lfa /
        # ti-lfa extension leaves).  A change must force a full SPF run:
        # that is what recomputes (or, on disable, drops) the backup
        # tables and republishes routes with the new repair set.
        new_frr = self._frr_config(new.get(f"{base}/fast-reroute"))
        if new_frr != inst.config.frr:
            inst.config.frr = new_frr
            inst._schedule_spf()
        # RFC 6987 stub-router maintenance mode (max-metric router-LSA).
        inst.set_stub_router(bool(new.get(f"{base}/stub-router", False)))

        areas = new.get(f"{base}/area", {}) or {}
        for area_id, area_conf in areas.items():
            area_type = area_conf.get("area-type", "normal")
            stub = area_type == "stub"
            nssa = area_type == "nssa"
            stub_cost = area_conf.get("default-cost", 1)
            for ifname, if_conf in (area_conf.get("interface") or {}).items():
                if ifname in inst._if_area:
                    # Live reconfiguration on the running circuit
                    # (reference configuration.rs InterfaceUpdate
                    # family); auth refreshes via _refresh_ospf_auth.
                    st = self.ifp.interfaces.get(ifname)
                    inst.iface_cost_update(ifname, if_conf.get("cost", 10))
                    inst.iface_update(
                        ifname,
                        hello=if_conf.get("hello-interval", 10),
                        dead=if_conf.get("dead-interval", 40),
                        priority=if_conf.get("priority", 1),
                        passive=if_conf.get("passive", False),
                        mtu=st.mtu if st is not None else None,
                        mtu_ignore=if_conf.get("mtu-ignore", False),
                        transmit_delay=if_conf.get("transmit-delay", 1),
                    )
                    continue
                st = self.ifp.interfaces.get(ifname)
                if st is None or not st.addresses:
                    continue
                addr = st.addresses[0].network
                host = st.addresses[0].ip
                cfg = IfConfig(
                    area_id=IPv4Address(area_id),
                    if_type=(
                        IfType.POINT_TO_POINT
                        if if_conf.get("interface-type") == "point-to-point"
                        else IfType.BROADCAST
                    ),
                    cost=if_conf.get("cost", 10),
                    hello_interval=if_conf.get("hello-interval", 10),
                    dead_interval=if_conf.get("dead-interval", 40),
                    rxmt_interval=if_conf.get("retransmit-interval", 5),
                    priority=if_conf.get("priority", 1),
                    passive=if_conf.get("passive", False),
                    mtu=st.mtu,
                    mtu_ignore=if_conf.get("mtu-ignore", False),
                    transmit_delay=if_conf.get("transmit-delay", 1),
                    bfd_enabled=if_conf.get("bfd", False),
                    auth=self._ospf_auth(if_conf.get("authentication")),
                )
                inst.add_interface(ifname, cfg, addr, host, stub=stub,
                                   stub_default_cost=stub_cost, nssa=nssa)
                self.loop.send(inst.name, IfUpMsg(ifname))
            # area-type reconfig on an existing area (no new interfaces):
            aid = IPv4Address(area_id)
            if aid in inst.areas and (
                inst.areas[aid].stub != stub or inst.areas[aid].nssa != nssa
            ):
                inst.set_area_type(aid, stub=stub, nssa=nssa)
        # Auth is change-driven on running circuits too: an inline key
        # change must re-key immediately, not only on keychain events
        # (_last_tree is set before the apply chain runs).
        self._refresh_ospf_auth()
        if redist_changed:
            self._reconcile_redistribution(inst)

    @staticmethod
    def _frr_config(frr_conf):
        """ietf fast-reroute container -> FrrConfig (None = disabled).

        Shape (shared by OSPFv2/v3 and IS-IS):
          fast-reroute: {lfa: true, remote-lfa: bool, ti-lfa: bool,
                         engine: scalar|tpu}
        """
        if not frr_conf:
            return None
        from holo_tpu.frr.manager import FrrConfig

        return FrrConfig(
            enabled=bool(frr_conf.get("lfa", True)),
            remote_lfa=bool(frr_conf.get("remote-lfa", False)),
            ti_lfa=bool(frr_conf.get("ti-lfa", False)),
            engine=frr_conf.get("engine", "scalar"),
        )

    def _reconcile_redistribution(self, inst) -> None:
        """Replay the RIB against a changed redistribute set: inject
        now-wanted active routes, withdraw no-longer-wanted type-5s."""
        from holo_tpu.utils.southbound import Protocol

        wanted = self._ospf_redistribute
        active = self.rib.active_routes()
        backed: set = set()
        for prefix, routemsg in active.items():
            if prefix.version != 4:
                continue
            if (
                routemsg.protocol.value in wanted
                and routemsg.protocol != Protocol.OSPFV2
            ):
                backed.add(prefix)
                inst.redistribute(prefix, metric=max(routemsg.metric, 1))
        for prefix in list(inst.redistributed.keys()):
            if prefix not in backed:
                inst.withdraw_redistributed(prefix)

    def _ospf_auth(self, auth_conf):
        """Build an AuthCtx from interface auth config, resolving keychain
        references through the keychain provider (holo-keychain analog).

        FAIL-CLOSED: an unresolvable keychain reference yields a deny-all
        context (random key nobody shares) — never an unauthenticated
        interface.  The reference likewise drops packets when the key
        cannot be resolved.
        """
        import os as _os

        from holo_tpu.protocols.ospf.packet import AuthCtx, AuthType

        if not auth_conf:
            return None
        kc_name = auth_conf.get("key-chain")
        if kc_name:
            # Lifetime-based selection (keychain.rs:42-92): the active
            # SEND key signs, received key ids validate against their
            # ACCEPT lifetimes — rollover works.
            resolved = self._resolve_keychain(kc_name)
            if resolved is not None:
                return AuthCtx(
                    AuthType.CRYPTOGRAPHIC,
                    keychain=resolved,
                    clock=lambda: self.loop.clock.now(),
                )
            return AuthCtx(AuthType.CRYPTOGRAPHIC, _os.urandom(16), key_id=0)
        atype = auth_conf.get("type", "none")
        key = (auth_conf.get("key") or "").encode()
        if atype == "simple":
            return AuthCtx(AuthType.SIMPLE, key)
        if atype == "md5":
            return AuthCtx(AuthType.CRYPTOGRAPHIC, key, key_id=1)
        return None

    def _apply_ospfv3(self, new):
        from holo_tpu.protocols.ospf.instance_v3 import (
            OspfV3Instance,
            V3IfConfig,
            V3IfUpMsg,
        )
        from holo_tpu.utils.southbound import Protocol

        base = "routing/control-plane-protocols/ospfv3"
        conf = new.get(base)
        enabled = bool(conf) and new.get(f"{base}/enabled", True)
        inst = self.instances.get("ospfv3")
        if not enabled:
            if inst is not None:
                self._drop_instance_routes(Protocol.OSPFV3, inst.routes)
                self._unplace_instance(inst.name)
                del self.instances["ospfv3"]
            return
        router_id = new.get(f"{base}/router-id")
        if router_id is None:
            return
        if inst is not None and inst.router_id != IPv4Address(router_id):
            # Router-id change: restart the instance (new LSA identity).
            self._drop_instance_routes(Protocol.OSPFV3, inst.routes)
            self._unplace_instance(inst.name)
            del self.instances["ospfv3"]
            inst = None
        if inst is None:
            actor = f"{self.prefix}ospfv3"
            inst = OspfV3Instance(
                name=actor,
                router_id=IPv4Address(router_id),
                netio=self.netio_factory(actor),
                route_cb=self._ospfv3_routes_to_rib,
                notif_cb=self.yang_notify,
            )
            inst = self._place_instance(inst)
            self.instances["ospfv3"] = inst
        # IP fast reroute + RFC 6987 stub-router (same leaves as v2).  An
        # FRR change forces a full SPF so backup tables and published
        # routes follow the new policy immediately.
        new_frr = self._frr_config(new.get(f"{base}/fast-reroute"))
        if new_frr != inst.frr:
            inst.frr = new_frr
            inst._schedule_spf()
        inst.set_stub_router(bool(new.get(f"{base}/stub-router", False)))
        areas = new.get(f"{base}/area", {}) or {}
        for area_id, area_conf in areas.items():
            for ifname, if_conf in (area_conf.get("interface") or {}).items():
                if ifname in inst.interfaces:
                    # Live reconfiguration (reference InterfaceUpdate
                    # family analog); auth refreshes below.
                    st = self.ifp.interfaces.get(ifname)
                    inst.iface_cost_update(ifname, if_conf.get("cost", 10))
                    inst.iface_update(
                        ifname,
                        hello=if_conf.get("hello-interval", 10),
                        dead=if_conf.get("dead-interval", 40),
                        priority=if_conf.get("priority", 1),
                        passive=if_conf.get("passive", False),
                        mtu=st.mtu if st is not None else None,
                        mtu_ignore=if_conf.get("mtu-ignore", False),
                        transmit_delay=if_conf.get("transmit-delay", 1),
                    )
                    continue
                st = self.ifp.interfaces.get(ifname)
                if st is None:
                    continue
                v6 = [a for a in st.addresses if a.version == 6]
                if not v6:
                    continue
                link_local = next(
                    (a.ip for a in v6 if a.ip.is_link_local), v6[0].ip
                )
                prefixes = [a.network for a in v6 if not a.ip.is_link_local]
                inst.add_interface(
                    ifname,
                    V3IfConfig(
                        area_id=IPv4Address(area_id),
                        cost=if_conf.get("cost", 10),
                        hello_interval=if_conf.get("hello-interval", 10),
                        dead_interval=if_conf.get("dead-interval", 40),
                        priority=if_conf.get("priority", 1),
                        passive=if_conf.get("passive", False),
                        mtu=st.mtu,
                        mtu_ignore=if_conf.get("mtu-ignore", False),
                        transmit_delay=if_conf.get("transmit-delay", 1),
                        auth=self._ospfv3_auth(
                            if_conf.get("authentication")
                        ),
                    ),
                    link_local,
                    prefixes,
                )
                self.loop.send(inst.name, V3IfUpMsg(ifname))
        # Auth is change-driven on running circuits too.
        self._refresh_ospfv3_auth(new)

    def _ospfv3_auth(self, auth_conf):
        """RFC 7166 authentication-trailer context from interface config
        (reference configuration.rs ospfv3_key_chain + sa paths): a
        key-chain resolves by lifetime with the SA id as the key id; an
        inline key uses sa-id + crypto-algorithm.  Unknown chain names
        FAIL CLOSED with a random key nobody shares."""
        import os as _os

        from holo_tpu.protocols.ospf.packet_v3 import AuthCtxV3

        if not auth_conf:
            return None
        kc_name = auth_conf.get("key-chain")
        if kc_name:
            resolved = self._resolve_keychain(kc_name)
            if resolved is not None:
                return AuthCtxV3(
                    key=b"",
                    keychain=resolved,
                    clock=lambda: self.loop.clock.now(),
                )
            return AuthCtxV3(key=_os.urandom(16))
        key = auth_conf.get("key")
        if not key:
            return None
        return AuthCtxV3(
            key=key.encode(),
            sa_id=auth_conf.get("sa-id", 1) & 0xFFFF,
            algo=auth_conf.get("crypto-algorithm", "sha256"),
        )

    def _refresh_ospfv3_auth(self, tree=None) -> None:
        """(Re)apply v3 circuit auth — change-driven per commit AND on
        keychain store updates (the _refresh_ospf_auth analog)."""
        tree = tree if tree is not None else getattr(self, "_last_tree", None)
        inst = self.instances.get("ospfv3")
        if tree is None or inst is None:
            return
        areas = tree.get(
            "routing/control-plane-protocols/ospfv3/area", {}
        ) or {}
        for area_conf in areas.values():
            for ifname, if_conf in (
                area_conf.get("interface") or {}
            ).items():
                iface = inst.interfaces.get(ifname)
                if iface is not None:
                    iface.config.auth = self._ospfv3_auth(
                        if_conf.get("authentication")
                    )

    def _sink_routes(self, protocol, items: dict) -> None:
        """Shared delta route sink: items = {prefix: (metric, {(if, addr)})}
        or, with IP-FRR repairs, (metric, nhs, {primary -> (backup,
        labels)}) — the backups ride the RouteMsg so the RIB can flip to
        them on BFD/link-down without waiting for this layer.

        Caches the last pushed set per protocol so unchanged routes skip
        RIB churn; the cache is cleared when the instance stops (otherwise
        a disable/re-enable would suppress re-installation).
        """
        from holo_tpu.utils.southbound import (
            DEFAULT_DISTANCE,
            Nexthop,
            RouteKeyMsg,
            RouteMsg,
        )

        caches = getattr(self, "_route_caches", None)
        if caches is None:
            caches = self._route_caches = {}
        old = caches.get(protocol, {})
        for prefix in old.keys() - items.keys():
            self.rib.route_del(RouteKeyMsg(protocol, prefix))
        for prefix, entry in items.items():
            if old.get(prefix) == entry:
                continue
            metric, nhs = entry[0], entry[1]
            raw_backups = entry[2] if len(entry) > 2 else None
            backups = {}
            for (pi, pa), ((bi, ba), labels) in (raw_backups or {}).items():
                if pa is None or ba is None:
                    continue
                backups[Nexthop(addr=pa, ifname=pi)] = Nexthop(
                    addr=ba, ifname=bi, labels=tuple(labels)
                )
            self.rib.route_add(
                RouteMsg(
                    protocol=protocol,
                    prefix=prefix,
                    distance=DEFAULT_DISTANCE.get(protocol, 250),
                    metric=metric,
                    nexthops=frozenset(
                        Nexthop(addr=a, ifname=i) for i, a in nhs
                    ),
                    backups=backups,
                )
            )
        caches[protocol] = dict(items)

    def _drop_instance_routes(self, protocol, inst_routes) -> None:
        from holo_tpu.utils.southbound import RouteKeyMsg

        for prefix in inst_routes:
            self.rib.route_del(RouteKeyMsg(protocol, prefix))
        if getattr(self, "_route_caches", None):
            self._route_caches.pop(protocol, None)

    def _ospfv3_routes_to_rib(self, routes):
        from holo_tpu.utils.southbound import Protocol

        self._sink_routes(
            Protocol.OSPFV3,
            {
                p: (
                    r.dist,
                    frozenset(r.nexthops),
                    getattr(r, "backups", None),
                )
                for p, r in routes.items()
            },
        )

    def _apply_isis(self, new):
        from holo_tpu.protocols.isis.instance import (
            IsisIfConfig,
            IsisIfUpMsg,
            IsisInstance,
        )
        from holo_tpu.utils.southbound import Protocol, RouteKeyMsg

        base = "routing/control-plane-protocols/isis"
        conf = new.get(base)
        enabled = bool(conf) and new.get(f"{base}/enabled", True)
        inst = self.instances.get("isis")
        if not enabled:
            if inst is not None:
                self._drop_instance_routes(Protocol.ISIS, inst.routes)
                self._unplace_instance(inst.name)
                del self.instances["isis"]
            return
        system_id = new.get(f"{base}/system-id")
        if system_id is None:
            return
        sysid = _parse_system_id(system_id)
        if sysid is None:
            return  # rejected in validate(); defensive here
        level_cfg = new.get(f"{base}/level", "level-all")
        if inst is not None and (
            inst.sysid != sysid
            or getattr(inst, "level_name", None) != level_cfg
        ):
            # System-id or level change requires a new incarnation:
            # withdraw and restart (mirrors disable+enable).
            from holo_tpu.utils.southbound import Protocol

            self._drop_instance_routes(Protocol.ISIS, inst.routes)
            self._unplace_instance(inst.name)
            del self.instances["isis"]
            inst = None
        if inst is None:
            actor = f"{self.prefix}isis"
            if level_cfg == "level-all":
                from holo_tpu.protocols.isis.multi import (
                    IsisLevelAllInstance,
                )

                raw = IsisLevelAllInstance(
                    actor, sysid, b"\x49\x00\x01",
                    netio=self.netio_factory(actor),
                    notif_cb=self.yang_notify,
                )
            else:
                raw = IsisInstance(
                    name=actor,
                    sysid=sysid,
                    level=1 if level_cfg == "level-1" else 2,
                    netio=self.netio_factory(actor),
                    notif_cb=self.yang_notify,
                )
                if level_cfg == "level-1":
                    raw.is_type = 0x01
                # level-2 keeps the default 0x03: ISO 10589 §9.9 requires
                # the L1-IS bit set even on L2-only systems
                # (reference lsdb.rs:202-207).
            raw.level_name = level_cfg
            # The RIB feed carries the installable view (route.rs:285-301:
            # connected prefixes stay out — the kernel owns them as
            # DIRECT).  last_installable is a snapshot the instance
            # thread published as ONE assignment after the SPF settled,
            # so this marshalled closure never sees a torn
            # routes/connected combination.
            raw.route_cb = lambda _r: self._isis_routes_to_rib(
                raw.last_installable
            )
            inst = self._place_instance(raw)
            self.instances["isis"] = inst
        # IP fast reroute (default-topology LFA; same container shape as
        # the OSPF instances).  A change schedules a topology SPF so the
        # backup tables and published routes follow the new policy.
        new_frr = self._frr_config(new.get(f"{base}/fast-reroute"))
        if new_frr != inst.frr:
            inst.frr = new_frr
            inst._schedule_spf()
        # Configured interface order for operational-state rendering: a
        # down interface leaves inst.interfaces but must still render.
        self._isis_ifnames = list(new.get(f"{base}/interface") or {})
        for ifname, if_conf in (new.get(f"{base}/interface") or {}).items():
            if ifname in inst.interfaces:
                # Live reconfiguration on the running circuit (reference
                # InterfaceUpdate): metric changes re-originate the LSP;
                # auth refreshes via _apply_isis_auth below.  Through
                # the handle so threaded marshalling holds (the L1/L2
                # node fans the call out to both levels itself).
                inst.iface_metric_update(ifname, if_conf.get("metric", 10))
                continue
            st = self.ifp.interfaces.get(ifname)
            if st is None or not st.addresses:
                continue
            inst.add_interface(
                ifname,
                IsisIfConfig(
                    metric=if_conf.get("metric", 10),
                    auth=self._isis_auth(
                        if_conf.get("hello-authentication")
                    ),
                ),
                st.addresses[0].ip,
                st.addresses[0].network,
            )
            if hasattr(inst, "instances"):
                # L1/L2 node: marshalled method call reaches both levels.
                inst.if_up(ifname)
            else:
                self.loop.send(inst.name, IsisIfUpMsg(ifname))
        # Authentication is change-driven on the RUNNING instance
        # (reference configuration.rs:531-597 reacts to the config
        # change): enabling/changing/removing auth applies immediately,
        # not only at instance creation.
        self._apply_isis_auth(inst, new)

    def _resolve_keychain(self, name):
        """Keychain object from the provider store, or None when the
        reference is unknown/empty (callers FAIL CLOSED).  Shared by the
        OSPF and IS-IS auth builders so keychain-resolution semantics
        cannot drift between protocols."""
        from holo_tpu.utils.keychain import Keychain

        kc = (
            self.keychains.keychains.get(name)
            if self.keychains is not None
            else None
        )
        if kc and kc.get("key"):
            return Keychain.from_config(name, kc)
        return None

    def _isis_auth(self, auth_conf):
        """AuthCtxIsis from IS-IS auth config: a key-chain reference
        resolves keys by lifetime (utils/keychain.py), an inline key is
        fixed (reference packet/auth.rs AuthMethod::{Keychain,ManualKey};
        config surface configuration.rs:531-597).  Unknown key-chain
        names FAIL CLOSED with a random key nobody shares."""
        import os as _os

        from holo_tpu.protocols.isis.packet import AuthCtxIsis

        if not auth_conf:
            return None
        kc_name = auth_conf.get("key-chain")
        if kc_name:
            resolved = self._resolve_keychain(kc_name)
            if resolved is not None:
                return AuthCtxIsis(
                    key=b"",
                    keychain=resolved,
                    clock=lambda: self.loop.clock.now(),
                )
            return AuthCtxIsis(key=_os.urandom(16))
        key = auth_conf.get("key")
        if not key:
            return None
        return AuthCtxIsis(
            key=key.encode(),
            # The RFC 5310 TLV carries a u16 key id: mask here so two
            # identically-configured peers agree on the wire value.
            key_id=auth_conf.get("key-id", 1) & 0xFFFF,
            algo=auth_conf.get("crypto-algorithm", "hmac-md5"),
        )

    def _apply_isis_auth(self, inst, tree) -> None:
        """(Re)apply instance + hello authentication from the isis
        config subtree — change-driven, every commit AND on keychain
        store updates (the OSPF _refresh_ospf_auth analog)."""
        base = "routing/control-plane-protocols/isis"
        auth = self._isis_auth(tree.get(f"{base}/authentication"))
        subs = (
            list(inst.instances())
            if hasattr(inst, "instances") and callable(inst.instances)
            else [inst]
        )
        for sub in subs:
            sub.auth = auth
        for ifname, if_conf in (tree.get(f"{base}/interface") or {}).items():
            for sub in subs:
                iface = sub.interfaces.get(ifname)
                if iface is not None:
                    iface.config.auth = self._isis_auth(
                        if_conf.get("hello-authentication")
                    )

    def _refresh_isis_auth(self) -> None:
        """Keychain store changed: re-resolve IS-IS auth contexts so the
        instances see the NEW key set (not the snapshot taken at the
        last config commit) — key rollover reaches IS-IS live."""
        tree = getattr(self, "_last_tree", None)
        inst = self.instances.get("isis")
        if tree is None or inst is None:
            return
        self._apply_isis_auth(inst, tree)

    def _isis_routes_to_rib(self, routes):
        from holo_tpu.utils.southbound import Protocol

        inst = self.instances.get("isis")
        frr_backups = getattr(inst, "frr_backups", None) or {}
        self._sink_routes(
            Protocol.ISIS,
            {
                p: (metric, frozenset(nhs), frr_backups.get(p))
                for p, (metric, nhs) in routes.items()
            },
        )

    def _apply_ldp(self, new):
        """LDP lifecycle from config (reference: holo-ldp spawn path).

        Egress FECs are seeded from the connected networks of the
        LDP-enabled interfaces; the LIB is surfaced in operational
        state.  label-distribution-control selects RFC 5036 §2.6
        independent vs ordered mode (a mode change restarts the LSR,
        like the reference's instance reconfiguration)."""
        from ipaddress import IPv4Address

        from holo_tpu.protocols.ldp import LdpInstance

        base = "routing/control-plane-protocols/ldp"
        conf = new.get(base)
        enabled = bool(conf) and new.get(f"{base}/enabled", True)
        lsr_id = new.get(f"{base}/lsr-id")
        inst = self.instances.get("ldp")
        if not enabled or lsr_id is None:
            if inst is not None:
                self._unplace_instance(inst.name)
                del self.instances["ldp"]
                self._uninstall_ldp_labels()
            return
        mode = new.get(
            f"{base}/label-distribution-control", "independent"
        )
        if inst is not None and (
            str(inst.lsr_id) != lsr_id or inst.control_mode != mode
        ):
            self._unplace_instance(inst.name)
            del self.instances["ldp"]
            self._uninstall_ldp_labels()
            inst = None
        if inst is None:
            actor = f"{self.prefix}ldp"
            inst = LdpInstance(
                name=actor,
                lsr_id=IPv4Address(lsr_id),
                netio=self.netio_factory(actor),
                control_mode=mode,
                lib_cb=self._ldp_lib_changed,
                notif_cb=self.yang_notify,
            )
            inst = self._place_instance(inst)
            self.instances["ldp"] = inst
        wanted = set(new.get(f"{base}/interface") or {})
        for ifname in list(inst.interfaces):
            if ifname not in wanted:
                st = self.ifp.interfaces.get(ifname)
                fec = (
                    st.addresses[0].network
                    if st is not None and st.addresses
                    else None
                )
                inst.remove_interface(ifname, fec)
        for ifname in wanted:
            if ifname in inst.interfaces:
                continue
            st = self.ifp.interfaces.get(ifname)
            if st is None or not st.addresses:
                continue
            addr = st.addresses[0]
            inst.add_interface(ifname, addr.ip)
            # Directly-attached networks are egress FECs (implicit null).
            inst.add_fec(addr.network, egress=True)

    def _apply_rip(self, new):
        """RIPv2/RIPng lifecycle from config (reference: holo-rip spawn
        path; both families share the Version-strategy instance)."""
        from holo_tpu.protocols.rip import (
            RipIfConfig,
            RipInstance,
            RipngVersion,
            RipVersion,
        )
        from holo_tpu.utils.southbound import Protocol

        for proto, version, want_v6 in (
            ("ripv2", RipVersion, False),
            ("ripng", RipngVersion, True),
        ):
            base = f"routing/control-plane-protocols/{proto}"
            conf = new.get(base)
            enabled = bool(conf) and new.get(f"{base}/enabled", True)
            inst = self.instances.get(proto)
            sink_proto = Protocol.RIPV2 if proto == "ripv2" else Protocol.RIPNG
            if not enabled:
                if inst is not None:
                    self._sink_routes(sink_proto, {})  # delta-clears RIB
                    self._unplace_instance(inst.name)
                    del self.instances[proto]
                continue
            if inst is None:
                actor = f"{self.prefix}{proto}"
                raw = RipInstance(
                    name=actor,
                    netio=self.netio_factory(actor),
                    update_interval=new.get(f"{base}/update-interval", 30),
                    timeout=new.get(f"{base}/invalid-interval", 180),
                    garbage=max(
                        new.get(f"{base}/flush-interval", 240)
                        - new.get(f"{base}/invalid-interval", 180),
                        1,
                    ),
                    version=version,
                )
                # The RIB feed installs LEARNED routes only — connected
                # prefixes stay with the kernel/DIRECT (same rule as
                # OSPF/IS-IS; the reference never installs them).
                raw.route_cb = lambda routes, rp=sink_proto: (
                    self._sink_routes(
                        rp,
                        {
                            p: (
                                r.metric,
                                frozenset({(r.ifname, r.nexthop)}),
                            )
                            for p, r in routes.items()
                            if r.route_type != "connected"
                            and r.nexthop is not None
                        },
                    )
                )
                inst = self._place_instance(raw)
                self.instances[proto] = inst
            # Timers reconfigure in place (they are read per tick).
            inst.update_interval = new.get(f"{base}/update-interval", 30)
            inst.timeout = new.get(f"{base}/invalid-interval", 180)
            inst.garbage = max(
                new.get(f"{base}/flush-interval", 240) - inst.timeout, 1
            )
            wanted = new.get(f"{base}/interface") or {}
            for ifname, if_conf in wanted.items():
                cost = if_conf.get("cost", 1)
                split = if_conf.get("split-horizon", "poison-reverse")
                akw = (
                    {}
                    if want_v6  # RFC 2080: RIPng has no in-protocol auth
                    else self._rip_auth_kwargs(if_conf.get("authentication"))
                )
                cur = inst.interfaces.get(ifname)
                if cur is not None:
                    # Live reconfiguration (reference configuration.rs
                    # InterfaceCostUpdate): metrics recompute table-wide;
                    # auth changes apply to the running circuit.
                    if cur[0].cost != cost:
                        inst.iface_cost_update(ifname, cost)
                    cur[0].split_horizon = split
                    self._set_rip_auth(cur[0], akw)
                    continue
                st = self.ifp.interfaces.get(ifname)
                if st is None:
                    continue
                addrs = [
                    a for a in st.addresses
                    if (a.ip.version == 6) == want_v6
                ]
                if not addrs:
                    continue
                a = addrs[0]
                inst.add_interface(
                    ifname,
                    RipIfConfig(cost=cost, split_horizon=split, **akw),
                    a.ip,
                    a.network,
                )
            for ifname in list(inst.interfaces):
                if ifname not in wanted:
                    inst.remove_interface(ifname)

    def _rip_auth_kwargs(self, auth_conf) -> dict:
        """RipIfConfig auth fields from interface auth config (reference
        holo-rip configuration.rs:309-339 key + crypto-algorithm; the
        key-chain option adds lifetime-resolved keys).  Unknown chain
        names FAIL CLOSED with a random key nobody shares."""
        import os as _os

        if not auth_conf:
            return {}
        kc_name = auth_conf.get("key-chain")
        if kc_name:
            resolved = self._resolve_keychain(kc_name)
            if resolved is None:
                return {"auth_key": _os.urandom(16)}
            return {
                "auth_keychain": resolved,
                "auth_clock": lambda: self.loop.clock.now(),
            }
        key = auth_conf.get("key")
        if not key:
            return {}
        if auth_conf.get("type", "md5") == "password":
            return {"auth_password": key}
        return {
            # RFC 2082 carries a u8 key id on the wire.
            "auth_key": key.encode(),
            "auth_key_id": auth_conf.get("key-id", 1) & 0xFF,
        }

    def _set_rip_auth(self, cfg, akw: dict) -> None:
        """Apply resolved auth kwargs onto a live RipIfConfig (absent
        keys clear — removing auth config really removes auth)."""
        cfg.auth_password = akw.get("auth_password")
        cfg.auth_key = akw.get("auth_key")
        cfg.auth_key_id = akw.get("auth_key_id", 1)
        cfg.auth_keychain = akw.get("auth_keychain")
        cfg.auth_clock = akw.get("auth_clock")

    def _refresh_rip_auth(self) -> None:
        """Keychain store changed: re-resolve keychain-backed RIP
        circuits (the OSPF/IS-IS refresh analog)."""
        tree = getattr(self, "_last_tree", None)
        inst = self.instances.get("ripv2")
        if tree is None or inst is None:
            return
        base = "routing/control-plane-protocols/ripv2"
        for ifname, if_conf in (tree.get(f"{base}/interface") or {}).items():
            cur = inst.interfaces.get(ifname)
            auth_conf = if_conf.get("authentication")
            if cur is not None and auth_conf and auth_conf.get("key-chain"):
                self._set_rip_auth(
                    cur[0], self._rip_auth_kwargs(auth_conf)
                )

    def _apply_igmp(self, new):
        """IGMP querier lifecycle from config (reference: holo-igmp
        spawn inside holo-routing).  Kernel VIF programming engages when
        the multicast routing socket is available (root)."""
        from holo_tpu.protocols.igmp import IgmpIfConfig, IgmpInstance

        base = "routing/control-plane-protocols/igmp"
        conf = new.get(base)
        wanted = (new.get(f"{base}/interface") or {}) if conf else {}
        inst = self.instances.get("igmp")
        if not wanted:
            if inst is not None:
                # Tear down kernel state first: del_vif per interface,
                # then release the one-per-system MRT socket so a
                # re-enable can MRT_INIT again.
                for ifname in list(inst.interfaces):
                    inst.remove_interface(ifname)
                if inst.mroute is not None:
                    inst.mroute.close()
                self._unplace_instance(inst.name)
                del self.instances["igmp"]
            return
        if inst is None:
            actor = f"{self.prefix}igmp"
            mroute = None
            import os

            if os.geteuid() == 0:
                try:
                    from holo_tpu.routing.mroute import MulticastRouting

                    mroute = MulticastRouting()
                except OSError:
                    mroute = None  # no kernel mcast socket: queried-only
            inst = self._place_instance(
                IgmpInstance(
                    name=actor,
                    netio=self.netio_factory(actor),
                    mroute=mroute,
                )
            )
            self.instances["igmp"] = inst
        for ifname, if_conf in wanted.items():
            if ifname in inst.interfaces:
                continue
            st = self.ifp.interfaces.get(ifname)
            if st is None or not st.addresses:
                continue
            v4 = [a for a in st.addresses if a.ip.version == 4]
            if not v4:
                continue
            inst.add_interface(
                ifname,
                IgmpIfConfig(
                    version=if_conf.get("version", 2),
                    query_interval=if_conf.get("query-interval", 125),
                ),
                v4[0].ip,
                ifindex=getattr(st, "ifindex", None),
            )
        for ifname in list(inst.interfaces):
            if ifname not in wanted:
                inst.remove_interface(ifname)

    def _apply_vrrp(self, new):
        """VRRP lifecycle: one instance per (interface, vrid).  The master
        owns a macvlan carrying the virtual MAC 00:00:5e:00:01:<vrid> and
        the virtual addresses (reference holo-vrrp/src/instance.rs:301-311
        macvlan programming); backup/init tears it down."""
        from ipaddress import ip_address

        from holo_tpu.protocols.vrrp import VrrpConfig, VrrpInstance

        base = "routing/control-plane-protocols/vrrp"
        wanted = {}
        for vrid_s, entry in (new.get(f"{base}/instance") or {}).items():
            vrid = int(entry.get("vrid", vrid_s))
            ifname = entry.get("interface")
            if ifname is None:
                continue
            st = self.ifp.interfaces.get(ifname)
            if st is None or not st.addresses:
                continue
            wanted[vrid] = (ifname, entry, st.addresses[0].ip)
        have = getattr(self, "vrrp_instances", {})
        self.vrrp_instances = have

        def _stop(vrid):
            inst = have.pop(vrid)
            inst.shutdown()  # on_state(INITIALIZE) removes the macvlan
            self._unplace_instance(inst.name)

        for vrid in list(have.keys() - wanted.keys()):
            _stop(vrid)
        for vrid, (ifname, entry, addr) in wanted.items():
            cfg = VrrpConfig(
                vrid=vrid,
                ifname=ifname,
                version=int(entry.get("version", 3)),
                priority=entry.get("priority", 100),
                advert_interval=entry.get("advertise-interval", 1),
                addresses=[
                    ip_address(a) for a in entry.get("virtual-address", [])
                ],
            )
            if vrid in have:
                if have[vrid].config == cfg:
                    continue
                # Config changed: restart with the new parameters (the
                # reference reconfigures the per-interface instance).
                _stop(vrid)
            actor = f"{self.prefix}vrrp-{ifname}-{vrid}"
            inst = VrrpInstance(
                name=actor,
                config=cfg,
                iface_addr=addr,
                netio=self.netio_factory(actor),
                notif_cb=self.yang_notify,
            )
            inst.vrrp_ifname = ifname
            inst.on_state = (
                lambda state, i=inst: self._vrrp_state_changed(i, state)
            )
            inst = self._place_instance(inst)
            have[vrid] = inst
            inst.startup()

    def _vrrp_macvlan(self, inst) -> str:
        # Kernel IFNAMSIZ is 16 incl. NUL; keep the vrid even when the
        # parent name gets truncated.
        return f"vrrp{inst.config.vrid}.{inst.vrrp_ifname}"[:15]

    def _vrrp_state_changed(self, inst, state) -> None:
        from ipaddress import ip_interface

        from holo_tpu.protocols.vrrp import VrrpState

        if self.link_mgr is None:
            return
        name = self._vrrp_macvlan(inst)
        if state == VrrpState.MASTER:
            # RFC 5798 §7.3 virtual MAC.
            mac = bytes((0x00, 0x00, 0x5E, 0x00, 0x01, inst.config.vrid))
            self.link_mgr.create_macvlan(inst.vrrp_ifname, name, mac)
            for addr in inst.config.addresses:
                self.link_mgr.add_address(
                    name, ip_interface(f"{addr}/{addr.max_prefixlen}")
                )
            self.link_mgr.set_link(name, up=True)
        else:
            self.link_mgr.delete_link(name)

    def _apply_bgp(self, new):
        """BGP lifecycle from config (reference: holo-bgp spawn path).

        Policies referenced by neighbors resolve through the policy
        provider's engine (set at wiring time via ``policy_engine``).
        """
        from ipaddress import ip_address

        from holo_tpu.protocols.bgp import BgpInstance, PeerConfig
        from holo_tpu.utils.southbound import Protocol

        base = "routing/control-plane-protocols/bgp"
        conf = new.get(base)
        inst = self.instances.get("bgp")
        asn = new.get(f"{base}/as")
        router_id = new.get(f"{base}/router-id")
        if not conf or asn is None or router_id is None:
            # Subtree (or its identity leaves) gone: tear down fully.
            if inst is not None:
                self._drop_instance_routes(Protocol.BGP, list(inst.loc_rib))
                self._unplace_instance(inst.name)
                del self.instances["bgp"]
                self._close_bgp_tcp()
            return
        wanted_transport = (
            new.get(f"{base}/transport", "fabric"),
            new.get(f"{base}/port", 179),
        )
        if inst is not None and (
            inst.asn != asn
            or inst.router_id != IPv4Address(router_id)
            or wanted_transport != getattr(self, "_bgp_transport", wanted_transport)
        ):
            # Speaker identity or transport change: restart (new OPENs,
            # fresh RIBs, fresh sockets).
            self._drop_instance_routes(Protocol.BGP, list(inst.loc_rib))
            self._unplace_instance(inst.name)
            del self.instances["bgp"]
            self._close_bgp_tcp()
            inst = None
        self._bgp_transport = wanted_transport
        tcp_io = getattr(self, "bgp_tcp_io", None)
        if inst is None:
            actor = f"{self.prefix}bgp"
            # Transport: real TCP sessions (production; RFC 4271 §8 over
            # holo-bgp/src/network.rs semantics) or the in-memory fabric
            # (deterministic tests).
            if new.get(f"{base}/transport") == "tcp":
                from holo_tpu.utils.tcpio import BgpTcpIo

                tcp_io = BgpTcpIo(
                    self.loop, actor, port=new.get(f"{base}/port", 179)
                )
                self.bgp_tcp_io = tcp_io
                netio = tcp_io
            else:
                netio = self.netio_factory(actor)
            inst = BgpInstance(
                name=actor,
                asn=asn,
                router_id=IPv4Address(router_id),
                netio=netio,
                route_cb=self._bgp_route_cb,
                notif_cb=self.yang_notify,
            )
            inst = self._place_instance(inst)
            self.instances["bgp"] = inst
        engine = self.policy_engine
        wanted_peers = set()
        for addr_s, n in (new.get(f"{base}/neighbor") or {}).items():
            addr = ip_address(n.get("address", addr_s))
            wanted_peers.add(addr)
            if addr in inst.peers:
                if tcp_io is not None:
                    # MD5 key rotation on a live neighbor re-keys the
                    # listeners and resets the session.
                    tcp_io.update_md5(
                        addr,
                        n["authentication-key"].encode()
                        if n.get("authentication-key")
                        else None,
                    )
                    tcp_io.update_mss(addr, n.get("tcp-mss") or None)
                continue
            # Outgoing interface: longest-prefix interface subnet
            # containing the peer (single-hop eBGP/iBGP assumption).
            ifname = None
            local = None
            best_len = -1
            for st in self.ifp.interfaces.values():
                for a in st.addresses:
                    if (
                        a.version == addr.version
                        and addr in a.network
                        and a.network.prefixlen > best_len
                    ):
                        ifname, local = st.name, a.ip
                        best_len = a.network.prefixlen
            if ifname is None:
                continue
            imp = exp = None
            if engine is not None:
                # Scope the hooks to this peer so match-neighbor-set
                # conditions see the route's source address.
                if n.get("import-policy"):
                    imp = engine.bgp_import_hook(
                        n["import-policy"], neighbor=addr
                    )
                if n.get("export-policy"):
                    exp = engine.bgp_import_hook(
                        n["export-policy"], neighbor=addr
                    )
            inst.add_peer(
                PeerConfig(
                    addr,
                    n.get("peer-as", asn),
                    ifname,
                    hold_time=n.get("hold-time", 90),
                    connect_retry=n.get("connect-retry-interval", 30),
                    import_policy=imp,
                    export_policy=exp,
                ),
                local,
            )
            if tcp_io is not None:
                try:
                    tcp_io.listen(local)  # idempotent per address
                except OSError as e:
                    log.error(
                        "BGP listen on %s:%s failed: %s (passive peers "
                        "cannot connect in)",
                        local, wanted_transport[1], e,
                    )
                tcp_io.add_peer(
                    local, addr, ifname=ifname,
                    md5_key=(
                        n["authentication-key"].encode()
                        if n.get("authentication-key")
                        else None
                    ),
                    # 0 means "not configured" (the uint8 leaf default).
                    ttl_security=n.get("ttl-security") or None,
                    tcp_mss=n.get("tcp-mss") or None,
                )
            inst.start_peer(addr)
        # Neighbors removed from config: drop the session + their routes.
        for addr in list(inst.peers.keys() - wanted_peers):
            inst.remove_peer(addr)
            if tcp_io is not None:
                tcp_io.remove_peer(addr)
        # network statements: locally originated routes (v4 or v6).
        from ipaddress import ip_network

        wanted_nets = set()
        for p_s, nconf in (new.get(f"{base}/network") or {}).items():
            prefix = ip_network(nconf.get("prefix", p_s), strict=False)
            wanted_nets.add(prefix)
            if prefix not in inst.originated:
                inst.originate(prefix)
        for prefix in list(inst.originated.keys() - wanted_nets):
            del inst.originated[prefix]
            inst._decision(prefix)

    def _rib_changed(self) -> None:
        """RIB delta: keep the LDP FEC table in lockstep (routed prefixes
        become transit FECs with real labels; reference seeds FECs from
        the RIB the same way) and refresh LFIB entries whose next hops
        may have moved."""
        ldp = self.instances.get("ldp")
        if ldp is None:
            return
        active = {
            prefix: msg
            for prefix, msg in self.rib.active_routes().items()
            if prefix.version == 4
        }
        from holo_tpu.utils.southbound import Protocol

        for prefix, msg in active.items():
            if msg.protocol == Protocol.DIRECT:
                continue  # connected nets are egress FECs (iface seeding)
            if prefix not in ldp.fec_table:
                ldp.add_fec(prefix, egress=False)
        for prefix, (label, egress) in list(ldp.fec_table.items()):
            if not egress and prefix not in active:
                ldp.remove_fec(prefix)
        # Ordered mode eligibility (§2.6.1): each FEC's downstream LSR is
        # the neighbor owning the route's next hop.
        nexthop_lsr = {}
        for prefix, msg in active.items():
            for nh in msg.nexthops:
                for lsr, nbr in ldp.neighbors.items():
                    if nbr.addr == nh.addr:
                        nexthop_lsr[prefix] = lsr
                        break
        ldp.set_nexthops(nexthop_lsr)
        self._ldp_lib_changed(ldp.lib())

    def _uninstall_ldp_labels(self) -> None:
        from holo_tpu.utils.southbound import LabelUninstallMsg, Protocol

        for label, msg in list(self.rib.mpls.items()):
            if msg.protocol == Protocol.LDP:
                self.rib.label_del(
                    LabelUninstallMsg(protocol=Protocol.LDP, label=label)
                )

    def _ldp_lib_changed(self, lib: dict) -> None:
        """Merge the LDP LIB with RIB next hops into LFIB entries
        (reference holo-routing/src/rib.rs:152-212): for every FEC with a
        real local label, the in-label swaps to the downstream peer's
        binding (implicit-null => penultimate-hop pop) along the FEC's
        routed next hops; egress FECs keep implicit-null and install
        nothing."""
        from holo_tpu.utils.mpls import IMPLICIT_NULL
        from holo_tpu.utils.southbound import (
            LabelInstallMsg,
            LabelUninstallMsg,
            Nexthop,
            Protocol,
        )

        ldp = self.instances.get("ldp")
        wanted: dict[int, LabelInstallMsg] = {}
        for fec, entry in lib.items():
            local = entry["local"]
            if entry.get("egress") or local == IMPLICIT_NULL:
                continue
            pr = self.rib.routes.get(fec)
            best = None
            if pr is not None:
                for e in pr.entries.values():
                    if e.active:
                        best = e.msg
                        break
            if best is None:
                continue
            # Downstream peer = the neighbor owning the route's next hop.
            remote = entry.get("remote", {})
            nhs = set()
            for nh in best.nexthops:
                out_label = None
                for lsr, label in remote.items():
                    nbr = ldp.neighbors.get(IPv4Address(lsr)) if ldp else None
                    if nbr is not None and nbr.addr == nh.addr:
                        out_label = label
                        break
                if out_label is None:
                    continue
                labels = () if out_label == IMPLICIT_NULL else (out_label,)
                nhs.add(
                    Nexthop(
                        addr=nh.addr,
                        ifname=nh.ifname,
                        ifindex=nh.ifindex,
                        labels=labels,
                    )
                )
            if nhs:
                wanted[local] = LabelInstallMsg(
                    protocol=Protocol.LDP,
                    label=local,
                    nexthops=frozenset(nhs),
                    route=(fec,),
                )
        current = {
            label
            for label, msg in self.rib.mpls.items()
            if msg.protocol == Protocol.LDP
        }
        for label, msg in wanted.items():
            self.rib.label_add(msg)
        for label in current - set(wanted):
            self.rib.label_del(
                LabelUninstallMsg(protocol=Protocol.LDP, label=label)
            )

    def _close_bgp_tcp(self):
        io = getattr(self, "bgp_tcp_io", None)
        if io is not None:
            io.close()
            self.bgp_tcp_io = None

    def _bgp_route_cb(self, prefix, best):
        from holo_tpu.utils.southbound import (
            DEFAULT_DISTANCE,
            Nexthop,
            Protocol,
            RouteKeyMsg,
            RouteMsg,
        )

        if best is None or best.peer is None:
            self.rib.route_del(RouteKeyMsg(Protocol.BGP, prefix))
            return
        from ipaddress import IPv6Network

        nh = (
            best.attrs.nh6
            if isinstance(prefix, IPv6Network)
            else best.attrs.next_hop
        )
        if nh is None:
            # No usable next hop for this family: never install a
            # blackhole; drop any previous entry instead.
            self.rib.route_del(RouteKeyMsg(Protocol.BGP, prefix))
            return
        self.rib.route_add(
            RouteMsg(
                protocol=Protocol.BGP,
                prefix=prefix,
                distance=DEFAULT_DISTANCE[Protocol.BGP],
                metric=best.attrs.med or 0,
                nexthops=frozenset({Nexthop(addr=nh)}),
            )
        )

    def _apply_static(self, new):
        from holo_tpu.utils.southbound import (
            Nexthop,
            Protocol,
            RouteKeyMsg,
            RouteMsg,
        )

        routes = new.get(
            "routing/control-plane-protocols/static-routes/route", {}
        ) or {}
        # Withdraw statics removed from config.
        new_prefixes = {r.get("prefix") for r in routes.values()}
        for prefix in getattr(self, "_static_prefixes", set()) - new_prefixes:
            self.rib.route_del(RouteKeyMsg(Protocol.STATIC, prefix))
        self._static_prefixes = {p for p in new_prefixes if p is not None}
        for _key, r in routes.items():
            prefix = r.get("prefix")
            if prefix is None:
                continue
            nhs = set()
            if r.get("next-hop") is not None:
                nhs.add(Nexthop(addr=r["next-hop"], ifname=r.get("interface")))
            elif r.get("interface"):
                nhs.add(Nexthop(ifname=r["interface"]))
            self.rib.route_add(
                RouteMsg(
                    protocol=Protocol.STATIC,
                    prefix=prefix,
                    distance=1,
                    metric=r.get("metric", 0),
                    nexthops=frozenset(nhs),
                )
            )

    def get_state(self, path=None):
        rib = {
            str(prefix): {
                "protocol": msg.protocol.value,
                "distance": msg.distance,
                "metric": msg.metric,
                "next-hops": sorted(
                    f"{nh.ifname or ''}:{nh.addr or ''}" for nh in msg.nexthops
                ),
            }
            for prefix, msg in self.rib.active_routes().items()
        }
        state = {"routing": {"rib": rib}}
        ospf = self.instances.get("ospfv2")
        if ospf is not None:
            now = self.loop.clock.now() if self.loop else 0.0

            def _lsdb_state(a):
                out = []
                for e in a.lsdb.all():
                    lsa = e.lsa
                    out.append(
                        {
                            "type": int(lsa.type),
                            "lsa-id": str(lsa.lsid),
                            "adv-router": str(lsa.adv_rtr),
                            "seq-num": lsa.seq_no & 0xFFFFFFFF,
                            "age": int(e.current_age(now)),
                            "length": lsa.length,
                        }
                    )
                return out

            state["routing"]["ospfv2"] = {
                "router-id": str(ospf.config.router_id),
                "spf-run-count": ospf.spf_run_count,
                "spf-log": list(ospf.spf_log),
                "is-abr": ospf.is_abr,
                "areas": {
                    str(aid): {
                        "area-type": (
                            "nssa" if a.nssa
                            else "stub" if a.stub
                            else "normal"
                        ),
                        "lsdb-count": len(a.lsdb.entries),
                        "database": _lsdb_state(a),
                        "interfaces": {
                            i.name: {
                                "state": i.state.name.lower(),
                                "type": i.config.if_type.name.lower(),
                                "cost": i.config.cost,
                                "hello-interval": (
                                    i.config.hello_interval
                                ),
                                "dead-interval": i.config.dead_interval,
                                "passive": i.config.passive,
                                "dr": str(i.dr),
                                "bdr": str(i.bdr),
                                "neighbor-count": len(i.neighbors),
                            }
                            for i in a.interfaces.values()
                        },
                    }
                    for aid, a in ospf.areas.items()
                },
                "neighbors": {
                    str(n.router_id): {
                        "state": n.state.name.lower(),
                        "iface": i.name,
                        "address": str(n.src),
                        "dr": str(n.dr),
                        "bdr": str(n.bdr),
                        "priority": n.priority,
                    }
                    for a in ospf.areas.values()
                    for i in a.interfaces.values()
                    for n in i.neighbors.values()
                },
                "local-rib": {
                    str(prefix): {
                        "metric": r.dist,
                        "route-type": getattr(r, "route_type", ""),
                        "next-hops": sorted(
                            f"{nh.ifname or ''}:{nh.addr or ''}"
                            for nh in r.nexthops
                        ),
                    }
                    for prefix, r in ospf.routes.items()
                },
                "sr-labels": {
                    str(prefix): label
                    for prefix, (label, _r) in getattr(
                        ospf, "sr_labels", {}
                    ).items()
                },
            }
            # YANG-modeled ietf-ospf tree (same renderer the conformance
            # harness diffs against the reference's recorded plane).
            try:
                from holo_tpu.protocols.ospf.nb_state import instance_state

                state["routing"]["ietf-ospf:ospf"] = instance_state(ospf)
            except Exception:  # noqa: BLE001 — ad-hoc state must survive
                log.exception("ietf-ospf state render failed")
        v3 = self.instances.get("ospfv3")
        if v3 is not None:
            # YANG-modeled ietf-ospf (v3) tree — the renderer the v3
            # conformance harness diffs 44/44 recorded routers against.
            try:
                from holo_tpu.protocols.ospf.nb_state_v3 import (
                    instance_state as v3_state,
                )

                state["routing"]["ietf-ospf:ospfv3"] = v3_state(v3)
            except Exception:  # noqa: BLE001 — ad-hoc state must survive
                log.exception("ietf-ospf v3 state render failed")
            # SPF run log ring (full/intra/inter/external types), like
            # the v2 and IS-IS blocks; list() snapshots vs the instance
            # thread's append/trim under threaded isolation.
            state["routing"]["ospfv3"] = {
                "spf-run-count": v3.spf_run_count,
                "spf-log": list(getattr(v3, "spf_log", [])),
            }
        isis = self.instances.get("isis")
        if isis is not None:
            # The YANG-modeled ietf-isis operational tree — the same
            # renderer the conformance harness diffs against the
            # reference's recorded state plane — served at the standard
            # module-qualified name alongside the ad-hoc summary below.
            # (ietf-ospf:ospf v2 is rendered in the ospf block above,
            # v3 in the ospfv3 block below.)
            try:
                from holo_tpu.protocols.isis.nb_state import (
                    instance_state as isis_state,
                )

                if hasattr(isis, "instances"):  # L1/L2 node
                    state["routing"]["ietf-isis:isis"] = isis_state(
                        list(isis.instances()),
                        node=isis._inst if hasattr(isis, "_inst") else isis,
                        ifnames=getattr(self, "_isis_ifnames", None),
                    )
                else:
                    state["routing"]["ietf-isis:isis"] = isis_state(
                        [isis],
                        ifnames=getattr(self, "_isis_ifnames", None),
                    )
            except Exception:  # noqa: BLE001 — ad-hoc state must survive
                log.exception("ietf-isis state render failed")
            isis_subs = (
                list(isis.instances())
                if hasattr(isis, "instances") and callable(isis.instances)
                else [isis]
            )
            state["routing"]["isis"] = {
                "spf-run-count": isis.spf_run_count,
                # SPF run log ring (reference state.rs spf_log events):
                # records the Full-vs-RouteOnly classification per run.
                "spf-log": [
                    {"level": sub.level} | dict(e)
                    for sub in isis_subs
                    # list() snapshot: the instance thread appends/trims
                    # the ring while this management-side render runs.
                    for e in list(getattr(sub, "spf_log", []))
                ],
                "lsdb-count": len(isis.lsdb),
                "database": [
                    {
                        "lsp-id": e.lsp.lsp_id.encode().hex(),
                        "seq-num": e.lsp.seqno,
                        "lifetime": e.remaining_lifetime(
                            self.loop.clock.now() if self.loop else 0.0
                        ),
                    }
                    for e in (
                        isis.lsdb.values()
                        if hasattr(isis.lsdb, "values")
                        else []
                    )
                ],
                "adjacencies": {
                    i.name: [
                        {"sysid": a.sysid.hex(), "state": a.state.value}
                        for a in i.up_adjacencies()
                    ]
                    for i in isis.interfaces.values()
                },
                "hostnames": {
                    k.hex() if hasattr(k, "hex") else str(k): v
                    for k, v in getattr(isis, "hostnames", {}).items()
                },
            }
        for proto in ("ripv2", "ripng"):
            rip = self.instances.get(proto)
            if rip is None:
                continue
            # dict() snapshots are GIL-atomic: under preemptive
            # isolation the instance thread mutates these containers
            # while this (management-side) render iterates.
            routes = dict(rip.routes)
            neighbors = dict(rip.neighbors)
            state["routing"][proto] = {
                "routes": {
                    str(p): {
                        "metric": r.metric,
                        "type": r.route_type,
                        "interface": r.ifname,
                        "next-hop": (
                            str(r.nexthop) if r.nexthop is not None else None
                        ),
                    }
                    for p, r in routes.items()
                },
                "neighbors": {
                    str(a): {"last-update": t}
                    for a, t in neighbors.items()
                },
            }
        igmp = self.instances.get("igmp")
        if igmp is not None:
            out_ifaces = {}
            for i in list(igmp.interfaces.values()):
                groups = dict(i.groups)
                out_ifaces[i.name] = {
                    "querier": i.querier,
                    "groups": {
                        str(g): {
                            "reporters": sorted(
                                str(r) for r in set(grp.reporters)
                            )
                        }
                        for g, grp in groups.items()
                    },
                }
            state["routing"]["igmp"] = {"interfaces": out_ifaces}
        ldp = self.instances.get("ldp")
        if ldp is not None:
            state["routing"]["ldp"] = {
                "lsr-id": str(ldp.lsr_id),
                "control-mode": ldp.control_mode,
                "neighbors": {
                    str(rid): n.state.value
                    for rid, n in ldp.neighbors.items()
                },
                "lib": {
                    str(fec): entry for fec, entry in ldp.lib().items()
                },
            }
        bgp = self.instances.get("bgp")
        if bgp is not None:
            state["routing"]["bgp"] = {
                "as": bgp.asn,
                "peers": {
                    str(a): {"state": p.state.value,
                             "prefixes-in": len(p.adj_rib_in)}
                    for a, p in bgp.peers.items()
                },
                "loc-rib-count": len(bgp.loc_rib),
            }
        return state

"""Static daemon config (TOML), parsed at boot.

Reference: holo-daemon/src/config.rs + holod.toml — user/group, db path,
logging, plugin addresses.  Runtime routing config flows through the
northbound transaction engine instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

try:
    import tomllib  # Python >= 3.11
except ImportError:  # pragma: no cover - interpreter-dependent
    # tomli is the stdlib module's upstream: a drop-in loads() for
    # pre-3.11 interpreters (a hand-rolled parser silently mis-handles
    # real TOML — escaped quotes, commas inside array strings).
    import tomli as tomllib


@dataclass
class LoggingConfig:
    level: str = "info"
    style: str = "compact"  # compact | full | json
    file: str | None = None
    # Per-subsystem level overrides (the reference's per-target tracing
    # directives, main.rs:59-146): {"ospf": "debug", "bgp.fsm": "trace"}.
    # Keys address holo_tpu logger names below the package root.
    subsystems: dict = field(default_factory=dict)


@dataclass
class GrpcConfig:
    enabled: bool = True
    address: str = "127.0.0.1:50051"
    # TLS (holo-daemon grpc.rs TLS option): both paths set = secure port.
    tls_cert: str | None = None
    tls_key: str | None = None


@dataclass
class GnmiConfig:
    enabled: bool = False
    address: str = "127.0.0.1:50052"
    tls_cert: str | None = None
    tls_key: str | None = None


@dataclass
class EventRecorderConfig:
    enabled: bool = False
    dir: str = "/tmp/holo_tpu-events"


@dataclass
class TelemetryConfig:
    # The registry itself is always on (metrics cost nanoseconds and the
    # gNMI state subtree serves them regardless); this section gates the
    # Prometheus scrape endpoint and the exit trace dump.
    enabled: bool = False
    address: str = "127.0.0.1:9464"  # Prometheus /metrics endpoint
    # Path for a Chrome trace-event JSON span dump written at daemon
    # stop (None = no dump; HOLO_TPU_TRACE_DUMP env overrides).
    trace_dump: str | None = None
    # Flight recorder (ISSUE 5): > 0 arms a bounded in-memory ring of
    # recent spans / journal markers / resilience events; breaker-open,
    # crash-loop degrade, and SIGTERM then dump a postmortem JSON
    # bundle to postmortem-dir (render: holo-tpu-tools postmortem).
    flight_buffer_entries: int = 0
    postmortem_dir: str | None = None
    # Per-dispatch device-time breakdown (marshal / device / readback
    # sub-spans + compile-time FLOP/bytes cost capture).  Off by
    # default: the enabled path adds a block_until_ready barrier per
    # dispatch (gated < 2% by bench.py profiling_overhead).
    profile_device_time: bool = False
    # Convergence observatory (ISSUE 6): > 0 arms the causal
    # event→FIB tracker with that many open-event/timeline slots —
    # holo_convergence_seconds{trigger,phase} histograms, causal ids on
    # ibus envelopes, per-event timelines into the flight ring.  Off by
    # default (gated < 2% by bench.py convergence_overhead).
    convergence_events: int = 0
    # Shared-delta gNMI fan-out (ISSUE 11): SAMPLE/ON_CHANGE streams
    # ride ONE per-tick state snapshot + change-set rendered once and
    # fanned out to every due subscriber (O(1) render cost in
    # subscriber count).  Off -> the pre-ISSUE-11 per-subscriber walk
    # path, byte-identical output (the same path any engine failure
    # degrades to).
    gnmi_shared_fanout: bool = True
    # Base tick (seconds) for ON_CHANGE delta delivery and the fan-out
    # coalescing cadence floor.
    fanout_tick: float = 1.0
    # ROADMAP carry-over: when set AND a real TPU is attached, capture
    # one jax.profiler.trace() around a seeded SPF dispatch into this
    # directory at boot.  Relay-probe-aware: without a TPU the daemon
    # records an explicit `relay: not-used` row — never a failure.
    device_trace_dir: str | None = None
    # Dispatch observatory (ISSUE 12): streaming quantile sketches per
    # (site, stage, engine, shape-bucket, kind) fed from the profiling
    # sub-span path, roofline attribution against the compile-time
    # cost model, and the warn-only regression sentinel.  Arming it
    # also arms profile-device-time (the observatory feeds off the
    # sub-span walls).  Gated < 2% by bench.py observatory_overhead.
    observatory: bool = False
    # Persisted sentinel baseline (the BENCH_baseline.json discipline:
    # seed unseen keys, flag >10% drift, ratchet improvements).  None
    # keeps the ledger in memory only.
    observatory_ledger: str | None = None
    # Roofline peak specs {flops=<per sec>, bytes=<per sec>, name=...};
    # None = the honest CPU defaults ("relay: not-used") until the TPU
    # relay returns with real specs.
    roofline_peaks: dict | None = None
    # SLO plane (ISSUE 20): error budgets + multi-window burn-rate
    # sentinels graded from the convergence end-cut / pipeline shed /
    # relay watch streams.  Objectives come from
    # [[telemetry.slo-objectives]] tables (name, kind, source,
    # quantile, threshold-ms, target); empty = the shipped default set
    # (trigger-fib latency, canary, relay availability, background
    # delivery).  Warn-only by contract; gated < 2% by bench.py
    # slo_overhead.
    slo: bool = False
    slo_objectives: tuple = ()
    slo_fast_window: float = 3600.0
    slo_slow_window: float = 86400.0
    slo_fast_burn: float = 14.4
    # Synthetic canary prober (ISSUE 20): a standing synthetic instance
    # on the daemon loop injecting heartbeat topology deltas through
    # the real actor→ibus→pipeline→RIB path as background-class
    # tickets.  Requires convergence-events > 0 — probes close at
    # fib_commit via the causal tracker.
    canary: bool = False
    canary_period: float = 5.0
    canary_deadline: float = 0.25


@dataclass
class ResilienceConfig:
    # Actor supervision ([resilience] in holod.toml): restart crashed
    # protocol actors with exponential backoff + deterministic jitter;
    # a crash loop (threshold crashes within window) parks the actor in
    # a permanent degraded state instead of flapping.
    supervision: bool = True
    restart_base_delay: float = 0.5
    restart_max_delay: float = 30.0
    crash_loop_threshold: int = 5
    crash_loop_window: float = 60.0
    # Dispatch circuit breaker defaults (TpuSpfBackend / FrrEngine):
    # consecutive failures before the circuit opens, seconds before a
    # half-open probe, optional per-dispatch deadline budget (seconds;
    # an overrun counts as a failure — once the circuit opens, SPF goes
    # to the scalar oracle up front instead of waiting on the device).
    breaker_failure_threshold: int = 3
    breaker_recovery_timeout: float = 30.0
    breaker_deadline: float | None = None


@dataclass
class ParallelConfig:
    # Multi-chip dispatch mesh ([parallel] in holod.toml, ISSUE 8): the
    # daemon installs one process-wide (batch, node) jax mesh at boot
    # and TpuSpfBackend / FrrEngine / the shared DeviceGraphCache
    # dispatch sharded over it (parallel/mesh.py layout contract).
    # Default: enabled, all devices on the batch axis (what-if batches
    # scale embarrassingly) — a 1-device host degenerates to the
    # single-device program at <2% overhead (bench sharding_overhead).
    enabled: bool = True
    # Axis sizes; None = derive (both None -> all devices on batch;
    # one set -> the other is devices/that).  batch*node must equal the
    # device count or boot logs a warning and stays single-device.
    batch: int | None = None
    node: int | None = None


@dataclass
class PipelineConfig:
    # Async dispatch pipeline + engine auto-tuner ([pipeline] in
    # holod.toml, ISSUE 9): when enabled, the daemon installs one
    # process-wide dispatch pipeline at boot and TpuSpfBackend /
    # FrrEngine instances built by the providers are wrapped so
    # protocol actors enqueue SPF/FRR work instead of blocking on the
    # device (holo_tpu/pipeline/dispatch.py).  Off by default: the
    # synchronous dispatch path stays byte-for-byte what PR 8 shipped.
    enabled: bool = False
    # Launched-but-unfinished entries (2 = double buffering) and the
    # bounded queue (a full queue backpressures the submitting actor).
    depth: int = 2
    queue: int = 32
    # Per-shape engine auto-tuner (holo_tpu/pipeline/tuner.py): can be
    # armed independently of the async pipeline — the synchronous
    # dispatch path consults it too.
    tuner: bool = False
    # Versioned on-disk tuner table (restarts don't re-learn); None
    # keeps the table in memory only.
    tuner_cache: str | None = None
    # Survivability plane (ISSUE 19).  Default relative deadline
    # (seconds) stamped onto advisory what-if tickets — expired batches
    # are shed at dequeue; None = advisory work never expires.
    advisory_deadline: float | None = None
    # Hung-dispatch watchdog: a supervised sentinel abandons a
    # launch/finish phase that overruns max(site-p99 × multiplier,
    # floor) — the ticket is served from the bit-identical scalar
    # fallback, the breaker escalates, and the worker respawns under
    # the Supervisor RestartPolicy.  Off by default (the stamps cost
    # nothing while disarmed, but a hang budget is policy).
    watchdog: bool = False
    watchdog_multiplier: float = 4.0
    watchdog_floor: float = 5.0


@dataclass
class RuntimeConfig:
    # "threaded" (default): each protocol instance on its own OS thread
    # — the reference's PRODUCTION posture (per-instance spawn_blocking,
    # holo-protocol/src/lib.rs:419-430).  Requires the real clock;
    # virtual-clock (test) daemons automatically fall back to
    # "cooperative" single-loop scheduling, the analog of the
    # reference's `testing` feature.
    isolation: str = "threaded"
    # True when [runtime] isolation was explicitly configured (vs the
    # default): an EXPLICIT threaded request that must downgrade (no
    # real clock) warns; the defaulted case downgrades silently.
    isolation_explicit: bool = False


@dataclass
class DaemonConfig:
    db_path: str | None = None
    # Production hardening (holo-daemon/src/main.rs:28-209 equivalents).
    lock_path: str | None = None  # flock single-instance (None = off)
    user: str | None = None  # drop privileges to this user after setup
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    grpc: GrpcConfig = field(default_factory=GrpcConfig)
    gnmi: GnmiConfig = field(default_factory=GnmiConfig)
    event_recorder: EventRecorderConfig = field(default_factory=EventRecorderConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)

    @classmethod
    def load(cls, path: str | Path | None) -> "DaemonConfig":
        cfg = cls()
        if path is None or not Path(path).exists():
            return cfg
        raw = tomllib.loads(Path(path).read_text())
        if "database" in raw:
            cfg.db_path = raw["database"].get("path")
        if "daemon" in raw:
            cfg.lock_path = raw["daemon"].get("lock-path")
            cfg.user = raw["daemon"].get("user")
        if "logging" in raw:
            for k in ("level", "style", "file"):
                if k in raw["logging"]:
                    setattr(cfg.logging, k, raw["logging"][k])
            subs = raw["logging"].get("subsystems")
            if isinstance(subs, dict):
                cfg.logging.subsystems = dict(subs)
        if "grpc" in raw:
            g = raw["grpc"]
            cfg.grpc.enabled = g.get("enabled", True)
            cfg.grpc.address = g.get("address", cfg.grpc.address)
            cfg.grpc.tls_cert = g.get("tls-cert")
            cfg.grpc.tls_key = g.get("tls-key")
        if "gnmi" in raw:
            g = raw["gnmi"]
            cfg.gnmi.enabled = g.get("enabled", False)
            cfg.gnmi.address = g.get("address", cfg.gnmi.address)
            cfg.gnmi.tls_cert = g.get("tls-cert")
            cfg.gnmi.tls_key = g.get("tls-key")
        if "event_recorder" in raw:
            e = raw["event_recorder"]
            cfg.event_recorder.enabled = e.get("enabled", False)
            cfg.event_recorder.dir = e.get("dir", cfg.event_recorder.dir)
        if "telemetry" in raw:
            t = raw["telemetry"]
            cfg.telemetry.enabled = t.get("enabled", False)
            cfg.telemetry.address = t.get("address", cfg.telemetry.address)
            cfg.telemetry.trace_dump = t.get("trace-dump")
            cfg.telemetry.flight_buffer_entries = int(
                t.get("flight-buffer-entries", 0)
            )
            cfg.telemetry.postmortem_dir = t.get("postmortem-dir")
            cfg.telemetry.convergence_events = int(
                t.get("convergence-events", 0)
            )
            cfg.telemetry.profile_device_time = t.get(
                "profile-device-time", False
            )
            cfg.telemetry.gnmi_shared_fanout = t.get(
                "gnmi-shared-fanout", True
            )
            cfg.telemetry.fanout_tick = float(t.get("fanout-tick", 1.0))
            cfg.telemetry.device_trace_dir = t.get("device-trace-dir")
            cfg.telemetry.observatory = t.get("observatory", False)
            cfg.telemetry.observatory_ledger = t.get("observatory-ledger")
            rp = t.get("roofline-peaks")
            if rp is not None:
                ok = isinstance(rp, dict) and all(
                    isinstance(rp.get(k), (int, float))
                    and not isinstance(rp.get(k), bool)
                    and rp.get(k) > 0
                    for k in ("flops", "bytes")
                )
                if not ok:
                    raise ValueError(
                        "[telemetry] roofline-peaks must be a table with "
                        f"positive 'flops' and 'bytes', got {rp!r}"
                    )
                cfg.telemetry.roofline_peaks = dict(rp)
            cfg.telemetry.slo = t.get("slo", False)
            objs = t.get("slo-objectives")
            if objs is not None:
                from holo_tpu.telemetry.slo import Objective

                if not isinstance(objs, list):
                    raise ValueError(
                        "[telemetry] slo-objectives must be an array of "
                        f"tables, got {objs!r}"
                    )
                try:
                    cfg.telemetry.slo_objectives = tuple(
                        Objective.from_config(o) for o in objs
                    )
                except (KeyError, TypeError, ValueError) as exc:
                    raise ValueError(
                        f"[telemetry] slo-objectives invalid: {exc!r}"
                    ) from exc
            cfg.telemetry.slo_fast_window = float(
                t.get("slo-fast-window", 3600.0)
            )
            cfg.telemetry.slo_slow_window = float(
                t.get("slo-slow-window", 86400.0)
            )
            cfg.telemetry.slo_fast_burn = float(t.get("slo-fast-burn", 14.4))
            if (
                cfg.telemetry.slo_fast_window <= 0
                or cfg.telemetry.slo_slow_window
                < cfg.telemetry.slo_fast_window
                or cfg.telemetry.slo_fast_burn <= 0
            ):
                raise ValueError(
                    "[telemetry] slo windows must satisfy 0 < "
                    "slo-fast-window <= slo-slow-window and "
                    "slo-fast-burn > 0"
                )
            cfg.telemetry.canary = t.get("canary", False)
            cfg.telemetry.canary_period = float(t.get("canary-period", 5.0))
            cfg.telemetry.canary_deadline = float(
                t.get("canary-deadline", 0.25)
            )
            if cfg.telemetry.canary_period <= 0:
                raise ValueError(
                    "[telemetry] canary-period must be positive, got "
                    f"{cfg.telemetry.canary_period}"
                )
            if (
                cfg.telemetry.canary
                and cfg.telemetry.convergence_events <= 0
            ):
                raise ValueError(
                    "[telemetry] canary requires convergence-events > 0 "
                    "(probes close at fib_commit through the causal "
                    "tracker)"
                )
        if "resilience" in raw:
            r = raw["resilience"]
            res = cfg.resilience
            res.supervision = r.get("supervision", True)
            for toml_key, attr in (
                ("restart-base-delay", "restart_base_delay"),
                ("restart-max-delay", "restart_max_delay"),
                ("crash-loop-threshold", "crash_loop_threshold"),
                ("crash-loop-window", "crash_loop_window"),
                ("breaker-failure-threshold", "breaker_failure_threshold"),
                ("breaker-recovery-timeout", "breaker_recovery_timeout"),
                ("breaker-deadline", "breaker_deadline"),
            ):
                if toml_key in r:
                    setattr(res, attr, r[toml_key])
        if "parallel" in raw:
            p = raw["parallel"]
            cfg.parallel.enabled = p.get("enabled", True)
            for key in ("batch", "node"):
                if key in p:
                    v = p[key]
                    # bool is an int subclass: `batch = true` must be
                    # rejected, not silently installed as batch=1.
                    if (
                        isinstance(v, bool)
                        or not isinstance(v, int)
                        or v < 1
                    ):
                        raise ValueError(
                            f"[parallel] {key} must be a positive "
                            f"integer, got {v!r}"
                        )
                    setattr(cfg.parallel, key, v)
        if "pipeline" in raw:
            p = raw["pipeline"]
            cfg.pipeline.enabled = p.get("enabled", False)
            cfg.pipeline.tuner = p.get("tuner", cfg.pipeline.enabled)
            cfg.pipeline.tuner_cache = p.get("tuner-cache")
            for key in ("depth", "queue"):
                if key in p:
                    v = p[key]
                    # bool is an int subclass: `depth = true` must be
                    # rejected, not silently installed as depth=1.
                    if (
                        isinstance(v, bool)
                        or not isinstance(v, int)
                        or v < 1
                    ):
                        raise ValueError(
                            f"[pipeline] {key} must be a positive "
                            f"integer, got {v!r}"
                        )
                    setattr(cfg.pipeline, key, v)
            cfg.pipeline.watchdog = p.get("watchdog", False)
            for key, toml_key in (
                ("advisory_deadline", "advisory-deadline"),
                ("watchdog_multiplier", "watchdog-multiplier"),
                ("watchdog_floor", "watchdog-floor"),
            ):
                if toml_key in p:
                    v = p[toml_key]
                    if isinstance(v, bool) or not isinstance(
                        v, (int, float)
                    ) or v <= 0:
                        raise ValueError(
                            f"[pipeline] {toml_key} must be a positive "
                            f"number, got {v!r}"
                        )
                    setattr(cfg.pipeline, key, float(v))
        if "runtime" in raw:
            iso = raw["runtime"].get("isolation")
            if iso is not None:
                if iso not in ("cooperative", "threaded"):
                    raise ValueError(
                        f"[runtime] isolation must be 'cooperative' or "
                        f"'threaded', got {iso!r}"
                    )
                cfg.runtime.isolation = iso
                cfg.runtime.isolation_explicit = True
        return cfg

"""gNMI service: Capabilities / Get / Set / Subscribe over the northbound.

Reference: holo-daemon gNMI plugin (client/gnmi.rs:49-268) — Get merges
config+state, Set runs one transaction per request, Subscribe streams
notifications.  gNMI paths map to the YANG-lite tree: path elems with keys
become the bracket path segments (``interface[name=eth0]`` ->
``interface[eth0]``).

STREAM serving scale (ISSUE 11): SAMPLE / ON_CHANGE subscriptions are
normally cheap epoch cursors inside the shared-delta
:class:`holo_tpu.telemetry.delta.FanoutEngine` — one state snapshot,
one change-set, and one render per coalesced tick epoch, fanned out to
every due subscriber through the bounded per-subscriber queues.  The
per-subscriber walk path (``_SubSampler``) remains as the
byte-identical fallback when the engine is disabled or its breaker
opens.
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time
from concurrent import futures
from pathlib import Path as FsPath

import grpc

sys.path.insert(0, str(FsPath(__file__).resolve().parent))
import gnmi_lite_pb2 as pb  # noqa: E402

import holo_tpu
from holo_tpu import telemetry
from holo_tpu.northbound.provider import CommitError
from holo_tpu.telemetry import delta as fanout_delta
from holo_tpu.telemetry import flight
from holo_tpu.yang.schema import SchemaError

# Subscribe-path hardening metrics: per-subscriber queues are bounded
# (SUBSCRIBE_QUEUE_DEPTH) so a stalled consumer costs dropped updates —
# counted here — instead of unbounded daemon memory.  Delivery-side
# tallies are stamped=False: they bump WHILE the delta engine serves a
# push, and re-arming the next tick's walk with our own bookkeeping
# would keep an idle system churning forever (registry.py rationale).
_SUB_DROPS = telemetry.counter(
    "holo_gnmi_subscribe_dropped_total",
    "gNMI Subscribe updates dropped on a full subscriber queue",
    stamped=False,
)
_SUBSCRIBERS = telemetry.gauge(
    "holo_gnmi_subscribers", "Active gNMI Subscribe streams"
)
_SAMPLE_UPDATES = telemetry.counter(
    "holo_gnmi_sample_updates_total",
    "Leaf updates pushed by SAMPLE / heartbeat subscription timers",
    ("mode",),
    stamped=False,
)

SUBSCRIBE_QUEUE_DEPTH = 256
# SAMPLE subscriptions leaving sample_interval at 0 get the
# target-chosen default (gNMI spec wording); a floor keeps a hostile
# 1ns interval from spinning the stream thread.
DEFAULT_SAMPLE_INTERVAL = 1.0
MIN_SAMPLE_INTERVAL = 0.01


class _SubSampler:
    """Per-subscription STREAM timer state (gNMI 0.8 semantics) — the
    per-subscriber WALK path.

    Since ISSUE 11 this is the fallback arm: streams normally attach to
    the shared-delta :class:`holo_tpu.telemetry.delta.FanoutEngine`
    (one snapshot + one render per tick epoch, shared across every due
    subscriber) and only run these samplers when the engine is disabled
    or its breaker opened.  The semantics here are the byte-identical
    contract the engine is graded against (``bench.py gnmi_fanout``).

    - ``SAMPLE``: push the subscribed subtree's scalar leaves every
      ``sample_interval`` (ns).  With ``suppress_redundant`` only leaves
      whose value changed since the last push go out; a non-zero
      ``heartbeat_interval`` forces a full resend at each beat so a
      quiet leaf still proves liveness.
    - ``ON_CHANGE`` / ``TARGET_DEFINED`` with ``heartbeat_interval``:
      the notification fanout carries the changes; this timer resends
      the current (unchanged) leaves at each beat.

    Samplers run on the stream's own generator thread and bypass the
    bounded fanout queue entirely — gRPC flow control is their
    backpressure, so the overflow-drop counter keeps meaning exactly
    "fanout updates lost to a stalled consumer".
    """

    def __init__(self, sub, now: float | None = None) -> None:
        if now is None:
            now = time.monotonic()
        self.path = path_to_str(sub.path)
        self.suppress = bool(sub.suppress_redundant)
        self.interval = None
        if sub.mode == pb.SAMPLE:
            self.interval = max(
                sub.sample_interval / 1e9 or DEFAULT_SAMPLE_INTERVAL,
                MIN_SAMPLE_INTERVAL,
            )
        self.heartbeat = (
            max(sub.heartbeat_interval / 1e9, MIN_SAMPLE_INTERVAL)
            if sub.heartbeat_interval
            else None
        )
        self.next_sample = now + self.interval if self.interval else None
        self.next_beat = now + self.heartbeat if self.heartbeat else None
        self.last: dict[str, object] = {}
        self.fired = (False, False)  # (beat, sample) of the last advance

    @property
    def active(self) -> bool:
        return self.next_sample is not None or self.next_beat is not None

    def next_due(self) -> float | None:
        due = [t for t in (self.next_sample, self.next_beat) if t is not None]
        return min(due) if due else None

    def advance_if_due(self, now: float) -> bool:
        """True when a beat or sample tick is due; advances the timers
        and remembers which fired (read by the renderer)."""
        beat = self.next_beat is not None and now >= self.next_beat
        sample = self.next_sample is not None and now >= self.next_sample
        if not (beat or sample):
            return False
        while self.next_beat is not None and self.next_beat <= now:
            self.next_beat += self.heartbeat
        while self.next_sample is not None and self.next_sample <= now:
            self.next_sample += self.interval
        self.fired = (beat, sample)
        return True


def path_to_str(path: pb.Path) -> str:
    segs = []
    for elem in path.elem:
        if elem.key:
            # single-key lists: the key value is the instance selector
            key = next(iter(elem.key.values()))
            segs.append(f"{elem.name}[{key}]")
        else:
            segs.append(elem.name)
    return "/".join(segs)


def str_to_path(s: str) -> pb.Path:
    from holo_tpu.yang.schema import parse_path

    p = pb.Path()
    for name, key in parse_path(s):
        e = p.elem.add()
        e.name = name
        if key is not None:
            e.key["name"] = key
    return p


class GnmiService:
    def __init__(
        self,
        daemon,
        shared_fanout: bool = True,
        fanout_tick: float = 1.0,
    ):
        self.daemon = daemon
        # Copy-on-write subscriber snapshot (ISSUE 11 lock-discipline
        # fix): an immutable tuple of (queue, ordinal) pairs rebuilt on
        # add/remove, so _fanout's lock hold is two reference reads —
        # never per-subscriber work — matching the Ibus._subs
        # snapshot-then-release discipline (HL203 surface).
        self._subscribers: tuple = ()
        self._sub_lock = threading.Lock()
        # Per-subscriber identity + drop-burst tracking (ISSUE 6
        # carry-over from PR 5): subscriber ordinal -> consecutive
        # drops in the current burst.  Burst edges land in the
        # flight-recorder ring so a postmortem bundle shows WHICH
        # subscriber was shedding and when — the aggregate counter
        # alone cannot answer that.
        self._sub_ids: dict[int, int] = {}  # id(queue) -> ordinal
        self._next_sub = 0
        self._bursts: dict[int, int] = {}  # ordinal -> burst depth
        # Injectable notification timestamp source: the byte-identity
        # bench arm pins it so the shared-render and walk paths stamp
        # identically.
        self._clock_ns = lambda: int(time.time() * 1e9)
        # Shared-delta fan-out engine (ISSUE 11): one state snapshot +
        # one render per tick epoch, shared across all due subscribers.
        self.fanout = None
        if shared_fanout:
            self.fanout = fanout_delta.FanoutEngine(
                fetch_state=self._fetch_state,
                deliver=self._deliver,
                burst_snapshot=self._burst_snapshot,
                on_push=self._count_push,
                tick=fanout_tick,
                clock_ns=lambda: self._clock_ns(),
            )
            fanout_delta.register_engine(self.fanout)

    def _fetch_state(self):
        """Scope-aware snapshot for the delta engine: fetch only the
        union of subscribed subtree roots (ONE lock acquisition, the
        legacy wake-loop discipline) — a narrow subscription must not
        cost a full provider-tree walk per tick."""
        roots = self.fanout.sample_roots() if self.fanout else None
        with self.daemon.lock:
            nb = self.daemon.northbound
            if roots is None:
                return nb.get_state(None)
            return [nb.get_state(r or None) for r in roots]

    @staticmethod
    def _count_push(mode: str, n_updates: int) -> None:
        _SAMPLE_UPDATES.labels(mode=mode).inc(n_updates)

    def _add_subscriber(self, q: queue.Queue) -> int:
        with self._sub_lock:
            self._next_sub += 1
            sid = self._next_sub
            self._sub_ids[id(q)] = sid
            self._subscribers = self._subscribers + ((q, sid),)
            _SUBSCRIBERS.set(len(self._subscribers))
        return sid

    def _remove_subscriber(self, q: queue.Queue) -> None:
        """Idempotent removal: the stream's finally block AND any future
        notify-side eviction may both call this — a double remove must
        not raise inside a gRPC generator teardown.  The gauge updates
        under the same lock so concurrent teardowns cannot publish a
        stale count."""
        with self._sub_lock:
            self._subscribers = tuple(
                (qq, s) for qq, s in self._subscribers if qq is not q
            )
            sid = self._sub_ids.pop(id(q), None)
            burst = self._bursts.pop(sid, 0) if sid is not None else 0
            _SUBSCRIBERS.set(len(self._subscribers))
        if burst:
            # The subscriber died mid-burst: close the story in the ring.
            flight.event(
                "gnmi-drop-burst", subscriber=sid, dropped=burst,
                ended="disconnect",
            )

    def _burst_snapshot(self) -> set:
        """Ordinals currently mid-burst (O(open bursts), usually 0)."""
        with self._sub_lock:
            return set(self._bursts)

    def _deliver(self, q, sid: int, notif, in_burst: bool) -> bool:
        """Bounded best-effort put with per-subscriber drop-burst
        accounting — shared by the on-change fanout and the delta
        engine's shared-render pushes.  Burst edges (first drop; first
        successful put after drops) land in the flight ring; the
        subscriber lock is only taken ON an edge, never on the healthy
        path."""
        try:
            q.put_nowait(notif)
        except queue.Full:
            _SUB_DROPS.inc()
            with self._sub_lock:
                if id(q) not in self._sub_ids:
                    # Removed concurrently: _remove_subscriber already
                    # closed (or owns) this burst story — re-creating
                    # the entry would leak it forever.
                    depth = 0
                else:
                    depth = self._bursts.get(sid, 0) + 1
                    self._bursts[sid] = depth
            if depth == 1:
                flight.event("gnmi-drop-burst-start", subscriber=sid)
            return False
        if in_burst:
            with self._sub_lock:
                burst = self._bursts.pop(sid, 0)
            if burst:
                flight.event(
                    "gnmi-drop-burst", subscriber=sid, dropped=burst,
                    ended="drained",
                )
        return True

    def _fanout(self, notif) -> None:
        """Best-effort delivery to every subscriber: bounded queues drop
        (and count) on overflow rather than block the publisher or grow
        memory for a stalled consumer.  The lock is held for two
        reference reads (copy-on-write snapshot + open-burst set);
        every put and burst edge happens after release."""
        with self._sub_lock:
            targets = self._subscribers
            bursts = set(self._bursts)
        for q, sid in targets:
            self._deliver(q, sid, notif, sid in bursts)

    def Capabilities(self, request, context):
        resp = pb.CapabilityResponse(
            supported_encodings=["JSON_IETF", "PROTO"],
            gNMI_version="0.8.0-lite",
        )
        for name in sorted(self.daemon.northbound.schema.roots.keys()):
            resp.supported_models.add(
                name=name, organization="holo_tpu", version=holo_tpu.__version__
            )
        return resp

    def Get(self, request, context):
        with self.daemon.lock:
            nb = self.daemon.northbound
            notif = pb.Notification(timestamp=int(time.time() * 1e9))
            paths = list(request.path) or [pb.Path()]
            for path in paths:
                try:
                    self._get_one(nb, request, notif, path)
                except SchemaError as e:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.GetResponse(notification=[notif])

    def _get_one(self, nb, request, notif, path):
        pstr = path_to_str(path)
        payload = {}
        if request.type in (pb.GetRequest.ALL, pb.GetRequest.CONFIG):
            val = (
                json.loads(nb.running.to_json())
                if not pstr
                else nb.running.get(pstr)
            )
            if val is not None:
                payload["config"] = val
        if request.type in (
            pb.GetRequest.ALL,
            pb.GetRequest.STATE,
            pb.GetRequest.OPERATIONAL,
        ):
            state = nb.get_state(pstr or None)
            if state:
                payload["state"] = state
        if request.encoding == pb.PROTO:
            # Proto-encoded updates: one Update per scalar leaf with a
            # native TypedValue (reference gnmi.rs gen_update_proto).
            # Leaves are rooted at the requested path (no config/state
            # wrapper segments) so returned paths round-trip into Set;
            # when both planes are requested, state wins on overlap.
            leaves: dict[str, object] = {}
            for section in ("config", "state"):
                if section in payload:
                    for leaf_path, value in _walk_leaves(
                        pstr, payload[section]
                    ):
                        leaves[leaf_path] = value
            for leaf_path, value in leaves.items():
                notif.update.add(
                    path=str_to_path(leaf_path),
                    val=_typed_value(value),
                )
            return
        notif.update.add(
            path=path,
            val=pb.TypedValue(json_ietf_val=json.dumps(payload, default=str)),
        )

    def Set(self, request, context):
        nb = self.daemon.northbound
        results = []
        try:
            with self.daemon.lock:
                cand = nb.running.copy()
                for path in request.delete:
                    cand.delete(path_to_str(path))
                    results.append(
                        pb.UpdateResult(path=path, op=pb.UpdateResult.DELETE)
                    )
                n_replace = len(request.replace)
                for i, upd in enumerate(
                    list(request.replace) + list(request.update)
                ):
                    is_replace = i < n_replace
                    pstr = path_to_str(upd.path)
                    if is_replace:
                        # gNMI Replace semantics: the subtree is replaced,
                        # not merged — leaves absent from the payload go.
                        cand.delete(pstr)
                    v = upd.val
                    which = v.WhichOneof("value")
                    if which == "json_ietf_val":
                        sub = json.loads(v.json_ietf_val)
                        _apply_json(cand, pstr, sub)
                    elif which is not None:
                        cand.set(pstr, getattr(v, which))
                    else:
                        cand.set(pstr)
                    op = (
                        pb.UpdateResult.REPLACE
                        if is_replace
                        else pb.UpdateResult.UPDATE
                    )
                    results.append(pb.UpdateResult(path=upd.path, op=op))
                txn = self.daemon.commit(cand, comment="gnmi-set")
        except (SchemaError, CommitError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.SetResponse(
            response=results, timestamp=int(time.time() * 1e9)
        )

    def Subscribe(self, request_iterator, context):
        q: queue.Queue = queue.Queue(maxsize=SUBSCRIBE_QUEUE_DEPTH)
        sid = self._add_subscriber(q)
        handle = None
        try:
            first = next(iter(request_iterator), None)
            # Initial sync: current state snapshot then sync_response.
            with self.daemon.lock:
                state = self.daemon.northbound.get_state(None)
            notif = pb.Notification(timestamp=self._clock_ns())
            notif.update.add(
                path=pb.Path(),
                val=pb.TypedValue(json_ietf_val=json.dumps(state, default=str)),
            )
            yield pb.SubscribeResponse(update=notif)
            yield pb.SubscribeResponse(sync_response=True)
            if (
                first is not None
                and first.subscribe.mode == pb.SubscriptionList.ONCE
            ):
                return
            # STREAM: the bounded fanout queue carries on-change
            # notifications, and — shared-delta path (ISSUE 11) — the
            # fan-out engine's shared rendered pushes: this stream is
            # then a cheap epoch cursor inside the engine's interval
            # buckets and the loop below is a pure queue drain.
            if (
                self.fanout is not None
                and first is not None
                and first.HasField("subscribe")
            ):
                handle = self.fanout.attach(
                    q, sid, first.subscribe.subscription
                )
            # Fallback contract: engine disabled or breaker open —
            # per-subscription samplers walk the subtree on this
            # stream's own timers (the pre-ISSUE-11 path, byte-
            # identical output).
            samplers = (
                self._make_samplers(first) if handle is None else []
            )
            while context.is_active():
                if handle is not None:
                    if not self.fanout.healthy():
                        # Engine breaker opened mid-stream: degrade to
                        # the walk path for the rest of this stream.
                        self.fanout.detach(handle)
                        handle = None
                        samplers = self._make_samplers(first)
                        continue
                    try:
                        notif = q.get(timeout=0.25)
                        yield pb.SubscribeResponse(update=notif)
                    except queue.Empty:
                        pass
                    continue
                wait = 1.0
                now = time.monotonic()
                for s in samplers:
                    due = s.next_due()
                    if due is not None:
                        wait = min(wait, due - now)
                try:
                    notif = q.get(timeout=max(wait, 0.005))
                    yield pb.SubscribeResponse(update=notif)
                except queue.Empty:
                    pass
                now = time.monotonic()
                due = [s for s in samplers if s.advance_if_due(now)]
                if due:
                    # One state fetch per distinct path per wake, under
                    # ONE lock acquisition: N samplers coming due
                    # together must not serialize N full provider-tree
                    # walks against the commit path.
                    states = {}
                    with self.daemon.lock:
                        for p in {s.path for s in due}:
                            states[p] = self.daemon.northbound.get_state(
                                p or None
                            )
                    for s in due:
                        out = self._sample_notif(s, states[s.path])
                        if out is not None:
                            yield pb.SubscribeResponse(update=out)
        finally:
            if handle is not None:
                self.fanout.detach(handle)
            self._remove_subscriber(q)

    @staticmethod
    def _make_samplers(first) -> list[_SubSampler]:
        if first is None or not first.HasField("subscribe"):
            return []
        return [
            s
            for s in map(_SubSampler, first.subscribe.subscription)
            if s.active
        ]

    def _sample_notif(self, s: _SubSampler, state):
        """Render one due sampler's updates from an already-fetched
        state tree (None when every leaf was suppressed as redundant)."""
        beat, sample = s.fired
        leaves = {
            p: v
            for p, v in _walk_leaves("", state)
            if not s.path
            or p == s.path
            or p.startswith((s.path + "/", s.path + "["))
        }
        # A heartbeat resends everything; a suppress-redundant sample
        # pushes only leaves whose value moved since the last push.
        out = {
            p: v
            for p, v in leaves.items()
            if beat or not (sample and s.suppress and s.last.get(p) == v)
        }
        s.last = leaves
        if not out:
            return None
        notif = pb.Notification(timestamp=self._clock_ns())
        for p, v in sorted(out.items()):
            notif.update.add(path=str_to_path(p), val=_typed_value(v))
        # A beat forcing the resend wins the label even when a sample
        # tick is due in the same wake — it is what put the unchanged
        # leaves back on the wire.
        _SAMPLE_UPDATES.labels(mode="heartbeat" if beat else "sample").inc(
            len(out)
        )
        return notif

    def _notify_yang(self, payload: dict) -> None:
        # Protocol YANG notifications ride the same update stream, one
        # update per notification keyed by its qualified name.  The
        # delta engine's stamp short-circuit is voided: protocol state
        # moved outside the metrics registry.
        if self.fanout is not None:
            self.fanout.invalidate()
        for kind, body in payload.items():
            notif = pb.Notification(timestamp=self._clock_ns())
            notif.update.add(
                path=str_to_path(kind),
                val=pb.TypedValue(
                    json_ietf_val=json.dumps(body, default=str)
                ),
            )
            self._fanout(notif)

    def _notify_commit(self, txn) -> None:
        if self.fanout is not None:
            self.fanout.invalidate()
        notif = pb.Notification(timestamp=self._clock_ns())
        notif.update.add(
            path=str_to_path("transactions"),
            val=pb.TypedValue(
                json_ietf_val=json.dumps(
                    {"transaction-id": txn.id, "comment": txn.comment}
                )
            ),
        )
        self._fanout(notif)


def _typed_value(value) -> pb.TypedValue:
    """Scalar -> native gNMI TypedValue (gnmi.rs:332-388 proto arm)."""
    if isinstance(value, bool):
        return pb.TypedValue(bool_val=value)
    if isinstance(value, int):
        if value < 0:
            return pb.TypedValue(int_val=value)
        return pb.TypedValue(uint_val=value)
    if isinstance(value, float):
        return pb.TypedValue(double_val=value)
    return pb.TypedValue(string_val=str(value))


def _walk_leaves(base: str, tree):
    """Yield (path, scalar) for every leaf under a JSON state tree.

    List entries use the value of their first key-ish member ("name",
    else the first scalar) as the gNMI path key segment.
    """
    if not isinstance(tree, (dict, list)):
        yield base, tree
        return
    if isinstance(tree, dict):
        for k, v in tree.items():
            sub = f"{base}/{k}" if base else str(k)
            yield from _walk_leaves(sub, v)
        return
    if all(not isinstance(e, dict) for e in tree):
        # Leaf-list: one update carrying the whole array (our lite
        # proto has no ScalarArray; JSON keeps the path unique).
        yield base, json.dumps(tree, default=str)
        return
    for i, entry in enumerate(tree):
        if isinstance(entry, dict):
            key = entry.get("name")
            if key is None:
                key = next(
                    (
                        v
                        for v in entry.values()
                        if not isinstance(v, (dict, list))
                    ),
                    None,
                )
            sub = f"{base}[{key}]" if key is not None else f"{base}[{i}]"
            yield from _walk_leaves(sub, entry)
        else:
            yield f"{base}[{i}]", entry


def _apply_json(tree, base: str, sub) -> None:
    """Merge a JSON subtree at base path (leaves set individually)."""
    if not isinstance(sub, dict):
        tree.set(base, sub)
        return
    for k, v in sub.items():
        p = f"{base}/{k}" if base else k
        if isinstance(v, dict):
            # list entries look like {"key": {...}} under a list node; we
            # detect by trying as a container first and falling back.
            try:
                node = tree.schema.resolve(p)
            except SchemaError:
                node = None
            from holo_tpu.yang.schema import List as SchemaList

            if isinstance(node, SchemaList):
                for key, entry in v.items():
                    _apply_json(tree, f"{p}[{key}]", entry)
            else:
                _apply_json(tree, p, v)
        elif isinstance(v, list):
            tree.set(p, v)
        else:
            tree.set(p, v)


def serve_gnmi(
    daemon,
    address: str,
    tls_cert=None,
    tls_key=None,
    shared_fanout: bool | None = None,
    fanout_tick: float | None = None,
) -> grpc.Server:
    tcfg = getattr(getattr(daemon, "config", None), "telemetry", None)
    if shared_fanout is None:
        shared_fanout = getattr(tcfg, "gnmi_shared_fanout", True)
    if fanout_tick is None:
        fanout_tick = getattr(tcfg, "fanout_tick", 1.0)
    service = GnmiService(
        daemon, shared_fanout=shared_fanout, fanout_tick=fanout_tick
    )
    if service.fanout is not None:
        # The coalescing ticker parks while no stream has a bucket, so
        # an idle service costs one blocked daemon thread.
        service.fanout.start()
    daemon.add_commit_listener(service._notify_commit)
    daemon.add_notification_listener(service._notify_yang)
    svc_desc = pb.DESCRIPTOR.services_by_name["gNMI"]
    handlers = {}
    for m in svc_desc.methods:
        req = getattr(pb, m.input_type.name)
        resp = getattr(pb, m.output_type.name)
        fn = getattr(service, m.name)
        if m.name == "Subscribe":
            handlers[m.name] = grpc.stream_stream_rpc_method_handler(
                fn, request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString)
        else:
            handlers[m.name] = grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("gnmi.gNMI", handlers),)
    )
    from holo_tpu.daemon.grpc_server import _bind

    _bind(server, address, tls_cert, tls_key)
    server.start()
    daemon._gnmi_service = service
    if service.fanout is not None:
        # The pre-existing caller contract is `server.stop(grace)`:
        # fold the fan-out ticker shutdown into it so every stop path
        # (tests, Daemon.stop, operators) joins the thread instead of
        # leaking a parked engine per serve_gnmi call.
        grpc_stop = server.stop

        def _stop(grace=None):
            service.fanout.stop()
            return grpc_stop(grace)

        server.stop = _stop
    return server


class GnmiClient:
    """Minimal test client."""

    def __init__(self, address: str):
        self.channel = grpc.insecure_channel(address)
        svc = pb.DESCRIPTOR.services_by_name["gNMI"]
        for m in svc.methods:
            req = getattr(pb, m.input_type.name)
            resp = getattr(pb, m.output_type.name)
            path = f"/gnmi.gNMI/{m.name}"
            if m.name == "Subscribe":
                call = self.channel.stream_stream(
                    path, request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString)
            else:
                call = self.channel.unary_unary(
                    path, request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString)
            setattr(self, m.name, call)

"""Daemon production hardening: single-instance flock, privilege drop
with retained network capabilities, and signal handling.

Reference: holo-daemon/src/main.rs — flock (28-57), privdrop + Linux
capabilities (159-187), signal listener (189-209).
"""

from __future__ import annotations

import ctypes
import errno
import fcntl
import logging
import os
import signal

log = logging.getLogger("holo_tpu.hardening")

# Linux capability bits we must keep after dropping root (raw protocol
# sockets, netlink FIB programming, port 179/514 binds).
CAP_NET_BIND_SERVICE = 10
CAP_NET_ADMIN = 12
CAP_NET_RAW = 13
_KEEP_CAPS = (CAP_NET_BIND_SERVICE, CAP_NET_ADMIN, CAP_NET_RAW)

PR_SET_KEEPCAPS = 8
_LINUX_CAPABILITY_VERSION_3 = 0x20080522
SYS_CAPSET = 126  # x86_64


def acquire_instance_lock(path: str):
    """flock an instance lock file; returns the held fd or raises
    RuntimeError when another daemon owns it (main.rs:28-57)."""
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError as e:
        os.close(fd)
        if e.errno in (errno.EAGAIN, errno.EACCES):
            raise RuntimeError(
                f"another instance holds {path!r} — refusing to start"
            ) from e
        raise
    os.truncate(fd, 0)
    os.write(fd, str(os.getpid()).encode())
    return fd


def _capset(caps: tuple[int, ...]) -> None:
    """capset(2) via syscall: permitted+effective = the given bits."""
    libc = ctypes.CDLL(None, use_errno=True)

    class Header(ctypes.Structure):
        _fields_ = [("version", ctypes.c_uint32), ("pid", ctypes.c_int)]

    class Data(ctypes.Structure):
        _fields_ = [
            ("effective", ctypes.c_uint32),
            ("permitted", ctypes.c_uint32),
            ("inheritable", ctypes.c_uint32),
        ]

    lo = hi = 0
    for cap in caps:
        if cap < 32:
            lo |= 1 << cap
        else:
            hi |= 1 << (cap - 32)
    hdr = Header(_LINUX_CAPABILITY_VERSION_3, 0)
    data = (Data * 2)(Data(lo, lo, 0), Data(hi, hi, 0))
    if libc.syscall(SYS_CAPSET, ctypes.byref(hdr), ctypes.byref(data)) != 0:
        raise OSError(ctypes.get_errno(), "capset failed")


def drop_privileges(user: str) -> None:
    """setuid/setgid to ``user`` keeping the network capabilities
    (main.rs:159-187).  No-op when not running as root."""
    if os.geteuid() != 0:
        return
    import pwd

    ent = pwd.getpwnam(user)
    libc = ctypes.CDLL(None, use_errno=True)
    # Keep permitted capabilities across the uid change...
    if libc.prctl(PR_SET_KEEPCAPS, 1, 0, 0, 0) != 0:
        raise OSError(ctypes.get_errno(), "prctl(PR_SET_KEEPCAPS) failed")
    os.setgroups([])
    os.setgid(ent.pw_gid)
    os.setuid(ent.pw_uid)
    # ...then re-enable the effective set (cleared by setuid).
    _capset(_KEEP_CAPS)
    log.info(
        "privileges dropped to %s (kept NET_ADMIN/NET_RAW/NET_BIND)", user
    )


def install_signal_handlers(
    shutdown_cb, dump_cb=None, flush_cb=None, postmortem_cb=None
) -> None:
    """SIGINT/SIGTERM -> orderly shutdown; SIGHUP ignored (config is
    transactional via the northbound, not file reload); SIGUSR1 ->
    runtime-introspection dump to the log when ``dump_cb`` is given.

    ``flush_cb`` runs FIRST in the handler: it fsyncs crash-forensics
    state (the event-recorder journal) before the orderly shutdown even
    starts, so the post-mortem trace survives a teardown that hangs or
    a process killed mid-drain — the orderly path in ``Daemon.stop``
    flushes again after the tx queues drain.  ``postmortem_cb`` runs
    right after it (flight-recorder bundle capture: the journal is
    synced first so the bundle's journal-tail markers reference entries
    that are already durable on disk)."""

    def _handler(signum, _frame):
        log.info("signal %s: shutting down", signal.Signals(signum).name)
        if flush_cb is not None:
            try:
                flush_cb()
            except Exception:  # the shutdown must proceed regardless
                log.exception("shutdown flush failed")
        if postmortem_cb is not None:
            try:
                postmortem_cb()
            except Exception:  # forensics must not block the shutdown
                log.exception("shutdown postmortem failed")
        shutdown_cb()

    signal.signal(signal.SIGINT, _handler)
    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGHUP, signal.SIG_IGN)
    if dump_cb is not None:
        def _dump(_signum, _frame):
            try:
                log.info("runtime introspection: %s", dump_cb())
            except Exception:  # never let a diagnostics hook kill us
                log.exception("runtime dump failed")

        signal.signal(signal.SIGUSR1, _dump)

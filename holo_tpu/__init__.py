"""holo_tpu — a TPU-native routing-protocol framework.

A from-scratch rebuild of the capabilities of `holo-routing/holo` (IP routing
protocol suite: OSPFv2/v3, IS-IS, BGP, LDP, RIP, BFD, VRRP, IGMP with
YANG-modeled transactional management), re-architected TPU-first:

- The link-state SPF hot path (reference: `holo-ospf/src/spf.rs`,
  `holo-isis/src/spf.rs`) runs behind a pluggable ``SpfBackend``. The TPU
  backend marshals the LSDB into padded ELL adjacency tensors and executes
  batched min-plus SSSP + ECMP next-hop extraction under JAX/XLA
  (:mod:`holo_tpu.ops`), with what-if link-failure batches vmapped and
  node-axis sharding over a `jax.sharding.Mesh` (:mod:`holo_tpu.parallel`).
- The scalar CPU SPF (reference Dijkstra semantics) remains the default and
  the bit-identical parity oracle (:mod:`holo_tpu.spf.scalar`).
- Protocol machinery (actors, timers, ibus, packet codecs, FSMs) lives in
  :mod:`holo_tpu.protocols` / :mod:`holo_tpu.utils`, with a C++ native
  runtime core under ``native/``.
- Management: YANG-modeled transactional config (:mod:`holo_tpu.yang`,
  :mod:`holo_tpu.northbound`) served over gRPC by :mod:`holo_tpu.daemon`.
"""

__version__ = "0.1.0"

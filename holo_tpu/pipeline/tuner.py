"""Per-shape engine auto-tuner (ISSUE 9 tentpole, part b).

The bench sweep history shows the winning gather-path fixpoint engine
flips with topology size — ``seq`` 1481 vs ``fused`` 464 vs ``hybrid``
892 runs/s on small jaxcpu graphs while ``gather``-family engines at
big V behave differently again on TPU (BENCH r02-r04) — yet the engine
has been a static config knob (``TpuSpfBackend(one_engine=...)``).
This module turns it into a measured decision per **shape bucket**:

    bucket = (pow2(V), pow2(E), pow2(batch), mesh identity)

For each (kind, bucket) the tuner runs a deterministic explore/exploit
schedule over the parity-identical engine set (every engine computes
the bit-exact same SPF, so flipping engines can never change routing
state — only latency):

- **explore** — until every candidate engine has ``explore_rounds``
  measured dispatches, pick engines round-robin, ordered by the
  compile-time ``cost_analysis()`` prior when one was captured
  (cheapest estimated bytes first — the profile-guided search-space
  cut of Bounded Dijkstra, arXiv:1903.00436, applied to engine
  selection);
- **exploit** — pick the engine with the lowest measured median wall;
  every ``reprobe_every`` dispatches one non-winner is re-measured
  (round-robin) so a drifting platform can flip the winner back.

Decisions, promotions (winner changes), and the exploration phase are
all counted in the ``holo_pipeline_tuner_*`` metric family.

The same per-bucket table also carries the DeltaPath depth knob
(ROADMAP item 1 follow-up): the backend feeds measured ``delta``-stage
vs full-rebuild walls per bucket, and
:meth:`EngineTuner.max_delta_depth` derives the chain-depth cap from
their ratio — a bucket whose in-place delta is 40x cheaper than a
re-marshal can afford a much longer chain than one where the delta
barely wins (`holo_tpu.ops.spf_engine.DeviceGraphCache` consults this
through :func:`active_tuner`).

Persistence: the whole table round-trips through a **versioned** JSON
file (``[pipeline] tuner-cache`` in holod.toml) written atomically
(tmp + rename), so a restarted daemon starts in the exploit phase with
the learned winners instead of re-learning them ("restarts don't
re-learn"); a version bump discards stale tables wholesale.

Everything here is import-light (telemetry + stdlib) and O(1) per
decision: the hot path pays two dict hits and a deque median over at
most ``SAMPLE_WINDOW`` floats.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from pathlib import Path

from holo_tpu import telemetry

log = logging.getLogger("holo_tpu.pipeline.tuner")

#: persisted-table format version: bump to invalidate old tables
#: (v2: shape buckets grew the multipath parent-set width element;
#: v3: the tropical min-plus engine joined the candidate sets, ISSUE 13)
TABLE_VERSION = 3

#: k=1 fixpoint engines (all bit-identical; see ops/spf_engine +
#: ops/tropical — the tropical entry is the blocked min-plus program)
ENGINES = ("seq", "fused", "packed", "hybrid", "tropical")

#: k>1 multipath formulations: the packed row-gather kernel ("mp") and
#: its tropical DAG-tile-contraction variant.  A/B'd per shape bucket
#: for kind=one only — the widened tropical program scatters per-run
#: DAG tiles, which a big what-if batch would multiply by B.
MP_ENGINES = ("mp", "mp_tropical")

#: measured samples retained per (kind, bucket, engine) — medians over
#: a short window track platform drift without unbounded memory
SAMPLE_WINDOW = 9

#: DeltaPath depth-cap derivation bounds (satellite: auto-tuned
#: max_delta_depth).  depth = clamp(round(full/delta) * DEPTH_SCALE).
DEPTH_SCALE = 32
DEPTH_MIN = 32
DEPTH_MAX = 4096
#: samples of each arm required before the cap leaves the default
DEPTH_MIN_SAMPLES = 3

_DECISIONS = telemetry.counter(
    "holo_pipeline_tuner_decisions_total",
    "Engine-tuner picks by schedule phase",
    ("kind", "engine", "phase"),
)
_PROMOTIONS = telemetry.counter(
    "holo_pipeline_tuner_promotions_total",
    "Shape buckets whose measured winner changed",
    ("kind",),
)
_BUCKETS = telemetry.gauge(
    "holo_pipeline_tuner_buckets",
    "Shape buckets the tuner currently tracks",
)


def _pow2(n: int) -> int:
    """Smallest power of two >= n (bucket quantization; >= 1)."""
    out = 1
    n = max(int(n), 1)
    while out < n:
        out *= 2
    return out


def shape_bucket(
    n_vertices: int, n_edges: int, batch: int = 1, mesh=None, k: int = 1
) -> tuple:
    """The tuner's shape key: pow2-quantized (V, E, batch) + the mesh
    identity (the same shapes under a different sharding are a
    different XLA program — see ``TpuSpfBackend._track_compile``) + the
    multipath parent-set width ``k`` (ISSUE 10: the widened kernel is a
    different program with different walls — k=8 samples must never
    outvote the k=1 engine medians, and the DeltaPath depth ratio of a
    multipath chain is its own measurement)."""
    return (_pow2(n_vertices), _pow2(n_edges), _pow2(batch), mesh, int(k))


def bgp_shape_bucket(n_prefixes: int, n_peers: int) -> tuple:
    """Observatory/tuner bucket for the device BGP table (ISSUE 16):
    pow2-quantized (prefixes, peers), tagged with a leading ``"bgp"``
    discriminant so a BGP fold wall can never land in — or outvote —
    an SPF bucket (SPF keys are 5-tuples of ints/mesh; this is a
    3-tuple led by a string, disjoint by construction)."""
    return ("bgp", _pow2(max(1, n_prefixes)), _pow2(max(1, n_peers)))


def _median(vals) -> float | None:
    """Lower median: with an even sample count, prefer the smaller
    middle value — stray one-off spikes (GC, scheduler) must not
    outvote a warm measurement in a 2-sample window."""
    if not vals:
        return None
    s = sorted(vals)
    return float(s[(len(s) - 1) // 2])


class _BucketState:
    """Per-(kind, bucket) tuner state (mutated under the tuner lock)."""

    __slots__ = ("dispatches", "samples", "cost", "winner", "explored")

    def __init__(self):
        self.dispatches = 0
        # engine -> deque of measured wall seconds (most recent last)
        self.samples: dict[str, deque] = {}
        # engine -> {"flops": f, "bytes": b} compile-time prior
        self.cost: dict[str, dict] = {}
        self.winner: str | None = None
        self.explored = 0  # decisions spent in the explore phase


class EngineTuner:
    """Measured per-shape engine selection + DeltaPath depth tuning.

    Thread-shared (instance threads dispatch concurrently under
    ``[runtime] isolation=threaded``; the pipeline worker observes from
    its own thread): all state mutates under one lock, decisions are
    O(1), and nothing here ever touches a device value.
    """

    def __init__(
        self,
        path: str | Path | None = None,
        engines: tuple[str, ...] = ENGINES,
        mp_engines: tuple[str, ...] = MP_ENGINES,
        explore_rounds: int = 2,
        reprobe_every: int = 64,
        default_engine: str = "seq",
        default_delta_depth: int = 256,
    ):
        self.engines = tuple(engines)
        self.mp_engines = tuple(mp_engines)
        self.explore_rounds = int(explore_rounds)
        self.reprobe_every = int(reprobe_every)
        self.default_engine = default_engine
        self.default_delta_depth = int(default_delta_depth)
        self.path = Path(path) if path is not None else None
        self._lock = threading.Lock()
        self._table: dict[tuple, _BucketState] = {}
        # (bucket) -> {"delta": deque, "full": deque} stage walls
        self._depth: dict[tuple, dict[str, deque]] = {}
        self._promotions = 0
        self._loaded = False
        if self.path is not None:
            self.load()

    # -- keys ----------------------------------------------------------

    @staticmethod
    def _key(kind: str, bucket: tuple) -> tuple:
        return (str(kind), *bucket)

    def _state(self, key: tuple) -> _BucketState:
        st = self._table.get(key)
        if st is None:
            st = self._table[key] = _BucketState()
            _BUCKETS.set(len(self._table))
        return st

    # -- engine selection ----------------------------------------------

    def _candidates(self, kind: str, bucket: tuple) -> tuple[str, ...]:
        """The engine set this (kind, bucket) chooses among: the k=1
        gather+tropical family, or — for k>1 single-SPF dispatches —
        the multipath pair (``mp`` vs ``mp_tropical``).  k>1 what-if
        batches stay on ``mp`` (see MP_ENGINES)."""
        k = bucket[4] if len(bucket) > 4 and isinstance(bucket[4], int) else 1
        if k > 1:
            return self.mp_engines if kind == "one" else ("mp",)
        return self.engines

    def pick(self, kind: str, bucket: tuple) -> str:
        """The engine this dispatch should run.  Deterministic: the
        schedule depends only on the bucket's dispatch counter and the
        recorded samples, never on an RNG — two daemons replaying the
        same dispatch sequence make identical choices."""
        key = self._key(kind, bucket)
        cands = self._candidates(kind, bucket)
        with self._lock:
            st = self._state(key)
            st.dispatches += 1
            # Explore until every candidate has explore_rounds samples.
            needy = [
                e
                for e in self._explore_order(st, cands)
                if len(st.samples.get(e, ())) < self.explore_rounds
            ]
            if needy:
                engine = needy[st.explored % len(needy)]
                st.explored += 1
                phase = "explore"
            else:
                winner = self._winner_locked(st, cands)
                if (
                    self.reprobe_every
                    and st.dispatches % self.reprobe_every == 0
                    and len(cands) > 1
                ):
                    # Deterministic round-robin over the non-winners.
                    others = [e for e in cands if e != winner]
                    engine = others[
                        (st.dispatches // self.reprobe_every) % len(others)
                    ]
                    phase = "reprobe"
                else:
                    engine = winner
                    phase = "exploit"
        _DECISIONS.labels(kind=kind, engine=engine, phase=phase).inc()
        return engine

    def _explore_order(
        self, st: _BucketState, cands: tuple[str, ...] | None = None
    ) -> tuple[str, ...]:
        """Candidate order for exploration: engines with a compile-time
        cost prior first, cheapest estimated bytes-accessed leading —
        the likely winner gets measured earliest, so even a truncated
        explore phase tends to have sampled it."""
        if cands is None:
            cands = self.engines
        if not st.cost:
            return cands
        return tuple(
            sorted(
                cands,
                key=lambda e: st.cost.get(e, {}).get("bytes", float("inf")),
            )
        )

    def _winner_locked(
        self, st: _BucketState, cands: tuple[str, ...] | None = None
    ) -> str:
        if cands is None:
            # Measured engines outside the k=1 set (the mp family) must
            # still be able to win their own buckets.
            cands = tuple(
                dict.fromkeys(self.engines + tuple(sorted(st.samples)))
            )
        best, best_med = None, None
        for e in cands:
            med = _median(st.samples.get(e))
            if med is not None and (best_med is None or med < best_med):
                best, best_med = e, med
        if best is not None:
            return best
        return self.default_engine if self.default_engine in cands else cands[0]

    def current_winner(self, kind: str, bucket: tuple) -> str | None:
        """Read-only peek at a bucket's measured winner (no schedule
        advance, no metrics): the backend routes engine-fixed kernels —
        the DeltaPath incremental dispatch — through the tropical tiles
        when this bucket's full-dispatch winner is tropical.  None when
        the bucket has never been measured."""
        key = self._key(kind, bucket)
        with self._lock:
            st = self._table.get(key)
            if st is None or not st.samples:
                return None
            return self._winner_locked(st, self._candidates(kind, bucket))

    def observe(
        self, kind: str, bucket: tuple, engine: str, seconds: float
    ) -> None:
        """Record one measured dispatch wall for (bucket, engine); a
        winner change is a promotion (counted, and the table is
        persisted so the restart picks it cold)."""
        key = self._key(kind, bucket)
        promoted = False
        with self._lock:
            st = self._state(key)
            dq = st.samples.get(engine)
            if dq is None:
                dq = st.samples[engine] = deque(maxlen=SAMPLE_WINDOW)
            dq.append(float(seconds))
            new_winner = self._winner_locked(st)
            if new_winner != st.winner:
                promoted = st.winner is not None
                st.winner = new_winner
                if promoted:
                    self._promotions += 1
        if promoted:
            _PROMOTIONS.labels(kind=kind).inc()
            self.save()

    def cost_prior(
        self, kind: str, bucket: tuple, engine: str, entry: dict | None
    ) -> None:
        """Attach a compile-time ``cost_analysis()`` estimate (the
        backends call this right after a fresh jit compile — see
        ``profiling.record_cost``).  None is a no-op (platforms without
        cost analysis)."""
        if not entry:
            return
        key = self._key(kind, bucket)
        with self._lock:
            self._state(key).cost[engine] = {
                "flops": float(entry.get("flops", 0.0)),
                "bytes": float(entry.get("bytes", 0.0)),
            }

    # -- partitioned-SPF arbitration (ISSUE 15) ------------------------

    def observe_partitioned(self, bucket: tuple, seconds: float) -> None:
        """One measured partitioned-SPF dispatch wall for this shape
        bucket.  Partitioned rows live under their own kind (they are a
        different PROGRAM STRUCTURE, not another parity-identical
        engine), so the kind=one explore/exploit schedule can never
        pick 'partitioned' for a monolithic dispatch — the threshold
        contract in ``TpuSpfBackend`` stays the routing authority and
        the table carries the measured evidence."""
        self.observe("partitioned", bucket, "partitioned", seconds)

    def partitioned_advantage(self, bucket: tuple) -> float | None:
        """median(monolithic winner wall) / median(partitioned wall)
        for one shape bucket — >1 means the partitioned path is
        measured faster at this shape.  None until both arms have
        samples (bench/operators read this; the backend's
        ``partition_threshold`` is deliberately not auto-flipped by
        it)."""
        with self._lock:
            st_p = self._table.get(self._key("partitioned", bucket))
            p_med = (
                _median(st_p.samples.get("partitioned", ()))
                if st_p is not None
                else None
            )
            st_o = self._table.get(self._key("one", bucket))
            o_med = None
            if st_o is not None:
                w = self._winner_locked(st_o)
                if w is not None:
                    o_med = _median(st_o.samples.get(w, ()))
        if not p_med or not o_med:
            return None
        return o_med / p_med

    # -- DeltaPath depth tuning ----------------------------------------

    def observe_delta(self, bucket: tuple, seconds: float) -> None:
        """One measured incremental (delta-path) dispatch wall."""
        self._observe_depth(bucket, "delta", seconds)

    def observe_full(self, bucket: tuple, seconds: float) -> None:
        """One measured full-rebuild (re-marshal) dispatch wall."""
        self._observe_depth(bucket, "full", seconds)

    def _observe_depth(self, bucket: tuple, arm: str, seconds: float) -> None:
        with self._lock:
            d = self._depth.setdefault(
                tuple(bucket),
                {
                    "delta": deque(maxlen=SAMPLE_WINDOW),
                    "full": deque(maxlen=SAMPLE_WINDOW),
                },
            )
            d[arm].append(float(seconds))

    def max_delta_depth(self, bucket: tuple, default: int | None = None) -> int:
        """The chain-depth cap for this shape bucket: proportional to
        how much cheaper the measured delta path is than a full
        rebuild (clamped to [DEPTH_MIN, DEPTH_MAX]).  Until both arms
        have DEPTH_MIN_SAMPLES per-bucket measurements, fall back to
        the process-wide ``holo_profile_stage_seconds`` medians of the
        ``delta`` vs ``marshal`` stages (the PR 7 profiling data that
        motivated this satellite) when device profiling is armed, and
        to ``default`` otherwise."""
        if default is None:
            default = self.default_delta_depth
        with self._lock:
            d = self._depth.get(tuple(bucket))
            delta_med = _median(d["delta"]) if d else None
            full_med = _median(d["full"]) if d else None
            enough = d is not None and (
                len(d["delta"]) >= DEPTH_MIN_SAMPLES
                and len(d["full"]) >= DEPTH_MIN_SAMPLES
            )
        if not enough or not delta_med or full_med is None:
            # Global fallback: the aggregate delta vs marshal stage
            # medians — shape-blind, but directionally right for a
            # bucket the backend has not measured yet.
            from holo_tpu.telemetry import profiling

            delta_med = profiling.stage_median("spf.one", "delta")
            full_med = profiling.stage_median("spf.one", "marshal")
            if not delta_med or full_med is None:
                return int(default)
        ratio = max(full_med / delta_med, 1.0)
        return max(DEPTH_MIN, min(DEPTH_MAX, int(round(ratio)) * DEPTH_SCALE))

    # -- persistence ----------------------------------------------------

    @staticmethod
    def _bucket_str(key: tuple) -> str:
        return json.dumps(list(key))

    @staticmethod
    def _bucket_from_str(s: str) -> tuple:
        out = []
        for v in json.loads(s):
            out.append(tuple(v) if isinstance(v, list) else v)
        return tuple(out)

    def snapshot(self) -> dict:
        """The persisted document (also the debugging surface)."""
        with self._lock:
            buckets = {}
            for key, st in self._table.items():
                buckets[self._bucket_str(key)] = {
                    "dispatches": st.dispatches,
                    "winner": st.winner,
                    "samples": {
                        e: [round(v, 9) for v in dq]
                        for e, dq in st.samples.items()
                    },
                    "cost": dict(st.cost),
                }
            depth = {
                self._bucket_str(b): {
                    arm: [round(v, 9) for v in dq] for arm, dq in d.items()
                }
                for b, d in self._depth.items()
            }
        return {
            "version": TABLE_VERSION,
            "engines": list(self.engines),
            "buckets": buckets,
            "depth": depth,
        }

    def save(self, path: str | Path | None = None) -> bool:
        """Atomic write (tmp + rename) of the versioned table; False
        when no path is configured.  Never raises: a full disk must not
        take an SPF dispatch down."""
        p = Path(path) if path is not None else self.path
        if p is None:
            return False
        try:
            doc = json.dumps(self.snapshot(), sort_keys=True, indent=1)
            tmp = p.with_suffix(p.suffix + ".tmp")
            tmp.write_text(doc + "\n")
            os.replace(tmp, p)
            return True
        except OSError as e:
            log.warning("tuner table save to %s failed: %s", p, e)
            return False

    def load(self, path: str | Path | None = None) -> bool:
        """Load a persisted table; version mismatch or a corrupt file
        discards it (the tuner just re-learns).  Returns True when
        state was restored."""
        p = Path(path) if path is not None else self.path
        if p is None or not p.exists():
            return False
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError) as e:
            log.warning("tuner table load from %s failed: %s", p, e)
            return False
        if doc.get("version") != TABLE_VERSION:
            log.info(
                "tuner table %s has version %r (want %d); discarding",
                p, doc.get("version"), TABLE_VERSION,
            )
            return False
        with self._lock:
            self._table.clear()
            for bstr, entry in doc.get("buckets", {}).items():
                try:
                    key = self._bucket_from_str(bstr)
                except ValueError:
                    continue
                st = _BucketState()
                st.dispatches = int(entry.get("dispatches", 0))
                st.winner = entry.get("winner")
                for e, vals in entry.get("samples", {}).items():
                    st.samples[e] = deque(
                        [float(v) for v in vals], maxlen=SAMPLE_WINDOW
                    )
                st.cost = {
                    e: dict(c) for e, c in entry.get("cost", {}).items()
                }
                self._table[key] = st
            self._depth.clear()
            for bstr, d in doc.get("depth", {}).items():
                try:
                    b = self._bucket_from_str(bstr)
                except ValueError:
                    continue
                self._depth[b] = {
                    arm: deque(
                        [float(v) for v in vals], maxlen=SAMPLE_WINDOW
                    )
                    for arm, vals in d.items()
                }
            _BUCKETS.set(len(self._table))
            self._loaded = True
        return True

    # -- introspection --------------------------------------------------

    def ledger(self) -> list[dict]:
        """Per-bucket win/loss rows for ``holo-tpu-tools explain`` —
        the tuner's decisions made explainable: the winner, every
        measured engine's median wall + compile-time cost prior, and
        the resource axis the winner actually leads on (``packed beat
        fused on bytes, not flops``)."""
        rows = []
        with self._lock:
            items = sorted(
                self._table.items(), key=lambda kv: self._bucket_str(kv[0])
            )
            for key, st in items:
                kind, bucket = key[0], key[1:]
                winner = st.winner or self.default_engine
                measured = [
                    e for e in st.samples
                    if _median(st.samples[e]) is not None
                ]
                if len(measured) == 1 and winner not in measured:
                    # A bucket with one formulation outside the tuned
                    # set (the k>1 "mp" kernel): there was no choice —
                    # report the engine that actually ran, not the
                    # never-dispatched default.
                    winner = measured[0]
                engines = {}
                for e in sorted(st.samples):
                    med = _median(st.samples[e])
                    engines[e] = {
                        "median_ms": (
                            round(med * 1e3, 4) if med is not None else None
                        ),
                        "samples": len(st.samples[e]),
                        "cost": st.cost.get(e),
                    }
                rows.append(
                    {
                        "kind": kind,
                        "bucket": list(bucket),
                        "winner": winner,
                        "dispatches": st.dispatches,
                        "engines": engines,
                        "basis": self._win_basis(st, winner),
                    }
                )
        return rows

    def _win_basis(self, st: _BucketState, winner: str) -> str:
        """Why the winner wins, on the cost model's axes: strictly the
        lowest estimated bytes among measured rivals -> "bytes",
        strictly the lowest flops -> "flops", otherwise the measured
        wall alone decided (call under the tuner lock)."""
        if _median(st.samples.get(winner)) is None:
            return "default (no samples)"
        rivals = [
            e
            for e in st.samples
            if e != winner and _median(st.samples[e]) is not None
        ]
        if not rivals:
            return "only measured engine"
        wc = st.cost.get(winner)
        priced = [e for e in rivals if st.cost.get(e)]
        basis = "wall"
        if wc and priced:
            inf = float("inf")
            if all(
                wc.get("bytes", inf) < st.cost[e].get("bytes", inf)
                for e in priced
            ):
                basis = "bytes"
            elif all(
                wc.get("flops", inf) < st.cost[e].get("flops", inf)
                for e in priced
            ):
                basis = "flops"
        # Name only the rivals the claim was actually checked against:
        # a cost-axis basis compared the PRICED rivals; an unpriced
        # rival (no cost_analysis on this platform) was only ever
        # beaten on the measured wall.
        named = sorted(priced if basis in ("bytes", "flops") else rivals)
        return f"{winner} beat {', '.join(named)} on {basis}"

    def stats(self) -> dict:
        """holo-telemetry state-leaf / bench view."""
        with self._lock:
            winners = {}
            for key, st in self._table.items():
                winners[self._bucket_str(key)] = {
                    "winner": st.winner or self.default_engine,
                    "dispatches": st.dispatches,
                    "measured-engines": sorted(st.samples),
                }
            return {
                "buckets": len(self._table),
                "promotions": self._promotions,
                "loaded-from-disk": self._loaded,
                "path": str(self.path) if self.path else None,
                "winners": winners,
                "depth-buckets": len(self._depth),
            }


# -- process-wide singleton --------------------------------------------

_TUNER: EngineTuner | None = None
_TUNER_LOCK = threading.Lock()


def configure_engine_tuner(
    path: str | Path | None = None, **kw
) -> EngineTuner:
    """Install the process-wide tuner (daemon boot from ``[pipeline]``;
    bench/tests call directly).  Replaces any previous tuner."""
    global _TUNER
    with _TUNER_LOCK:
        _TUNER = EngineTuner(path=path, **kw)
        return _TUNER


def active_tuner() -> EngineTuner | None:
    """The installed tuner, or None (backends then keep their pinned
    engine and DeviceGraphCache its static depth cap)."""
    return _TUNER


def reset_engine_tuner() -> None:
    """Uninstall (tests / bench teardown)."""
    global _TUNER
    with _TUNER_LOCK:
        _TUNER = None
